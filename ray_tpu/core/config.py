"""Runtime configuration knobs, env-overridable.

Analog of the reference's RAY_CONFIG X-macro system
(src/ray/common/ray_config_def.h — 203 ``RAY_CONFIG(type, name, default)``
entries, overridable via ``RAY_<name>`` env vars). We keep the same contract:
every knob has a typed compile-time default and can be overridden with
``RAY_TPU_<NAME>`` in the environment or via ``init(_system_config=...)``.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields

_ENV_PREFIX = "RAY_TPU_"


@dataclass
class Config:
    # --- object store ---
    # Size of the shared-memory object store arena per node, bytes.
    object_store_memory: int = 512 * 1024 * 1024
    # Objects smaller than this are inlined into task replies / in-process
    # store instead of the shm store (reference: max_direct_call_object_size,
    # ray_config_def.h).
    max_inline_object_size: int = 100 * 1024
    # Chunk size for node-to-node object transfer (reference: 5 MiB,
    # ray_config_def.h:348).
    object_transfer_chunk_bytes: int = 5 * 1024 * 1024
    # Spill threshold: fraction of arena used before spilling kicks in.
    object_spilling_threshold: float = 0.8
    spill_dir: str = ""
    # Multi-source striped pulls (reference: PullManager fans chunk
    # requests across every node in the ObjectDirectory's holder set,
    # pull_manager.cc): max concurrent source nodes per pull, and the
    # minimum object size worth splitting across sources at all.
    pull_max_sources: int = 4
    pull_min_stripe_bytes: int = 1 * 1024 * 1024
    # Cooperative pipelined broadcast (one-to-many distribution of one
    # object, e.g. model weights pulled by every gang member at step
    # start). The head's pull planner treats every node it has ALREADY
    # told to pull an object as an *in-progress location*: until that
    # pull completes or aborts, later pullers may be pointed at it, and
    # the in-progress node's TransferServer relays each chunk as soon as
    # it lands locally (partial-object serving) — forming an implicit
    # pipelined tree so a cold N-node broadcast moves ~S bytes off the
    # original holder instead of N x S. ``broadcast_fanout`` bounds how
    # many concurrent downstream pulls any single source (sealed holder
    # OR in-progress relay) is assigned before the planner moves on to
    # the next source; saturating every source falls back to the
    # least-loaded sealed holder (and fires the rate-limited
    # ``broadcast_fanout_saturated`` cluster event). The load accounting
    # is PER OBJECT (one _ObjLoc.serving map each): concurrent
    # broadcasts of K different objects held by one node may still put
    # K x fanout streams on that host's uplink — the bound shapes each
    # object's distribution tree; cap what actually leaves the host
    # with ``host_egress_limit_bps`` (the shared per-host token bucket).
    # 0 disables cooperative planning entirely: every puller stripes
    # across the sealed holder set (the pre-r9 behavior). In-progress
    # locations are
    # removed from the directory the moment their pull completes
    # (promoted to a sealed holder) or fails/aborts (never handed out
    # again; downstream pulls that already hold the address fail over to
    # the sealed root set via OBJ_PULL_FAIL / connection loss).
    broadcast_fanout: int = 2
    # How long a TransferServer waits for a directory-promised object to
    # appear locally (the relay's own pull may not have created the
    # buffer yet) and, once relaying, for each next chunk to arrive,
    # before failing the remaining range back to the requester
    # (OBJ_PULL_FAIL -> requester re-pulls from the root holder set).
    # Only pulls the head marked as relay-served wait at all; a plain
    # pull from a stale directory entry still fails fast.
    broadcast_serve_wait_s: float = 10.0

    # --- wire fast path ---
    # Small-frame coalescing (protocol.Connection): when several threads
    # send on one connection concurrently, queued frames are flushed
    # together in ONE vectored write (socket.sendmsg) by whichever sender
    # holds the write lock — one syscall instead of one per frame. These
    # knobs bound a single coalesced flush; an uncontended send is always
    # flushed immediately (batch of 1), so the idle-connection latency
    # path is unchanged. The queue reaching wire_coalesce_max_frames also
    # fires the wire-backpressure cluster event / counter.
    wire_coalesce_max_bytes: int = 1 * 1024 * 1024
    wire_coalesce_max_frames: int = 64
    # Batched task completions (protocol.TASK_DONE_BATCH, the return-side
    # mirror of PUSH_TASK_BATCH): a worker that finishes several tasks
    # while more are already queued acks them in one frame — at most this
    # many completions per frame. Replies flush whenever the worker's
    # task queue empties (a lone task's reply is never deferred), and a
    # reply-flusher thread ships anything still buffered ~1 ms after the
    # executor moves on, so a long-running next task can never withhold
    # an earlier task's finished result. 0 disables batching (one
    # TASK_REPLY frame per task, the pre-r8 behavior).
    task_done_batch_max: int = 128

    # Host-wide egress token bucket for the peer-to-peer object plane
    # (TransferServer): ALL concurrent serves on one host — every
    # object, every downstream puller, root and relay streams alike —
    # drain one shared bucket of this many bytes/second. This is the
    # host-level companion to ``broadcast_fanout``: the fanout bound
    # shapes each OBJECT's distribution tree, but K concurrent
    # broadcasts of K different objects held by one node could still
    # stack K x fanout streams on that host's uplink — the bucket caps
    # what actually leaves the NIC regardless of how many trees the
    # planner built through it. 0 (default) disables pacing; benches
    # and tests also set ``TransferServer.egress_limit_bps`` directly
    # for uplink emulation.
    host_egress_limit_bps: int = 0

    # --- device path (r13) ---
    # Typed zero-copy serialization for ``jax.Array`` (and large
    # non-contiguous ``np.ndarray``): the reducer emits dtype/shape
    # metadata in frame 0 and the array payload as an out-of-band
    # buffer VIEW of the source array's host buffer — no
    # device_get-then-pickle intermediate copy — so ``put_serialized``
    # writes device bytes straight into the mapped arena. On the read
    # side ``deserialize`` rebuilds through dlpack /
    # ``jax.numpy.asarray`` from the arena-backed view: a consumer pays
    # at most one host->device import (zero copies where XLA supports
    # aliased dlpack import; exactly one transfer on TPU), and plain
    # ndarray consumers alias arena memory outright — the store's
    # borrow-pin ledger keeps the arena slice alive while any such view
    # is (see ``ShmObjectStore.get_frames(pin_borrows=True)``). False
    # restores the pre-r13 in-band pickle path (the A/B control for
    # bench_device_path.py).
    serialization_device_zero_copy: bool = True

    # --- speculative arg prefetch (r13) ---
    # At lease grant — and again at driver dispatch via PREFETCH_HINT,
    # since leases are long-lived and serve many tasks — the head checks
    # the granted node's directory entry against the task's deduped
    # by-ref arg ids and fires a prefetch-flagged PULL_OBJECT at that
    # node's agent for every missing arg, so the pull overlaps the lease
    # reply, driver dispatch and worker wakeup instead of starting cold
    # inside the worker's _decode_args (the reference PullManager's
    # prefetch role; FETCHING_ARGS phase overlap). The worker's get()
    # joins the in-flight pull via the puller's _pending leadership.
    # False disables both the grant-time and hint-driven prefetch (the
    # A/B control).
    arg_prefetch_enabled: bool = True
    # Per-destination-node bound on concurrent prefetch pulls. The caps
    # PACE rather than drop (the reference PullManager's bounded pull
    # activation): requests over the caps queue per node (bounded FIFO,
    # 256) and activate as PREFETCH_RESULTs free slots, re-checking
    # holders/caps/lease liveness at activation. <= 0 disables
    # prefetching entirely.
    arg_prefetch_max_inflight: int = 4
    # Per-destination-node bound on the total bytes of in-flight
    # prefetch pulls; over-cap requests wait in the same pending queue
    # (a misconfigured cap shows up as doctor_warnings()'s prefetch
    # waste-ratio warning or as joins instead of warm hits, not as
    # arena pressure).
    arg_prefetch_max_bytes: int = 256 * 1024 * 1024
    # Dispatch-time PREFETCH_HINT dedupe window (r14): the driver
    # submitter remembers, per leased worker, which by-ref arg ids it
    # hinted in the last this-many seconds and strips them from later
    # hints — an actor-task hot loop that passes the same refs on every
    # call (the serve-handle weights/payload pattern) sends ONE hint per
    # (lease, arg) per window instead of one per pushed batch. The head
    # keeps its own dedupe, so this only saves wire frames + head-loop
    # wakeups; <= 0 restores the hint-per-batch behavior.
    prefetch_hint_dedupe_ttl_s: float = 5.0
    # PREFETCH_HINT coalescing (r15): dedupe catches REPEATED arg ids,
    # but a pipeline hot loop ships FRESH by-ref args every call (each
    # microbatch's activation is a new object) — one hint frame per
    # pushed batch per stage actor. With coalescing on, hints buffer
    # per (lease | actor) destination and the submitter's next wakeup
    # flushes everything pending as ONE PREFETCH_HINT_BATCH frame
    # (destinations ride together; ids hinted to the same destination
    # across consecutive batches merge — counted in the context's
    # ``prefetch_hints_coalesced``). Latency cost is one submitter
    # wakeup (~sub-ms), irrelevant to speculation that exists to
    # overlap a multi-ms transfer. False restores the r14
    # frame-per-batch behavior (the A/B control).
    prefetch_hint_coalesce: bool = True

    # --- MPMD pipeline parallelism (r15) ---
    # Stage-actor placement for ``train.pipeline.Pipeline``:
    # "auto" pins stage k to node (k mod n_alive_nodes) with soft node
    # affinity — one stage per node when the cluster has enough nodes,
    # so activations flow store-to-store over the object plane and each
    # stage's compute overlaps its neighbours' transfers; "spread" uses
    # a SPREAD placement group (the reference's pipeline-stage
    # placement-group idiom) without explicit node pinning; "none"
    # leaves placement to the default hybrid policy (stages may
    # co-locate — correct, but transfer/compute overlap vanishes).
    pipeline_stage_placement: str = "auto"
    # Upper bound on microbatches in flight per ``run_batch``. 0 = the
    # schedule's natural bound: 1F1B is self-limiting at O(stages)
    # in-flight (stage k holds at most S-k live activation contexts)
    # while GPipe keeps all M alive until its backward wave. A positive
    # value runs the batch in WAVES of at most this many microbatches —
    # grads keep accumulating across waves so results are unchanged,
    # each wave boundary drains the pipeline (one extra bubble per
    # wave) — useful to cap arena footprint when running GPipe with
    # many microbatches.
    pipeline_max_inflight_microbatches: int = 0

    # --- data-parallel pipelines (r18) ---
    # Default replica count per pipeline stage for ``train.Pipeline``
    # (the constructor's ``replicas_per_stage=`` overrides). With R > 1
    # the pipeline becomes the MPMD paper's full PP x DP composition:
    # each stage runs as R gang-placed actors, microbatch mb flows
    # through replica (mb mod R) of EVERY stage (activations never
    # cross replicas — R independent 1-wide pipelines share the stage
    # program), and at batch end each stage's replica group runs a
    # bucketed gradient all-reduce over ``ray_tpu.collective`` (ring
    # transport by default), submitted into each replica's task lane
    # right after its last backward so late stages' grad sync overlaps
    # early stages' remaining backward waves. Grads after run_batch
    # equal the 1-replica run (sum of per-replica sums, mean over the
    # global microbatch count).
    pipeline_replicas_per_stage: int = 1
    # Bucket size for the batch-end data-parallel grad all-reduce:
    # consecutive same-dtype gradient leaves are concatenated into
    # ~this-many-byte flat buckets and each bucket is all-reduced
    # separately, so the first buckets' ring hops overlap the later
    # buckets' (and other stages') work and no single collective
    # payload grows with model size. Mirrors the reference DDP /
    # NCCL-group bucketing. Must be identical across replicas (it is,
    # via shared config — the bucket split must line up for the ring's
    # chunk exchange to rendezvous).
    pipeline_grad_bucket_bytes: int = 16 * 1024 * 1024

    # --- elastic pipeline repair (r16) ---
    # Object-plane stage checkpoints: every this-many completed WAVES
    # (see ``pipeline_max_inflight_microbatches`` — with bound 0 the
    # whole batch is one wave) each ``_StageWorker`` snapshots its
    # params + accumulated grads + microbatch count as a by-ref tree
    # (plasma-resident on the stage's node via the r13 typed zero-copy
    # reducer for ``jax.Array`` leaves); the driver holds one ref per
    # stage tagged by wave, replicates sole-copy snapshots off the
    # producing node (so a node kill cannot take the only copy with
    # it), and frees the previous wave's refs eagerly — O(stages)
    # checkpoint footprint, the same discipline as activations. On a
    # stage's node death the gang restores to the latest checkpointed
    # wave and replays ONLY the waves since it (redo bounded by this
    # knob x the wave size). <= 0 disables checkpointing AND the repair
    # path entirely (a stage death fails the batch, the pre-r16
    # behavior).
    pipeline_checkpoint_every_waves: int = 1
    # How many stage-death repairs one ``train.Pipeline`` absorbs
    # before giving up and re-raising the failure to the caller — a
    # node that dies repeatedly (or a cluster with no capacity left to
    # re-place the stage) must not retry forever. Counted per repair
    # event (one event may re-place several co-located stages).
    pipeline_max_repairs: int = 3
    # Graceful node drain (``ray_tpu.drain_node`` / ``DRAIN_NODE``):
    # how long the head waits for a draining node's in-flight leases to
    # complete (and its sole-copy objects to replicate off) before
    # force-escalating to the deliberate r12 ``SHUTDOWN_NODE`` anyway
    # (``drain_forced`` cluster event; surviving work then rides the
    # normal lineage/retry machinery). While draining, the node takes
    # no new leases, placements, or prefetch/warm pulls; holders keep
    # serving so copies replicate off via the existing pull machinery.
    # ``doctor_warnings()`` flags a node stuck draining past this
    # deadline (the escalation itself wedged).
    drain_deadline_s: float = 30.0

    # --- host-plane collectives (r18) ---
    # Default transport family for ray_tpu.collective operations when a
    # call passes transport="auto". "ring" (default): the data plane is
    # the OBJECT PLANE — each rank put()s its chunks into its local
    # arena and peers pull them store-to-store (striped pulls, r13
    # typed zero-copy reducer; neither the coordinator actor nor the
    # driver ever carries payload bytes, counter-asserted in
    # BENCH_dp_r18.json), with sized payloads riding a chunked ring
    # reduce-scatter+allgather (~2·(R-1)/R·nbytes per rank, per-hop
    # pulls warmed ahead of the fold) and small payloads a
    # halving-doubling tree (log2 R hops) on power-of-two worlds.
    # "rendezvous": the pre-r18 auto behavior, preserved verbatim —
    # payloads below 256 KiB funnel inline through the per-group
    # rendezvous actor (whose incremental fold keeps its peak memory at
    # O(1) payloads), larger ones ride the two-round slice exchange.
    # Per-call transport= overrides (transport="rendezvous" forces the
    # pure coordinator funnel — the only data plane with ZERO
    # object-plane involvement, the true escape hatch and the bench's
    # A/B baseline); every rank of one operation must resolve the SAME
    # family (identical config + shapes do).
    collective_transport: str = "ring"
    # Chunk size for the ring/tree collectives' object-plane payloads:
    # each published slice is split into ~this-many-byte arena objects,
    # so a consumer's pull of chunk k+1 (started ahead by the
    # OBJECT_WARM prefetch) overlaps its fold of chunk k, and per-pull
    # latency stays bounded on paced links. Smaller chunks = more
    # overlap but more per-object control traffic (put + directory +
    # pull round-trips); the default suits multi-MiB gradient buckets.
    # Must agree across the ranks of one operation (same config, or the
    # same explicit chunk_bytes= argument).
    collective_ring_chunk_bytes: int = 4 * 1024 * 1024

    # --- serve at scale (r14) ---
    # How long a ``slow_node`` detector flag stays routable-around: the
    # head marks the node slow in its `nodes` state rows for this long
    # after each detection (refreshed while the skew persists), and
    # serve routers deprioritize replicas on flagged nodes (power-of-
    # two-choices falls back to them only when every clean replica is
    # saturated). Longer than the detector's 30s per-(node,phase) event
    # rate limit so a persistently slow host stays flagged between
    # sweeps; <= 0 disables routing flags entirely (events still fire).
    slow_node_route_ttl_s: float = 60.0
    # Serve ingress zero-copy threshold: a handle.remote() positional /
    # keyword arg that is bytes / bytearray / ndarray / jax.Array of at
    # least this many bytes is put() into the object store and passed BY
    # REFERENCE, so the payload rides the r8 vectored zero-copy wire
    # path + r13 arena-backed typed reducer end-to-end (driver arena ->
    # replica arena, no intermediate pickle copies) and the dispatch-
    # time PREFETCH_HINT overlaps the replica's fetch with dispatch.
    # Small args stay inline (a put + directory round-trip costs more
    # than it saves). The default is deliberately high: inline args
    # already ride the r8 zero-copy wire one hop, so by-ref only wins
    # once the payload is large enough to amortize the extra arena hop
    # and per-object control traffic — the ingress A/B in
    # SERVE_BENCH_r14.json measured by-ref LOSING on loopback below
    # ~16 MiB (0.34x rps at 2 MiB, 0.87x at 16 MiB). Lower it (e.g.
    # 512 KiB) when replicas sit behind a paced/real network link or
    # when the same payload fans out to many replicas (broadcast +
    # prefetch regimes, where by-ref wins). <= 0 disables the by-ref
    # conversion (the bench A/B control).
    serve_request_by_ref_min_bytes: int = 16 * 1024 * 1024
    # Serve deployment weights-by-ref threshold: an init arg of
    # ``Deployment.bind(...)`` that is an ndarray / jax.Array / bytes
    # of at least this many bytes — applied PER ARRAY, including
    # elements found inside (nested) list/tuple/dict containers; a
    # container of small shards each below the threshold ships inline
    # even if the container total exceeds it — is put() into the object
    # store ONCE at serve.run() time and
    # replaced by a reference in the replica-spec payload — every
    # replica fetches it through the object plane (cooperative
    # pipelined broadcast under concurrent scale-up: near-constant
    # cold-start in fleet size, root egress ~2xS) instead of unpickling
    # a private copy shipped inside CREATE_ACTOR args. The controller
    # also pre-warms these refs onto nodes at scale-up decision time
    # (OBJECT_WARM). <= 0 disables the conversion; explicit ObjectRef
    # init args are always resolved replica-side regardless.
    serve_weights_by_ref_min_bytes: int = 4 * 1024 * 1024
    # doctor_warnings(): flag a serve deployment whose autoscaler
    # reversed direction (up->down or down->up) more than this many
    # times inside the flap window (60s) — a flapping policy burns
    # cold-starts and kills warm replicas; raise the hysteresis
    # windows/cooldowns instead of living with it.
    serve_flap_warn_reversals: int = 3
    # doctor_warnings(): flag a deployment whose replica cold-start p95
    # exceeds this bound — weights are not riding the broadcast path
    # (missing by-ref init), or scale-ups are queueing behind placement.
    serve_cold_start_p95_warn_s: float = 30.0

    # --- streaming datasets: pipelined shuffle (r17) ---
    # Master switch for the r17 exchange. True (default) runs
    # all-to-all ops as the pipelined object-plane exchange: streamed
    # split admission with holder-locality, the merge fold tree with
    # eager part free, per-partition home placement, arena-fill
    # backpressure and merge-side prefetch hints, with COLUMNAR
    # split/merge kernels for Arrow blocks (routing computed without
    # materializing row dicts; ~5x kernel speedup measured at 1 MiB
    # blocks). False restores the pre-r17 drain-based exchange
    # verbatim (upstream ref drain, row-path kernels, all parts held
    # to their terminal merge) — the bench baseline and the escape
    # hatch should a block shape misbehave under the new kernels.
    data_shuffle_pipelined: bool = True
    # Split-task admission window of the data layer's all-to-all
    # exchange (`data/executor.py`): at most this many split tasks may
    # be submitted-but-incomplete at once, so upstream blocks are
    # consumed as a stream instead of drained wholesale and the store's
    # intermediate part footprint stays O(n_out x (window + fanin))
    # rather than O(n_in x n_out). 0 (default) sizes the window like
    # the map-stage budget: 2 tasks per cluster CPU, min 4.
    data_shuffle_inflight_window: int = 0
    # Arena-fill backpressure high-water fraction: while ANY node's shm
    # object-store fill (the `node.object_store_used_bytes /
    # node.object_store_capacity_bytes` telemetry gauges the head
    # already exports in its node state rows) exceeds this fraction,
    # the exchange pauses split admission — a shuffle working set
    # larger than memory degrades to pacing plus the existing spill
    # path (`object_spilling_threshold`, deliberately above this
    # default so pacing engages BEFORE spilling) instead of OOMing the
    # arena. <= 0 disables the gauge check (window-only admission).
    data_shuffle_store_highwater: float = 0.75
    # Merge-side fold-tree fan-in: each output partition folds every
    # this-many incoming split parts into ONE intermediate block
    # (order-preserving concat; piled-up intermediates fold again), so
    # part refs are freed at fold-submission time instead of every
    # (input, output) part surviving to the terminal merge. A TREE, not
    # an accumulator chain: rows are copied O(log_fanin(n_in)) times
    # and no fold waits on a chain of predecessors. Higher = fewer
    # merge tasks but more parts pending per partition (footprint
    # O(n_out x (fanin + window))); values < 2 are clamped to 2.
    data_shuffle_merge_fanin: int = 8
    # Dispatch-time PREFETCH_HINT / PREFETCH_HINT_BATCH for merge-task
    # args (the per-task `prefetch_args` option): with hints on, the
    # head starts pulling a merge's n_in part objects to its node while
    # earlier merges still compute — wide reads overlap compute, with
    # the r6 striped pulls doing the heavy lifting for multi-holder
    # parts. False submits shuffle merges with `prefetch_args=False`
    # (the bench A/B control; demand fetches still work).
    data_shuffle_prefetch_hints: bool = True

    # --- scheduling ---
    # Hybrid scheduling policy: prefer local node until its utilization
    # exceeds this, then spread (reference: scheduler_spread_threshold).
    scheduler_spread_threshold: float = 0.5
    # Top-k fraction of nodes considered for random tie-breaking
    # (reference: scheduler_top_k_fraction).
    scheduler_top_k_fraction: float = 0.2
    # Max tasks in flight pushed to one worker before backpressure.
    # Pipeline depth per leased worker. Deep enough to hide reply latency at
    # high task rates (the async-task throughput benchmark); the submitter
    # spreads queued tasks evenly across free workers, so coarse-grained
    # workloads still parallelize rather than hoarding one worker's pipeline.
    max_tasks_in_flight_per_worker: int = 40
    # Rate limit on concurrent lease requests per scheduling class (the
    # reference's max_pending_lease_requests_per_scheduling_category): the
    # head queues ungrantable requests, so unbounded requests just churn.
    max_pending_lease_requests_per_class: int = 10
    # Batched lease granting (head dispatcher thread): queued
    # LEASE_REQUESTs are granted in ONE pass over node state per
    # dispatch tick — a single head-lock hold instead of a lock/scan
    # per lease per retry — and a driver granted several leases in one
    # pass is acked with ONE ``LEASE_GRANT_BATCH`` frame carrying up to
    # this many grants (the request-side mirror of r8's
    # TASK_DONE_BATCH). <= 1 disables the batched reply frames (every
    # grant ships as its own LEASE_REPLY; the single-pass dispatch
    # itself is always on).
    lease_grant_batch_max: int = 64
    # Locality-aware leasing (reference: LocalityAwareLeasePolicy +
    # scheduler locality data, locality_aware_lease_policy.h): when a
    # task's by-reference args total at least locality_min_arg_bytes,
    # prefer the feasible node already holding the most argument bytes
    # over the hybrid/spread policies — the bytes then never move.
    scheduler_locality_enabled: bool = True
    locality_min_arg_bytes: int = 100 * 1024

    # --- worker pool ---
    # Max idle workers kept alive per scheduling class.
    idle_worker_keep_alive_s: float = 30.0
    # Fork CPU-count workers at head start so the first task burst finds an
    # idle pool (reference: WorkerPool prestart). Interpreter startup is
    # seconds; paying it mid-workload serializes behind the GIL-bound
    # driver on small hosts.
    prestart_workers: bool = True
    # Hard cap on worker processes per node (we run on few cores).
    max_workers_per_node: int = 16
    # Seconds to wait for a worker process to register before failing.
    worker_register_timeout_s: float = 30.0

    # --- actors ---
    actor_creation_timeout_s: float = 60.0

    # --- health / fault tolerance ---
    # Reference: 3s period, 5 failures (ray_config_def.h:791-797).
    health_check_period_s: float = 3.0
    health_check_failure_threshold: int = 5
    task_max_retries_default: int = 3
    # Owner-side lineage cache: plasma-resident task results whose creating
    # TaskSpec is retained for reconstruction after node loss (reference:
    # lineage_pinning + ObjectRecoveryManager, object_recovery_manager.h:41).
    lineage_cache_max_entries: int = 4096
    # Attempts to re-execute a creating task when recovering a lost object.
    object_recovery_max_attempts: int = 3
    # Durable head WAL (reference: GCS Redis-backed store client —
    # redis_store_client.h). Restores KV / named actors / PGs on restart.
    head_persistence: bool = True
    # Head fault tolerance (reference: GCS FT —
    # gcs_rpc_server_reconnect_timeout_s, ray_config_def.h): how long a
    # node agent / driver / worker keeps retrying its head channel after
    # a ConnectionLost before giving up with the pre-r12 fail-fast error
    # (agents shut down, workers exit, driver calls raise). While
    # reconnecting, writes park and in-flight call()s are replayed after
    # reattach with their original request ids — the head's
    # (client_id, request_id) dedupe map keeps a retried mutation that
    # already landed from applying twice. A head restarted on the same
    # address/session dir within this window is a recoverable event: the
    # cluster re-registers instead of dying.
    head_reconnect_timeout_s: float = 30.0
    # Bootstrap grace window of a RESTARTED head (same session dir => WAL
    # records found): lease granting, restored-actor/PG rescheduling and
    # the straggler/slow-node detectors hold for up to this long while
    # node agents / workers re-register, so the head never schedules
    # against a half-empty node table or double-schedules an actor whose
    # surviving worker is about to reclaim it. The window lifts EARLY
    # once at least one node is present and no new registration has
    # landed for 0.5s (re-registrations arrive in a burst right after
    # the head comes back). Fresh sessions (no WAL records) pay nothing.
    head_restart_grace_s: float = 5.0
    # OOM control (reference: memory_monitor.h:52 — 0.95 threshold,
    # 250ms refresh). refresh <= 0 disables the monitor.
    memory_usage_threshold: float = 0.95
    memory_monitor_refresh_s: float = 0.25

    # --- logging / events ---
    log_dir: str = ""
    task_event_buffer_size: int = 10000
    # Off-loop task-event folding (head fold thread): TASK_EVENTS
    # batches from the wire queue here and a dedicated thread folds
    # them into the timeline table — the head IO loop only routes. At
    # most this many BATCHES may be queued; overflow sheds the batch
    # (counted in ``fold_queue_drops``, surfaced via io_loop state and
    # doctor_warnings()) rather than backpressuring the control plane.
    # Sync flushes (timeline()'s ordering barrier) are acked by the
    # fold thread only after ingestion, so queries still observe them.
    task_event_fold_queue_max: int = 512
    # Folded per-task lifecycle timelines on the head (state_ts /
    # phase_ms rows behind `state.list_tasks`): max tasks retained,
    # FIFO-evicted by last activity. Independent of the raw event ring —
    # a timeline survives ring overflow. <= 0 disables folding entirely
    # (list_tasks goes empty; the raw ring still serves task_events).
    task_timeline_max_entries: int = 10000
    # Straggler detection (head-side detector thread over the folded
    # timelines). A RUNNING task is flagged — once, with one rate-limited
    # `task_straggler` cluster event naming task/node/worker — when its
    # current exec time exceeds `straggler_factor` x the p95 of its
    # func's COMPLETED exec distribution (task.phase_ms{func,exec}
    # histogram). The robust-bound comparison only arms once that
    # distribution holds at least `straggler_min_samples` completions
    # (the min-sample gate: p95 of two data points is noise, and a
    # brand-new func must not alarm on its first long run). The same
    # factor+gate drive the per-node phase-skew check (`slow_node`
    # events when one node's dispatch/arg_fetch p95 is factor x the
    # cluster median and at least 5ms over it).
    straggler_factor: float = 3.0
    straggler_min_samples: int = 5
    # Detector sweep period, seconds; <= 0 disables the detector thread
    # entirely (timelines and histograms still fold — only the
    # task_straggler / slow_node flagging stops).
    straggler_detect_period_s: float = 1.0
    # Head-side ring buffer for the structured cluster event log
    # (reference: the GCS event aggregator behind `ray list
    # cluster-events`). Overflow drops the oldest and counts the drops.
    cluster_event_buffer_size: int = 10000
    # Per-node physical telemetry sampling period (reference:
    # dashboard/modules/reporter/reporter_agent.py, 2.5s). <= 0 disables
    # the reporter thread.
    node_telemetry_period_s: float = 2.0
    # Flight recorder (r19): the head samples its merged metric table
    # every `timeseries_sample_s` seconds into per-series ring buffers —
    # counters folded to per-second rates, gauges as-is, histograms to
    # p50/p95/p99 point estimates. The fine ring keeps the most recent
    # `timeseries_window_s` seconds at full sample resolution; samples
    # that age out are 8:1 downsampled (mean) into a coarse ring
    # covering ~8x the window, so a post-hoc `state.metrics_history()`
    # or `/api/timeseries` query can still see the shape of an hour-old
    # incident at reduced resolution. Memory is bounded per series:
    # window_s/sample_s fine points + window_s/sample_s coarse points.
    # <= 0 sample period disables the recorder entirely.
    timeseries_sample_s: float = 1.0
    timeseries_window_s: float = 300.0
    # --- memory observatory (r20) ---
    # Arena accounting rides the node-telemetry heartbeat above
    # (`node_telemetry_period_s` is the sample cadence): each beat
    # publishes the store's memory_stats() as object_plane.arena_*
    # gauges, which flow into node rows, Prometheus, and the flight
    # recorder. The knobs below tune the derived surfaces only — the
    # accounting itself has no switch of its own (disable telemetry to
    # disable it).
    # Top-N largest-object cap for `ray_tpu memory` /
    # `/api/summary/memory` (reference: ray memory's --num-entries).
    memory_summary_top_n: int = 20
    # doctor: warn when a node's arena_used_bytes grew monotonically
    # (no sample below its predecessor) across the trailing
    # `arena_growth_warn_window_s` seconds of flight-recorder history
    # AND the total growth exceeds `arena_growth_warn_min_frac` of
    # capacity — the signature of a reference leak, as opposed to
    # steady-state churn which dips on every free.
    arena_growth_warn_window_s: float = 120.0
    arena_growth_warn_min_frac: float = 0.05
    # doctor: warn when a node's arena fill (used/capacity) crosses
    # this fraction — next allocation burst likely evicts or OOMs.
    arena_pressure_warn_frac: float = 0.90
    # doctor: warn when a borrow-ledger deferred delete has been stuck
    # behind live zero-copy views for longer than this (a leaked view
    # holds the arena slot forever); <= 0 disables the check.
    borrow_deferred_delete_warn_s: float = 30.0

    # Object-plane transfers (pull/push/prefetch) below this byte size
    # do NOT emit comm.* timeline spans; tiny control-sized objects
    # would otherwise flood the task-event ring with microsecond spans
    # that no overlap analysis cares about. Collective hops always
    # emit spans regardless of size (they are the workload).
    transfer_span_min_bytes: int = 65536

    # --- TPU ---
    # Override autodetected TPU topology, e.g. "v5p-64".
    tpu_accelerator_type: str = ""

    # --- cross-language gateway ---
    # Comma-separated module-prefix allowlist for XLANG_CALL (the framed
    # JSON task-submission endpoint used by the C++/Java clients). Empty =
    # allow any importable module, matching the trust model of the rest of
    # the protocol: every peer that can reach the head socket can already
    # submit pickled tasks (pickle implies arbitrary code execution), the
    # same cluster-internal trust boundary as the reference's GCS. Set
    # e.g. "myapp.,mylib.jobs" to restrict non-Python clients to known
    # entry points.
    xlang_allowed_prefixes: str = ""

    def __post_init__(self):
        for f in fields(self):
            env = os.environ.get(_ENV_PREFIX + f.name.upper())
            if env is None:
                continue
            if f.type in ("int", int):
                setattr(self, f.name, int(env))
            elif f.type in ("float", float):
                setattr(self, f.name, float(env))
            elif f.type in ("bool", bool):
                setattr(self, f.name, env.lower() in ("1", "true", "yes"))
            else:
                setattr(self, f.name, env)

    def apply_overrides(self, overrides: dict | str | None):
        if not overrides:
            return
        if isinstance(overrides, str):
            overrides = json.loads(overrides)
        for k, v in overrides.items():
            if not hasattr(self, k):
                raise ValueError(f"Unknown config key: {k}")
            setattr(self, k, v)


_config: Config | None = None


def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config()
    return _config


def reset_config():
    """Reset the singleton to defaults (+ env overrides).

    IN PLACE when a singleton already exists (r15): ``init()`` resets the
    config before applying ``_system_config``, and a module that grabbed
    ``get_config()`` BEFORE ``init()`` used to keep an orphaned object —
    its reads went stale and its mutations (e.g. a bench A/B toggling a
    flag) silently never reached the live runtime. Re-initializing the
    existing instance keeps every reference, whenever taken, pointing at
    the one live config."""
    global _config
    if _config is None:
        return
    fresh = Config()
    for f in fields(_config):
        setattr(_config, f.name, getattr(fresh, f.name))
