"""Peer-to-peer chunked object transfer between hosts.

Ref analog: src/ray/object_manager/ — the reference's per-node
ObjectManager serves 5 MiB chunk pulls directly between raylets
(object_manager.proto, pull_manager.cc) so object payloads never transit
the GCS. Same shape here: every host (each node agent, plus the head on
behalf of its in-process nodes) runs a ``TransferServer`` — a dedicated
TCP listener streaming objects out of the local shm arena in ~1 MiB raw
frames — and an ``ObjectPuller`` that connects straight to a peer's
server and writes arriving chunks into the local arena. The head only
brokers *who pulls from whom* (it hands the destination the holder set's
transfer addresses); payload bytes never touch head memory (asserted by
tests via the head's relay-byte counter).

Multi-source striped pulls (the reference's PullManager fan-out): when
the directory reports several holders and the object is large, the
puller opens connections to up to ``pull_max_sources`` of them and
requests disjoint contiguous ranges from each. Every chunk header
carries its absolute offset, so writes route into one arena buffer
regardless of which source they rode in on. A source dying mid-pull
fails only its remaining range: the tail it never delivered is
re-requested from a surviving holder instead of failing the pull.

Wire flow (all frames on a direct peer<->peer connection):
    puller -> server   OBJ_PULL (oid, start, length)         one-way
    server -> puller   OBJ_PULL_META (oid, size|-1, meta)    create buffer
    server -> puller   OBJ_PULL_CHUNK hdr + RAW frame  x N   (atomic pair)
    server -> puller   OBJ_PULL_DONE (oid, start, length)    range complete

Every buffer mutation happens on the puller's single IO thread, in stream
order — META creates the arena buffer before any chunk of that object can
be dispatched, so there is no allocation/arrival race by construction.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from . import protocol as P
from .config import get_config
from .ids import ObjectID
from .object_store import ObjectExistsError, ShmObjectStore


class TransferServer:
    """Serves OBJ_PULL range requests for objects in local shm arenas.

    ``read_fn(oid) -> (data_memoryview, meta_bytes, release_cb) | None``
    abstracts over "one agent store" vs "the head's local node stores".
    ``partial_fn(oid) -> PartialObject | None`` (optional) exposes this
    host's IN-PROGRESS pulls: with it, a pull the head routed at an
    in-progress location streams each chunk as soon as the local puller
    lands it (cooperative pipelined broadcast) instead of failing fast —
    the serving side of the reference PullManager's chunked re-serving.
    """

    def __init__(self, io: P.IOLoop, read_fn: Callable, host: str = "",
                 advertise_ip: str = "", partial_fn: Callable = None):
        self._read_fn = read_fn
        self._partial_fn = partial_fn
        self._listener = P.listen_tcp(host or "0.0.0.0", 0)
        port = self._listener.getsockname()[1]
        ip = advertise_ip or P.local_ip()
        self.addr = f"tcp:{ip}:{port}"
        self._io = io
        # per-chunk pause, settable by tests/chaos tooling to exercise the
        # mid-pull source-failure path deterministically
        self.throttle_s = 0.0
        # Per-HOST egress token bucket: every concurrent serve on this
        # host — all objects, all downstream pullers, root and relay
        # streams alike — drains this one bucket (0 = unlimited).
        # Seeded from ``host_egress_limit_bps``: broadcast_fanout's
        # per-object load accounting cannot stop K concurrent
        # broadcasts of K DIFFERENT objects stacking K x fanout streams
        # on one uplink (the r9 caveat); this bucket caps what actually
        # leaves the NIC no matter how many trees the planner built
        # through this host. Benches/tests also set it directly for
        # shared-uplink emulation.
        self.egress_limit_bps = get_config().host_egress_limit_bps
        self._pace_lock = threading.Lock()
        self._pace_t = 0.0
        # observability: requests served + egress bytes, split by source
        # role — "root" streams a sealed local copy, "relay" re-serves an
        # in-progress pull's chunks as they arrive. Guarded by
        # _stats_lock: serve threads run concurrently and a bare += on
        # the byte counters would lose increments (pull_requests alone
        # is IO-thread-only).
        self.pull_requests = 0
        self.served_root = 0
        self.served_relay = 0
        self.bytes_served = 0
        self.relay_bytes_served = 0
        self._stats_lock = threading.Lock()
        io.add_listener(self._listener, self._on_accept)

    def _on_accept(self, sock, _addr):
        sock.setsockopt(P.socket.IPPROTO_TCP, P.socket.TCP_NODELAY, 1)
        conn = P.Connection(sock, peer="xfer-client")
        self._io.add_connection(conn, self._on_message)

    def _on_message(self, conn: P.Connection, msg):
        if msg[0] != P.OBJ_PULL:
            return
        start = msg[3] if len(msg) > 3 else 0
        length = msg[4] if len(msg) > 4 else -1
        # clamp the peer-supplied wait once at the boundary: it is both
        # the appear-window and the per-chunk relay budget, and a rogue
        # value must not park serve threads forever
        wait_s = min(float(msg[5]), 120.0) if len(msg) > 5 else 0.0
        self.pull_requests += 1  # sole writer: this IO thread
        # Stream on a side thread: a multi-GiB send must not wedge the IO
        # loop that every other connection on this host shares. Concurrent
        # pulls on one connection are safe: each chunk's header+raw pair is
        # sent atomically (send_with_raw), and the puller writes by the
        # (oid, offset) in each header.
        threading.Thread(target=self._serve_pull,
                         args=(conn, msg[2], start, length, wait_s),
                         daemon=True).start()

    def _pace(self, nbytes: int):
        """Debit the shared egress bucket; sleeps the calling serve
        thread until its chunk's slot on the emulated uplink."""
        if not self.egress_limit_bps:
            return
        with self._pace_lock:
            now = time.monotonic()
            self._pace_t = max(self._pace_t, now) + \
                nbytes / self.egress_limit_bps
            wait = self._pace_t - now
        if wait > 0:
            time.sleep(wait)

    def _lookup(self, oid: ObjectID, wait_s: float):
        """-> (sealed_read | None, partial | None). With ``wait_s`` > 0
        the directory PROMISED this object is headed here (the local
        pull is in flight): poll briefly for the buffer to materialize
        instead of failing fast — a plain pull off a stale directory
        entry (wait_s == 0) keeps the old immediate-failover behavior."""
        got = self._read_fn(oid)
        if got is not None:
            return got, None
        if wait_s <= 0:
            # plain pull (e.g. a stale-directory probe): never serve a
            # partial — chunk-by-chunk dribble behind a slow upstream is
            # strictly worse than the immediate failover to a live
            # sealed holder the META -1 reply triggers
            return None, None
        part = self._partial_fn(oid) if self._partial_fn else None
        if part is not None:
            return None, part
        deadline = time.monotonic() + wait_s
        pause = 0.005
        while time.monotonic() < deadline:
            time.sleep(pause)
            # back off: the promised buffer usually appears within tens
            # of ms, but N waiters polling fast for the full budget
            # would hammer read_fn/partial_fn's locks (on the head,
            # that's the global head lock)
            pause = min(pause * 1.5, 0.1)
            got = self._read_fn(oid)
            if got is not None:
                return got, None
            part = self._partial_fn(oid) if self._partial_fn else None
            if part is not None:
                return None, part
        return None, None

    def _serve_pull(self, conn: P.Connection, oid_bin: bytes,
                    start: int = 0, length: int = -1, wait_s: float = 0.0):
        oid = ObjectID(oid_bin)
        try:
            got, part = self._lookup(oid, wait_s)
            if got is None and part is not None and \
                    part.state != "aborted":
                self._serve_partial(conn, oid, oid_bin, part, start,
                                    length, wait_s)
                return
            if got is None:
                # absent — or an aborted-pull tombstone: either way the
                # requester should fail over to another source NOW
                conn.send(P.OBJ_PULL_META, oid_bin, -1, b"")
                return
            data, meta, release = got
            try:
                # META always reports the FULL object size + meta so any
                # one source's reply lets the puller size the arena buffer
                conn.send(P.OBJ_PULL_META, oid_bin, len(data), bytes(meta))
                end = len(data) if length < 0 else min(start + length,
                                                       len(data))
                with self._stats_lock:
                    self.served_root += 1
                self._stream_range(conn, oid_bin, data, start, end,
                                   relay=False)
                # echo the REQUESTED range so the puller can match it even
                # when length was -1 (open-ended)
                conn.send(P.OBJ_PULL_DONE, oid_bin, start, length)
                self._count_serve("root", max(end - start, 0))
            finally:
                release()
        except P.ConnectionLost:
            pass

    def _stream_range(self, conn: P.Connection, oid_bin: bytes, data,
                      start: int, end: int, relay: bool):
        """Chunk-stream ``data[start:end]`` — ~1 MiB chunks so each
        typically completes within one receiver recv() buffer, hitting
        feed()'s zero-copy fast path (protocol.py). Sealed-view slices
        ship straight from the shm arena — no serialization copies."""
        cs = min(get_config().object_transfer_chunk_bytes, 1 << 20)
        for off in range(start, end, cs):
            self._send_chunk(conn, oid_bin, off, data[off:off + min(
                cs, end - off)], relay)

    def _send_chunk(self, conn: P.Connection, oid_bin: bytes, off: int,
                    chunk, relay: bool):
        """One chunk's egress: throttle, shared-uplink pacing, the
        atomic header+raw pair, byte accounting — the single sequence
        every serve path (sealed stream AND relay) must share."""
        if self.throttle_s:
            time.sleep(self.throttle_s)
        self._pace(len(chunk))
        conn.send_with_raw(P.OBJ_PULL_CHUNK, oid_bin, off, raw=chunk)
        self._count_bytes(len(chunk), relay)

    def _count_bytes(self, n: int, relay: bool):
        with self._stats_lock:
            self.bytes_served += n
            if relay:
                self.relay_bytes_served += n

    def _serve_partial(self, conn: P.Connection, oid: ObjectID,
                       oid_bin: bytes, part, start: int, length: int,
                       wait_s: float):
        """Relay an in-progress pull: stream each requested chunk the
        moment the local puller has it. If the local pull seals mid-
        relay, finish from the sealed copy (pinned); if it aborts or
        stalls past the wait budget, hand the UNDELIVERED tail back with
        OBJ_PULL_FAIL so the requester re-pulls it from the root holder
        set (relay-aware failover)."""
        size = part.size
        conn.send(P.OBJ_PULL_META, oid_bin, size, part.meta)
        end = size if length < 0 else min(start + length, size)
        with self._stats_lock:
            self.served_relay += 1
        cs = min(get_config().object_transfer_chunk_bytes, 1 << 20)
        budget = max(wait_s, 1.0)
        off = start
        sealed = False
        while off < end:
            n = min(cs, end - off)
            status = part.wait_covered(off, off + n, budget)
            if status == "sealed":
                sealed = True
                break
            chunk = part.read(off, off + n) if status == "ok" else None
            if chunk is None:
                if part.state == "sealed":
                    # seal landed between wait_covered and read (finish
                    # dropped the buffer): the object is HERE, whole —
                    # switch to the sealed copy, don't fail the range
                    sealed = True
                    break
                # aborted or stalled past the wait budget
                conn.send(P.OBJ_PULL_FAIL, oid_bin, off)
                self._count_serve("relay", max(off - start, 0))
                return
            self._send_chunk(conn, oid_bin, off, chunk, relay=True)
            off += n
        if sealed and off < end:
            # the partial is finished just BEFORE the native seal lands
            # (object_store.seal's eviction-safe ordering), so the
            # pinned read can trail the sealed flag by a moment — poll
            # briefly before declaring the copy gone (evicted)
            got = self._read_fn(oid)
            deadline = time.monotonic() + 2.0
            while got is None and time.monotonic() < deadline:
                time.sleep(0.002)
                got = self._read_fn(oid)
            if got is None:  # sealed copy evicted before we switched over
                conn.send(P.OBJ_PULL_FAIL, oid_bin, off)
                self._count_serve("relay", max(off - start, 0))
                return
            data, _meta, release = got
            try:
                self._stream_range(conn, oid_bin, data, off, end,
                                   relay=True)
            finally:
                release()
        conn.send(P.OBJ_PULL_DONE, oid_bin, start, length)
        self._count_serve("relay", max(end - start, 0))

    def _count_serve(self, role: str, nbytes: int):
        try:
            from ray_tpu.metrics import object_plane_metrics

            m = object_plane_metrics()
            tags = {"role": role}
            m["serves"].inc(1, tags)
            m["serve_bytes"].inc(nbytes, tags)
        except Exception:  # noqa: BLE001 — metrics must never fail a serve
            pass

    def close(self):
        try:
            self._io.remove(self._listener)
            self._listener.close()
        except OSError:
            pass


def send_eviction_report(head_conn, node_idx: int, oids) -> None:
    """One batched one-way OBJ_LOCATION_REMOVE dropping ``node_idx`` from
    the evicted objects' holder sets (best-effort: a missed report just
    means one extra pull failover off a stale directory entry)."""
    oid_bins = [oid.binary() for oid in oids]
    if not oid_bins:
        return
    try:
        head_conn.send(P.OBJ_LOCATION_REMOVE, oid_bins, node_idx)
    except P.ConnectionLost:
        pass


def send_eviction_report_async(head_conn, node_idx: int, oids) -> None:
    """Same, from a short-lived thread: evict() fires inside store.create
    on whatever thread is allocating — the puller IO thread included —
    and must never block there on a head socket write."""
    oids = list(oids)
    threading.Thread(target=send_eviction_report,
                     args=(head_conn, node_idx, oids), daemon=True).start()


class _Range:
    """One contiguous byte range assigned to one source."""

    __slots__ = ("start", "length", "received", "addr", "done")

    def __init__(self, start: int, length: int, addr: str):
        self.start = start
        self.length = length  # -1 = through end (size unknown at request)
        self.received = 0     # chunks per range arrive in order
        self.addr = addr
        self.done = False


class _PullState:
    __slots__ = ("buf", "done", "error", "buf_lock", "size", "ranges",
                 "conns", "addrs", "failed_addrs", "started",
                 "planned_sources", "max_sources", "relay_addrs", "part",
                 "prefetch", "joined")

    def __init__(self):
        # speculative-prefetch bookkeeping (r13): ``prefetch`` marks a
        # pull the head fired ahead of demand at lease grant/dispatch;
        # ``joined`` flips when a demand get() attaches to it via the
        # _pending leadership below — a joined prefetch is real work
        # and must no longer be abortable
        self.prefetch = False
        self.joined = False
        self.buf = None
        self.done = threading.Event()
        self.error: Optional[str] = None
        self.size = -1  # full object size, set by the first META
        self.planned_sources = 0  # stripe width at plan time (not failover)
        self.ranges: List[_Range] = []
        self.conns: Dict[P.Connection, str] = {}  # participating sources
        self.addrs: List[str] = []                # every candidate source
        self.failed_addrs: set = set()
        self.started = False
        self.max_sources = 0       # planner-imposed stripe cap (0 = config)
        self.relay_addrs: frozenset = frozenset()  # in-progress sources
        self.part = None  # local chunk-availability map (relay serving)
        # serializes chunk writes + range bookkeeping against the abort
        # path's buf=None + arena delete and against source reassignment —
        # a copy into a freed (and possibly reallocated) arena slot would
        # corrupt another object
        self.buf_lock = threading.Lock()


class ObjectPuller:
    """Pulls objects from peers' TransferServers into a local shm store.

    ``pull`` accepts one address or a holder list; with several holders
    and a known size, disjoint ranges are striped across up to
    ``pull_max_sources`` concurrent connections (PullManager analog).
    """

    def __init__(self, io: P.IOLoop, store: ShmObjectStore):
        self._io = io
        self._store = store
        self._conns: Dict[str, P.Connection] = {}
        self._pending: Dict[ObjectID, _PullState] = {}
        # per-connection (oid, offset) the next RAW frame belongs to —
        # send_with_raw guarantees the raw frame directly follows its header
        self._expect: Dict[P.Connection, Tuple[ObjectID, int]] = {}
        self._lock = threading.Lock()
        # cumulative observability counters (all written on the IO thread
        # or under pull()'s completion path; read by tests/metrics)
        self.bytes_by_source: Dict[str, int] = {}
        self.pulls_completed = 0
        self.multi_source_pulls = 0
        self.source_failovers = 0
        # demand get()s that attached to an in-flight prefetch pull
        # instead of starting cold (the r13 overlap actually observed)
        self.prefetch_joins = 0

    def _peer(self, addr: str) -> P.Connection:
        with self._lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn.closed:
                return conn
        sock = P.connect_addr(addr)
        conn = P.Connection(sock, peer=f"xfer:{addr}")
        conn.on_close = self._on_conn_close
        self._io.add_connection(conn, self._on_message)
        with self._lock:
            self._conns[addr] = conn
        return conn

    def pull(self, oid: ObjectID,
             peer_addr: Union[str, Sequence[str]],
             timeout: float = 120.0, size_hint: int = -1,
             max_sources: int = 0,
             relay_addrs: Sequence[str] = (),
             prefetch: bool = False) -> bool:
        """Blocking: fetch ``oid`` into the local store.

        ``peer_addr`` is one transfer address or the holder list from the
        object directory; ``size_hint`` (the directory's recorded size)
        enables striping without a metadata round trip. ``max_sources``
        caps the stripe width below ``pull_max_sources`` (the head's
        broadcast planner sets 1 so a relay-served pull never also
        stripes the root set — later addrs stay failover-only);
        ``relay_addrs`` marks which candidates are IN-PROGRESS pullers:
        their OBJ_PULLs carry the broadcast serve-wait budget so the
        relay subscribes us to chunk arrival instead of failing fast.
        ``prefetch`` marks a head-speculated pull (fired at lease
        grant/dispatch, ahead of any worker demand): it is abortable via
        ``abort()`` until a demand pull() joins it.
        """
        if self._store.contains(oid):
            return True
        addrs = [peer_addr] if isinstance(peer_addr, str) else \
            [a for a in peer_addr if a]
        addrs = list(dict.fromkeys(addrs))
        if not addrs:
            return False
        if size_hint <= 0:
            # a directory entry can carry size 0 before its true size is
            # learned — that means UNKNOWN, not zero-length (a requested
            # (0, 0) range would stream no bytes yet still seal)
            size_hint = -1
        with self._lock:
            st = self._pending.get(oid)
            if st is not None:
                leader = False
                if not prefetch and not st.joined:
                    # a demand get() attaching to an in-flight pull: if
                    # the leader was speculative, the join makes it real
                    # work (no longer abortable) — THE r13 overlap: the
                    # prefetch ran while dispatch was in flight and the
                    # worker's arg fetch starts warm
                    st.joined = True
                    if st.prefetch:
                        self.prefetch_joins += 1
            else:
                st = self._pending[oid] = _PullState()
                st.max_sources = max_sources
                st.relay_addrs = frozenset(relay_addrs)
                st.prefetch = prefetch
                leader = True
        if not leader:  # another thread is already pulling this object
            st.done.wait(timeout)
            return st.error is None and self._store.contains(oid)
        t0 = time.monotonic()
        try:
            self._start_pull(st, oid, addrs, size_hint)
            if st.error is None and not st.done.wait(timeout):
                st.error = "pull timed out"
        except P.ConnectionLost as e:
            st.error = str(e)
        finally:
            if st.error is not None and not self._store.contains(oid):
                # never leave a created-but-unsealed entry behind: it would
                # poison every retry (create fails on existing ids) while
                # readers block forever on an object that never seals.
                # buf_lock: an in-flight chunk copy must finish before the
                # arena slot is freed. Delete BEFORE dropping the _pending
                # entry: while we hold it no retry can become leader, so
                # this delete can never land on a retry's fresh buffer
                # (the reclaim in the META handler would otherwise race).
                with st.buf_lock:
                    st.buf = None
                    self._store.delete(oid)
            with self._lock:
                self._pending.pop(oid, None)
            st.done.set()
        ok = st.error is None
        if ok:
            self._record_pull(st, time.monotonic() - t0)
        return ok

    def _send_pull_req(self, conn: P.Connection, st: _PullState,
                       oid: ObjectID, start: int, length: int, addr: str):
        """OBJ_PULL with the serve-wait budget when the target is an
        in-progress relay (it subscribes us to chunk arrival) and the
        old fail-fast zero for sealed holders."""
        wait_s = get_config().broadcast_serve_wait_s \
            if addr in st.relay_addrs else 0.0
        conn.send(P.OBJ_PULL, oid.binary(), start, length, wait_s)

    def _start_pull(self, st: _PullState, oid: ObjectID,
                    addrs: List[str], size_hint: int):
        cfg = get_config()
        st.addrs = list(addrs)
        width = min(st.max_sources or cfg.pull_max_sources,
                    cfg.pull_max_sources)
        conns: List[Tuple[P.Connection, str]] = []
        for a in addrs:  # backfill past unreachable holders
            if len(conns) >= max(1, width):
                break
            try:
                conns.append((self._peer(a), a))
            except OSError:
                st.failed_addrs.add(a)
        if not conns:
            st.error = "no reachable sources"
            return
        with st.buf_lock:
            if size_hint >= max(cfg.pull_min_stripe_bytes, 1) and \
                    len(conns) > 1:
                # contiguous stripes, chunk-aligned so server-side chunking
                # stays on chunk boundaries
                cs = min(cfg.object_transfer_chunk_bytes, 1 << 20)
                per = ((size_hint + len(conns) - 1) // len(conns)
                       + cs - 1) // cs * cs
                start = 0
                for conn, addr in conns:
                    if start >= size_hint:
                        break
                    length = min(per, size_hint - start)
                    st.ranges.append(_Range(start, length, addr))
                    st.conns[conn] = addr
                    start += length
            else:
                conn, addr = conns[0]
                st.ranges.append(_Range(0, size_hint if size_hint >= 0
                                        else -1, addr))
                st.conns[conn] = addr
            st.started = True
            st.planned_sources = len({r.addr for r in st.ranges})
            plan = [(c, a, r) for r in st.ranges
                    for c, a in conns if a == r.addr]
        for conn, addr, r in plan:
            try:
                self._send_pull_req(conn, st, oid, r.start, r.length, addr)
            except P.ConnectionLost:
                # the IO loop may not have noticed the death yet — run the
                # failover path ourselves (idempotent with on_close)
                self._handle_conn_failure(conn)

    def _record_pull(self, st: _PullState, latency_s: float):
        # planned stripe width, NOT len({r.addr}): a failover replacement
        # range adds a second addr without the sources ever streaming
        # concurrently — counting it would conflate failover with striping
        n_sources = st.planned_sources or 1
        self.pulls_completed += 1
        if n_sources > 1:
            self.multi_source_pulls += 1
        try:
            from ray_tpu.metrics import object_plane_metrics

            m = object_plane_metrics()
            tags = {"source_count": str(n_sources)}
            m["pulls"].inc(1, tags)
            m["pull_bytes"].inc(max(st.size, 0), tags)
            m["pull_latency"].observe(latency_s)
        except Exception:  # noqa: BLE001 — metrics must never fail a pull
            pass
        # comm-aware timeline (r19): transfers worth analyzing land as
        # retroactive comm.* spans — stamped once at completion so the
        # streaming path itself carries no tracing work. Small control
        # objects stay off the ring (transfer_span_min_bytes); node
        # agents (no CoreContext) no-op inside record_comm_span.
        try:
            if st.size >= get_config().transfer_span_min_bytes:
                from ray_tpu import tracing

                kind = "prefetch" if st.prefetch and not st.joined \
                    else "pull"
                now_m, now_w = time.monotonic(), time.time()
                tracing.record_comm_span(
                    f"{kind}.{n_sources}src", now_w - latency_s, now_w,
                    now_m - latency_s, now_m)
        except Exception:  # noqa: BLE001 — tracing must never fail a pull
            pass

    # ---- everything below runs on the IO thread, in stream order ----

    def _on_message(self, conn: P.Connection, msg):
        mt = msg[0]
        if mt == P.OBJ_PULL_META:
            oid, size, meta = ObjectID(msg[2]), msg[3], msg[4]
            with self._lock:
                st = self._pending.get(oid)
            if st is None:
                return
            if size < 0:
                # stale directory entry: this source no longer holds THIS
                # object — fail over this pull's ranges only. The
                # connection itself is healthy and may be mid-stream for
                # other objects; failing those too would poison their
                # source sets.
                self._handle_conn_failure(conn, reason="object not on peer",
                                          only_oid=oid)
                return
            with st.buf_lock:
                if st.size >= 0:
                    return  # another source's META already sized the buffer
                st.size = size
                for r in st.ranges:
                    if r.length < 0:  # open-ended request, now resolvable
                        r.length = size - r.start
                try:
                    st.buf = self._store.create(oid, size, len(meta))
                except ObjectExistsError:
                    if self._store.contains(oid):  # already sealed locally
                        st.done.set()
                        return
                    # unsealed leftover from a failed earlier pull: reclaim
                    self._store.delete(oid)
                    try:
                        st.buf = self._store.create(oid, size, len(meta))
                    except Exception as e:  # noqa: BLE001
                        st.error = f"create failed: {e}"
                        st.done.set()
                        return
                except Exception as e:  # noqa: BLE001 — e.g. store full
                    st.error = f"create failed: {e}"
                    st.done.set()
                    return
                st.buf[size:] = meta
                if size == 0:
                    st.buf = None
                    self._store.seal(oid)
                    st.done.set()
                    return
                # publish the unsealed buffer's availability map so this
                # host's TransferServer can relay chunks as they land
                # (cooperative broadcast); seal/delete of the id finish
                # the entry automatically
                st.part = self._store.begin_partial(oid, st.buf, size,
                                                    bytes(meta))
        elif mt == P.OBJ_PULL_CHUNK:
            self._expect[conn] = (ObjectID(msg[2]), msg[3])
        elif mt == P.RAW_FRAME:
            exp = self._expect.pop(conn, None)
            if exp is None:
                return
            oid, off = exp
            payload = msg[2]
            with self._lock:
                st = self._pending.get(oid)
            if st is None:
                return
            n = len(payload)
            with st.buf_lock:
                buf = st.buf
                addr = st.conns.get(conn)
                if buf is not None:
                    # vectorized copy into the arena (~2x a memoryview
                    # slice assignment; this is the receive-side hot
                    # loop). payload may be a memoryview into the recv
                    # buffer (feed()'s zero-copy fast path) — consumed
                    # before returning.
                    np.copyto(
                        np.frombuffer(buf[off:off + n], np.uint8),
                        np.frombuffer(payload, np.uint8))
                    # per-range progress, for resume-after-source-death.
                    # Match by source + containment (ranges are disjoint
                    # per source), NOT just expected-next-offset: at a
                    # stripe boundary the next range's first chunk lands
                    # exactly at start+received of a finished-but-not-DONE
                    # neighbour and must not be credited to it. Chunks
                    # within one range arrive in stream order, so the
                    # received high-water mark only advances on the next
                    # expected offset.
                    if addr is not None:
                        for r in st.ranges:
                            if r.done or r.addr != addr or off < r.start:
                                continue
                            if r.length >= 0 and off >= r.start + r.length:
                                continue
                            if off == r.start + r.received:
                                r.received += n
                            break
                    if st.part is not None:
                        # AFTER the copy: a relay must never stream bytes
                        # the arena doesn't hold yet
                        st.part.mark(off, off + n)
            if addr is not None:
                # sole writer is this IO thread — plain dict update is safe
                self.bytes_by_source[addr] = \
                    self.bytes_by_source.get(addr, 0) + n
        elif mt == P.OBJ_PULL_FAIL:
            # a relay could not complete our range (its own pull aborted
            # or stalled): fail over THIS object's ranges on this
            # connection only — the connection is healthy, and what
            # already arrived stays credited; the undelivered tail is
            # re-requested from the remaining candidates (the root set)
            oid = ObjectID(msg[2])
            self._handle_conn_failure(conn, reason="relay source aborted",
                                      only_oid=oid)
        elif mt == P.OBJ_PULL_DONE:
            oid = ObjectID(msg[2])
            start = msg[3] if len(msg) > 3 else 0
            with self._lock:
                st = self._pending.get(oid)
            if st is None:
                return
            with st.buf_lock:
                for r in st.ranges:
                    if not r.done and r.start == start:
                        r.done = True
                        break
                self._maybe_seal(st, oid)

    def _maybe_seal(self, st: _PullState, oid: ObjectID):
        """Seal + wake once every assigned range completed (buf_lock held)."""
        if st.buf is None or not st.started:
            return
        if any(not r.done for r in st.ranges):
            return
        st.buf = None  # drop the arena view before sealing
        try:
            self._store.seal(oid)
        except KeyError:
            st.error = "seal failed"
        st.done.set()

    def abort(self, oid: ObjectID) -> bool:
        """Abort an in-flight PREFETCH pull (head PULL_ABORT: the task
        that speculated it was cancelled / retried elsewhere). Only
        prefetch-flagged pulls no demand get() has joined are honored —
        a pull real work waits on is never killed by stale speculation.
        The woken leader's cleanup path deletes the created-but-unsealed
        arena entry (the r9 abort machinery: partial finished under the
        entry lock, relays handed OBJ_PULL_FAIL, slot freed only after
        in-flight reads drain)."""
        with self._lock:
            # same lock the follower path sets st.joined under: either
            # the join serialized first (we back off) or the abort wins
            # outright — a join can no longer slip between the check
            # and the error write
            st = self._pending.get(oid)
            if st is None or not st.prefetch or st.joined:
                return False
            if st.error is None:
                st.error = "prefetch aborted"
        st.done.set()
        return True

    # ---- source failure / striped-range failover ----

    def _on_conn_close(self, conn: P.Connection):
        """A source died: fail over its in-flight ranges now, not at
        timeout — and drop every per-connection table entry so a recycled
        Connection object can never route a stale chunk."""
        self._expect.pop(conn, None)
        with self._lock:
            for addr, c in list(self._conns.items()):
                if c is conn:
                    del self._conns[addr]
        self._handle_conn_failure(conn)

    def _handle_conn_failure(self, conn: P.Connection,
                             reason: str = "transfer connection lost",
                             only_oid: Optional[ObjectID] = None):
        """``only_oid`` scopes the failover to one pull (stale directory
        entry on a live connection); None means the connection died and
        every pull riding it must reassign."""
        with self._lock:
            stale = [(oid, st) for oid, st in self._pending.items()
                     if conn in st.conns
                     and (only_oid is None or oid == only_oid)]
        if not stale:
            return
        # Reassignment may dial a NEW source (blocking connect) — never on
        # the IO thread, which delivers every other connection's bytes.
        threading.Thread(target=self._failover, args=(conn, stale, reason),
                         daemon=True).start()

    def _failover(self, dead: P.Connection, stale, reason: str):
        for oid, st in stale:
            with st.buf_lock:
                addr_dead = st.conns.pop(dead, None)
                if addr_dead is None:
                    continue  # concurrent failover already handled it
                st.failed_addrs.add(addr_dead)
                # Ranges the dead source fully delivered (only the DONE
                # frame was lost) can close now. Ranges with an undelivered
                # tail stay NOT-done until their replacement range exists:
                # marking them done before the reassignment lands would let
                # a surviving source's OBJ_PULL_DONE seal a partially-
                # written object in the window between lock holds.
                broken: List[_Range] = []
                for r in st.ranges:
                    if r.done or r.addr != addr_dead:
                        continue
                    if r.length >= 0 and r.received >= r.length:
                        r.done = True
                        continue
                    broken.append(r)
                if not broken:
                    # the dead source had finished its share — the pull may
                    # now be complete
                    self._maybe_seal(st, oid)
                    continue
            target = self._pick_failover_source(st)
            if target is None:
                st.error = reason
                st.done.set()
                continue
            tconn, taddr = target
            self.source_failovers += 1
            plan: List[Tuple[int, int]] = []
            with st.buf_lock:
                st.conns[tconn] = taddr
                for r in broken:
                    # freeze the old range at what actually arrived; its
                    # undelivered tail becomes a fresh range on the target
                    # — appended in the SAME lock hold that closes the old
                    # one, so _maybe_seal never sees a gap
                    resume = r.start + r.received
                    remaining = (r.length - r.received) if r.length >= 0 \
                        else -1
                    r.length = r.received
                    r.done = True
                    st.ranges.append(_Range(resume, remaining, taddr))
                    plan.append((resume, remaining))
            try:
                for resume, remaining in plan:
                    self._send_pull_req(tconn, st, oid, resume, remaining,
                                        taddr)
            except P.ConnectionLost:
                self._handle_conn_failure(tconn)

    def _pick_failover_source(self, st: _PullState):
        """A surviving participant, else an untried candidate address."""
        with st.buf_lock:
            for c, a in st.conns.items():
                if not c.closed:
                    return c, a
            candidates = [a for a in st.addrs if a not in st.failed_addrs
                          and a not in st.conns.values()]
        for a in candidates:
            try:
                return self._peer(a), a
            except OSError:
                with st.buf_lock:
                    st.failed_addrs.add(a)
        return None

    def close(self):
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.on_close = None
            c.close()
