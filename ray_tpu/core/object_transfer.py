"""Peer-to-peer chunked object transfer between hosts.

Ref analog: src/ray/object_manager/ — the reference's per-node
ObjectManager serves 5 MiB chunk pulls directly between raylets
(object_manager.proto, pull_manager.cc) so object payloads never transit
the GCS. Same shape here: every host (each node agent, plus the head on
behalf of its in-process nodes) runs a ``TransferServer`` — a dedicated
TCP listener streaming objects out of the local shm arena in ~1 MiB raw
frames — and an ``ObjectPuller`` that connects straight to a peer's
server and writes arriving chunks into the local arena. The head only
brokers *who pulls from whom* (it hands the destination the holder set's
transfer addresses); payload bytes never touch head memory (asserted by
tests via the head's relay-byte counter).

Multi-source striped pulls (the reference's PullManager fan-out): when
the directory reports several holders and the object is large, the
puller opens connections to up to ``pull_max_sources`` of them and
requests disjoint contiguous ranges from each. Every chunk header
carries its absolute offset, so writes route into one arena buffer
regardless of which source they rode in on. A source dying mid-pull
fails only its remaining range: the tail it never delivered is
re-requested from a surviving holder instead of failing the pull.

Wire flow (all frames on a direct peer<->peer connection):
    puller -> server   OBJ_PULL (oid, start, length)         one-way
    server -> puller   OBJ_PULL_META (oid, size|-1, meta)    create buffer
    server -> puller   OBJ_PULL_CHUNK hdr + RAW frame  x N   (atomic pair)
    server -> puller   OBJ_PULL_DONE (oid, start, length)    range complete

Every buffer mutation happens on the puller's single IO thread, in stream
order — META creates the arena buffer before any chunk of that object can
be dispatched, so there is no allocation/arrival race by construction.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from . import protocol as P
from .config import get_config
from .ids import ObjectID
from .object_store import ObjectExistsError, ShmObjectStore


class TransferServer:
    """Serves OBJ_PULL range requests for objects in local shm arenas.

    ``read_fn(oid) -> (data_memoryview, meta_bytes, release_cb) | None``
    abstracts over "one agent store" vs "the head's local node stores".
    """

    def __init__(self, io: P.IOLoop, read_fn: Callable, host: str = "",
                 advertise_ip: str = ""):
        self._read_fn = read_fn
        self._listener = P.listen_tcp(host or "0.0.0.0", 0)
        port = self._listener.getsockname()[1]
        ip = advertise_ip or P.local_ip()
        self.addr = f"tcp:{ip}:{port}"
        self._io = io
        # per-chunk pause, settable by tests/chaos tooling to exercise the
        # mid-pull source-failure path deterministically
        self.throttle_s = 0.0
        io.add_listener(self._listener, self._on_accept)

    def _on_accept(self, sock, _addr):
        sock.setsockopt(P.socket.IPPROTO_TCP, P.socket.TCP_NODELAY, 1)
        conn = P.Connection(sock, peer="xfer-client")
        self._io.add_connection(conn, self._on_message)

    def _on_message(self, conn: P.Connection, msg):
        if msg[0] != P.OBJ_PULL:
            return
        start = msg[3] if len(msg) > 3 else 0
        length = msg[4] if len(msg) > 4 else -1
        # Stream on a side thread: a multi-GiB send must not wedge the IO
        # loop that every other connection on this host shares. Concurrent
        # pulls on one connection are safe: each chunk's header+raw pair is
        # sent atomically (send_with_raw), and the puller writes by the
        # (oid, offset) in each header.
        threading.Thread(target=self._serve_pull,
                         args=(conn, msg[2], start, length),
                         daemon=True).start()

    def _serve_pull(self, conn: P.Connection, oid_bin: bytes,
                    start: int = 0, length: int = -1):
        oid = ObjectID(oid_bin)
        got = self._read_fn(oid)
        try:
            if got is None:
                conn.send(P.OBJ_PULL_META, oid_bin, -1, b"")
                return
            data, meta, release = got
            try:
                # META always reports the FULL object size + meta so any
                # one source's reply lets the puller size the arena buffer
                conn.send(P.OBJ_PULL_META, oid_bin, len(data), bytes(meta))
                end = len(data) if length < 0 else min(start + length,
                                                       len(data))
                # ~1 MiB chunks so each typically completes within one
                # receiver recv() buffer, hitting feed()'s zero-copy fast
                # path (protocol.py). Each chunk is written straight from
                # the shm arena view — no serialization copies.
                cs = min(get_config().object_transfer_chunk_bytes, 1 << 20)
                for off in range(start, end, cs):
                    if self.throttle_s:
                        time.sleep(self.throttle_s)
                    conn.send_with_raw(P.OBJ_PULL_CHUNK, oid_bin, off,
                                       raw=data[off:min(off + cs, end)])
                # echo the REQUESTED range so the puller can match it even
                # when length was -1 (open-ended)
                conn.send(P.OBJ_PULL_DONE, oid_bin, start, length)
            finally:
                release()
        except P.ConnectionLost:
            pass

    def close(self):
        try:
            self._io.remove(self._listener)
            self._listener.close()
        except OSError:
            pass


def send_eviction_report(head_conn, node_idx: int, oids) -> None:
    """One batched one-way OBJ_LOCATION_REMOVE dropping ``node_idx`` from
    the evicted objects' holder sets (best-effort: a missed report just
    means one extra pull failover off a stale directory entry)."""
    oid_bins = [oid.binary() for oid in oids]
    if not oid_bins:
        return
    try:
        head_conn.send(P.OBJ_LOCATION_REMOVE, oid_bins, node_idx)
    except P.ConnectionLost:
        pass


def send_eviction_report_async(head_conn, node_idx: int, oids) -> None:
    """Same, from a short-lived thread: evict() fires inside store.create
    on whatever thread is allocating — the puller IO thread included —
    and must never block there on a head socket write."""
    oids = list(oids)
    threading.Thread(target=send_eviction_report,
                     args=(head_conn, node_idx, oids), daemon=True).start()


class _Range:
    """One contiguous byte range assigned to one source."""

    __slots__ = ("start", "length", "received", "addr", "done")

    def __init__(self, start: int, length: int, addr: str):
        self.start = start
        self.length = length  # -1 = through end (size unknown at request)
        self.received = 0     # chunks per range arrive in order
        self.addr = addr
        self.done = False


class _PullState:
    __slots__ = ("buf", "done", "error", "buf_lock", "size", "ranges",
                 "conns", "addrs", "failed_addrs", "started",
                 "planned_sources")

    def __init__(self):
        self.buf = None
        self.done = threading.Event()
        self.error: Optional[str] = None
        self.size = -1  # full object size, set by the first META
        self.planned_sources = 0  # stripe width at plan time (not failover)
        self.ranges: List[_Range] = []
        self.conns: Dict[P.Connection, str] = {}  # participating sources
        self.addrs: List[str] = []                # every candidate source
        self.failed_addrs: set = set()
        self.started = False
        # serializes chunk writes + range bookkeeping against the abort
        # path's buf=None + arena delete and against source reassignment —
        # a copy into a freed (and possibly reallocated) arena slot would
        # corrupt another object
        self.buf_lock = threading.Lock()


class ObjectPuller:
    """Pulls objects from peers' TransferServers into a local shm store.

    ``pull`` accepts one address or a holder list; with several holders
    and a known size, disjoint ranges are striped across up to
    ``pull_max_sources`` concurrent connections (PullManager analog).
    """

    def __init__(self, io: P.IOLoop, store: ShmObjectStore):
        self._io = io
        self._store = store
        self._conns: Dict[str, P.Connection] = {}
        self._pending: Dict[ObjectID, _PullState] = {}
        # per-connection (oid, offset) the next RAW frame belongs to —
        # send_with_raw guarantees the raw frame directly follows its header
        self._expect: Dict[P.Connection, Tuple[ObjectID, int]] = {}
        self._lock = threading.Lock()
        # cumulative observability counters (all written on the IO thread
        # or under pull()'s completion path; read by tests/metrics)
        self.bytes_by_source: Dict[str, int] = {}
        self.pulls_completed = 0
        self.multi_source_pulls = 0
        self.source_failovers = 0

    def _peer(self, addr: str) -> P.Connection:
        with self._lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn.closed:
                return conn
        sock = P.connect_addr(addr)
        conn = P.Connection(sock, peer=f"xfer:{addr}")
        conn.on_close = self._on_conn_close
        self._io.add_connection(conn, self._on_message)
        with self._lock:
            self._conns[addr] = conn
        return conn

    def pull(self, oid: ObjectID,
             peer_addr: Union[str, Sequence[str]],
             timeout: float = 120.0, size_hint: int = -1) -> bool:
        """Blocking: fetch ``oid`` into the local store.

        ``peer_addr`` is one transfer address or the holder list from the
        object directory; ``size_hint`` (the directory's recorded size)
        enables striping without a metadata round trip.
        """
        if self._store.contains(oid):
            return True
        addrs = [peer_addr] if isinstance(peer_addr, str) else \
            [a for a in peer_addr if a]
        addrs = list(dict.fromkeys(addrs))
        if not addrs:
            return False
        if size_hint <= 0:
            # a directory entry can carry size 0 before its true size is
            # learned — that means UNKNOWN, not zero-length (a requested
            # (0, 0) range would stream no bytes yet still seal)
            size_hint = -1
        with self._lock:
            st = self._pending.get(oid)
            if st is not None:
                leader = False
            else:
                st = self._pending[oid] = _PullState()
                leader = True
        if not leader:  # another thread is already pulling this object
            st.done.wait(timeout)
            return st.error is None and self._store.contains(oid)
        t0 = time.monotonic()
        try:
            self._start_pull(st, oid, addrs, size_hint)
            if st.error is None and not st.done.wait(timeout):
                st.error = "pull timed out"
        except P.ConnectionLost as e:
            st.error = str(e)
        finally:
            with self._lock:
                self._pending.pop(oid, None)
            if st.error is not None and not self._store.contains(oid):
                # never leave a created-but-unsealed entry behind: it would
                # poison every retry (create fails on existing ids) while
                # readers block forever on an object that never seals.
                # buf_lock: an in-flight chunk copy must finish before the
                # arena slot is freed.
                with st.buf_lock:
                    st.buf = None
                    self._store.delete(oid)
            st.done.set()
        ok = st.error is None
        if ok:
            self._record_pull(st, time.monotonic() - t0)
        return ok

    def _start_pull(self, st: _PullState, oid: ObjectID,
                    addrs: List[str], size_hint: int):
        cfg = get_config()
        st.addrs = list(addrs)
        conns: List[Tuple[P.Connection, str]] = []
        for a in addrs:  # backfill past unreachable holders
            if len(conns) >= max(1, cfg.pull_max_sources):
                break
            try:
                conns.append((self._peer(a), a))
            except OSError:
                st.failed_addrs.add(a)
        if not conns:
            st.error = "no reachable sources"
            return
        with st.buf_lock:
            if size_hint >= max(cfg.pull_min_stripe_bytes, 1) and \
                    len(conns) > 1:
                # contiguous stripes, chunk-aligned so server-side chunking
                # stays on chunk boundaries
                cs = min(cfg.object_transfer_chunk_bytes, 1 << 20)
                per = ((size_hint + len(conns) - 1) // len(conns)
                       + cs - 1) // cs * cs
                start = 0
                for conn, addr in conns:
                    if start >= size_hint:
                        break
                    length = min(per, size_hint - start)
                    st.ranges.append(_Range(start, length, addr))
                    st.conns[conn] = addr
                    start += length
            else:
                conn, addr = conns[0]
                st.ranges.append(_Range(0, size_hint if size_hint >= 0
                                        else -1, addr))
                st.conns[conn] = addr
            st.started = True
            st.planned_sources = len({r.addr for r in st.ranges})
            plan = [(c, a, r) for r in st.ranges
                    for c, a in conns if a == r.addr]
        for conn, _addr, r in plan:
            try:
                conn.send(P.OBJ_PULL, oid.binary(), r.start, r.length)
            except P.ConnectionLost:
                # the IO loop may not have noticed the death yet — run the
                # failover path ourselves (idempotent with on_close)
                self._handle_conn_failure(conn)

    def _record_pull(self, st: _PullState, latency_s: float):
        # planned stripe width, NOT len({r.addr}): a failover replacement
        # range adds a second addr without the sources ever streaming
        # concurrently — counting it would conflate failover with striping
        n_sources = st.planned_sources or 1
        self.pulls_completed += 1
        if n_sources > 1:
            self.multi_source_pulls += 1
        try:
            from ray_tpu.metrics import object_plane_metrics

            m = object_plane_metrics()
            tags = {"source_count": str(n_sources)}
            m["pulls"].inc(1, tags)
            m["pull_bytes"].inc(max(st.size, 0), tags)
            m["pull_latency"].observe(latency_s)
        except Exception:  # noqa: BLE001 — metrics must never fail a pull
            pass

    # ---- everything below runs on the IO thread, in stream order ----

    def _on_message(self, conn: P.Connection, msg):
        mt = msg[0]
        if mt == P.OBJ_PULL_META:
            oid, size, meta = ObjectID(msg[2]), msg[3], msg[4]
            with self._lock:
                st = self._pending.get(oid)
            if st is None:
                return
            if size < 0:
                # stale directory entry: this source no longer holds THIS
                # object — fail over this pull's ranges only. The
                # connection itself is healthy and may be mid-stream for
                # other objects; failing those too would poison their
                # source sets.
                self._handle_conn_failure(conn, reason="object not on peer",
                                          only_oid=oid)
                return
            with st.buf_lock:
                if st.size >= 0:
                    return  # another source's META already sized the buffer
                st.size = size
                for r in st.ranges:
                    if r.length < 0:  # open-ended request, now resolvable
                        r.length = size - r.start
                try:
                    st.buf = self._store.create(oid, size, len(meta))
                except ObjectExistsError:
                    if self._store.contains(oid):  # already sealed locally
                        st.done.set()
                        return
                    # unsealed leftover from a failed earlier pull: reclaim
                    self._store.delete(oid)
                    try:
                        st.buf = self._store.create(oid, size, len(meta))
                    except Exception as e:  # noqa: BLE001
                        st.error = f"create failed: {e}"
                        st.done.set()
                        return
                except Exception as e:  # noqa: BLE001 — e.g. store full
                    st.error = f"create failed: {e}"
                    st.done.set()
                    return
                st.buf[size:] = meta
                if size == 0:
                    st.buf = None
                    self._store.seal(oid)
                    st.done.set()
        elif mt == P.OBJ_PULL_CHUNK:
            self._expect[conn] = (ObjectID(msg[2]), msg[3])
        elif mt == P.RAW_FRAME:
            exp = self._expect.pop(conn, None)
            if exp is None:
                return
            oid, off = exp
            payload = msg[2]
            with self._lock:
                st = self._pending.get(oid)
            if st is None:
                return
            n = len(payload)
            with st.buf_lock:
                buf = st.buf
                addr = st.conns.get(conn)
                if buf is not None:
                    # vectorized copy into the arena (~2x a memoryview
                    # slice assignment; this is the receive-side hot
                    # loop). payload may be a memoryview into the recv
                    # buffer (feed()'s zero-copy fast path) — consumed
                    # before returning.
                    np.copyto(
                        np.frombuffer(buf[off:off + n], np.uint8),
                        np.frombuffer(payload, np.uint8))
                    # per-range progress, for resume-after-source-death.
                    # Match by source + containment (ranges are disjoint
                    # per source), NOT just expected-next-offset: at a
                    # stripe boundary the next range's first chunk lands
                    # exactly at start+received of a finished-but-not-DONE
                    # neighbour and must not be credited to it. Chunks
                    # within one range arrive in stream order, so the
                    # received high-water mark only advances on the next
                    # expected offset.
                    if addr is not None:
                        for r in st.ranges:
                            if r.done or r.addr != addr or off < r.start:
                                continue
                            if r.length >= 0 and off >= r.start + r.length:
                                continue
                            if off == r.start + r.received:
                                r.received += n
                            break
            if addr is not None:
                # sole writer is this IO thread — plain dict update is safe
                self.bytes_by_source[addr] = \
                    self.bytes_by_source.get(addr, 0) + n
        elif mt == P.OBJ_PULL_DONE:
            oid = ObjectID(msg[2])
            start = msg[3] if len(msg) > 3 else 0
            with self._lock:
                st = self._pending.get(oid)
            if st is None:
                return
            with st.buf_lock:
                for r in st.ranges:
                    if not r.done and r.start == start:
                        r.done = True
                        break
                self._maybe_seal(st, oid)

    def _maybe_seal(self, st: _PullState, oid: ObjectID):
        """Seal + wake once every assigned range completed (buf_lock held)."""
        if st.buf is None or not st.started:
            return
        if any(not r.done for r in st.ranges):
            return
        st.buf = None  # drop the arena view before sealing
        try:
            self._store.seal(oid)
        except KeyError:
            st.error = "seal failed"
        st.done.set()

    # ---- source failure / striped-range failover ----

    def _on_conn_close(self, conn: P.Connection):
        """A source died: fail over its in-flight ranges now, not at
        timeout — and drop every per-connection table entry so a recycled
        Connection object can never route a stale chunk."""
        self._expect.pop(conn, None)
        with self._lock:
            for addr, c in list(self._conns.items()):
                if c is conn:
                    del self._conns[addr]
        self._handle_conn_failure(conn)

    def _handle_conn_failure(self, conn: P.Connection,
                             reason: str = "transfer connection lost",
                             only_oid: Optional[ObjectID] = None):
        """``only_oid`` scopes the failover to one pull (stale directory
        entry on a live connection); None means the connection died and
        every pull riding it must reassign."""
        with self._lock:
            stale = [(oid, st) for oid, st in self._pending.items()
                     if conn in st.conns
                     and (only_oid is None or oid == only_oid)]
        if not stale:
            return
        # Reassignment may dial a NEW source (blocking connect) — never on
        # the IO thread, which delivers every other connection's bytes.
        threading.Thread(target=self._failover, args=(conn, stale, reason),
                         daemon=True).start()

    def _failover(self, dead: P.Connection, stale, reason: str):
        for oid, st in stale:
            with st.buf_lock:
                addr_dead = st.conns.pop(dead, None)
                if addr_dead is None:
                    continue  # concurrent failover already handled it
                st.failed_addrs.add(addr_dead)
                # Ranges the dead source fully delivered (only the DONE
                # frame was lost) can close now. Ranges with an undelivered
                # tail stay NOT-done until their replacement range exists:
                # marking them done before the reassignment lands would let
                # a surviving source's OBJ_PULL_DONE seal a partially-
                # written object in the window between lock holds.
                broken: List[_Range] = []
                for r in st.ranges:
                    if r.done or r.addr != addr_dead:
                        continue
                    if r.length >= 0 and r.received >= r.length:
                        r.done = True
                        continue
                    broken.append(r)
                if not broken:
                    # the dead source had finished its share — the pull may
                    # now be complete
                    self._maybe_seal(st, oid)
                    continue
            target = self._pick_failover_source(st)
            if target is None:
                st.error = reason
                st.done.set()
                continue
            tconn, taddr = target
            self.source_failovers += 1
            plan: List[Tuple[int, int]] = []
            with st.buf_lock:
                st.conns[tconn] = taddr
                for r in broken:
                    # freeze the old range at what actually arrived; its
                    # undelivered tail becomes a fresh range on the target
                    # — appended in the SAME lock hold that closes the old
                    # one, so _maybe_seal never sees a gap
                    resume = r.start + r.received
                    remaining = (r.length - r.received) if r.length >= 0 \
                        else -1
                    r.length = r.received
                    r.done = True
                    st.ranges.append(_Range(resume, remaining, taddr))
                    plan.append((resume, remaining))
            try:
                for resume, remaining in plan:
                    tconn.send(P.OBJ_PULL, oid.binary(), resume, remaining)
            except P.ConnectionLost:
                self._handle_conn_failure(tconn)

    def _pick_failover_source(self, st: _PullState):
        """A surviving participant, else an untried candidate address."""
        with st.buf_lock:
            for c, a in st.conns.items():
                if not c.closed:
                    return c, a
            candidates = [a for a in st.addrs if a not in st.failed_addrs
                          and a not in st.conns.values()]
        for a in candidates:
            try:
                return self._peer(a), a
            except OSError:
                with st.buf_lock:
                    st.failed_addrs.add(a)
        return None

    def close(self):
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.on_close = None
            c.close()
