"""Peer-to-peer chunked object transfer between hosts.

Ref analog: src/ray/object_manager/ — the reference's per-node
ObjectManager serves 5 MiB chunk pulls directly between raylets
(object_manager.proto, pull_manager.cc) so object payloads never transit
the GCS. Same shape here: every host (each node agent, plus the head on
behalf of its in-process nodes) runs a ``TransferServer`` — a dedicated
TCP listener streaming objects out of the local shm arena in ~1 MiB raw
frames — and an ``ObjectPuller`` that connects straight to a peer's
server and writes arriving chunks into the local arena. The head only
brokers *who pulls from whom* (it hands the destination the source's
transfer address); payload bytes never touch head memory (asserted by
tests via the head's relay-byte counter).

Wire flow (all frames on a direct peer<->peer connection):
    puller -> server   OBJ_PULL (oid)                       one-way
    server -> puller   OBJ_PULL_META (oid, size|-1, meta)   create buffer
    server -> puller   OBJ_PULL_CHUNK hdr + RAW frame  x N  (atomic pair)
    server -> puller   OBJ_PULL_DONE (oid)                  seal + wake

Every buffer mutation happens on the puller's single IO thread, in stream
order — META creates the arena buffer before any chunk of that object can
be dispatched, so there is no allocation/arrival race by construction.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

from . import protocol as P
from .config import get_config
from .ids import ObjectID
from .object_store import ObjectExistsError, ShmObjectStore


class TransferServer:
    """Serves OBJ_PULL requests for objects in local shm arenas.

    ``read_fn(oid) -> (data_memoryview, meta_bytes, release_cb) | None``
    abstracts over "one agent store" vs "the head's local node stores".
    """

    def __init__(self, io: P.IOLoop, read_fn: Callable, host: str = "",
                 advertise_ip: str = ""):
        self._read_fn = read_fn
        self._listener = P.listen_tcp(host or "0.0.0.0", 0)
        port = self._listener.getsockname()[1]
        ip = advertise_ip or P.local_ip()
        self.addr = f"tcp:{ip}:{port}"
        self._io = io
        io.add_listener(self._listener, self._on_accept)

    def _on_accept(self, sock, _addr):
        sock.setsockopt(P.socket.IPPROTO_TCP, P.socket.TCP_NODELAY, 1)
        conn = P.Connection(sock, peer="xfer-client")
        self._io.add_connection(conn, self._on_message)

    def _on_message(self, conn: P.Connection, msg):
        if msg[0] != P.OBJ_PULL:
            return
        # Stream on a side thread: a multi-GiB send must not wedge the IO
        # loop that every other connection on this host shares. Concurrent
        # pulls on one connection are safe: each chunk's header+raw pair is
        # sent atomically (send_with_raw), and the puller writes by the
        # (oid, offset) in each header.
        threading.Thread(target=self._serve_pull, args=(conn, msg[2]),
                         daemon=True).start()

    def _serve_pull(self, conn: P.Connection, oid_bin: bytes):
        oid = ObjectID(oid_bin)
        got = self._read_fn(oid)
        try:
            if got is None:
                conn.send(P.OBJ_PULL_META, oid_bin, -1, b"")
                return
            data, meta, release = got
            try:
                conn.send(P.OBJ_PULL_META, oid_bin, len(data), bytes(meta))
                # ~1 MiB chunks so each typically completes within one
                # receiver recv() buffer, hitting feed()'s zero-copy fast
                # path (protocol.py). Each chunk is written straight from
                # the shm arena view — no serialization copies.
                cs = min(get_config().object_transfer_chunk_bytes, 1 << 20)
                for off in range(0, len(data), cs):
                    end = min(off + cs, len(data))
                    conn.send_with_raw(P.OBJ_PULL_CHUNK, oid_bin, off,
                                       raw=data[off:end])
                conn.send(P.OBJ_PULL_DONE, oid_bin)
            finally:
                release()
        except P.ConnectionLost:
            pass

    def close(self):
        try:
            self._io.remove(self._listener)
            self._listener.close()
        except OSError:
            pass


class _PullState:
    __slots__ = ("buf", "done", "error", "conn", "buf_lock")

    def __init__(self, conn: P.Connection):
        self.buf = None
        self.done = threading.Event()
        self.error: Optional[str] = None
        self.conn = conn
        # serializes chunk writes against the abort path's buf=None +
        # arena delete — a copy into a freed (and possibly reallocated)
        # arena slot would corrupt another object
        self.buf_lock = threading.Lock()


class ObjectPuller:
    """Pulls objects from peers' TransferServers into a local shm store."""

    def __init__(self, io: P.IOLoop, store: ShmObjectStore):
        self._io = io
        self._store = store
        self._conns: Dict[str, P.Connection] = {}
        self._pending: Dict[ObjectID, _PullState] = {}
        # per-connection (oid, offset) the next RAW frame belongs to —
        # send_with_raw guarantees the raw frame directly follows its header
        self._expect: Dict[P.Connection, Tuple[ObjectID, int]] = {}
        self._lock = threading.Lock()

    def _peer(self, addr: str) -> P.Connection:
        with self._lock:
            conn = self._conns.get(addr)
            if conn is not None and not conn.closed:
                return conn
        sock = P.connect_addr(addr)
        conn = P.Connection(sock, peer=f"xfer:{addr}")
        conn.on_close = self._on_conn_close
        self._io.add_connection(conn, self._on_message)
        with self._lock:
            self._conns[addr] = conn
        return conn

    def pull(self, oid: ObjectID, peer_addr: str,
             timeout: float = 120.0) -> bool:
        """Blocking: fetch `oid` from the peer into the local store."""
        if self._store.contains(oid):
            return True
        try:
            conn = self._peer(peer_addr)
        except OSError:
            return False
        with self._lock:
            st = self._pending.get(oid)
            if st is not None:
                leader = False
            else:
                st = self._pending[oid] = _PullState(conn)
                leader = True
        if not leader:  # another thread is already pulling this object
            st.done.wait(timeout)
            return st.error is None and self._store.contains(oid)
        try:
            st.conn.send(P.OBJ_PULL, oid.binary())
            if not st.done.wait(timeout):
                st.error = "pull timed out"
        except P.ConnectionLost as e:
            st.error = str(e)
        finally:
            with self._lock:
                self._pending.pop(oid, None)
            if st.error is not None and not self._store.contains(oid):
                # never leave a created-but-unsealed entry behind: it would
                # poison every retry (create fails on existing ids) while
                # readers block forever on an object that never seals.
                # buf_lock: an in-flight chunk copy must finish before the
                # arena slot is freed.
                with st.buf_lock:
                    st.buf = None
                    self._store.delete(oid)
            st.done.set()
        return st.error is None

    # ---- everything below runs on the IO thread, in stream order ----

    def _on_message(self, conn: P.Connection, msg):
        mt = msg[0]
        if mt == P.OBJ_PULL_META:
            oid, size, meta = ObjectID(msg[2]), msg[3], msg[4]
            with self._lock:
                st = self._pending.get(oid)
            if st is None:
                return
            if size < 0:
                st.error = "object not on peer"
                st.done.set()
                return
            try:
                st.buf = self._store.create(oid, size, len(meta))
            except ObjectExistsError:
                if self._store.contains(oid):  # already sealed locally
                    st.done.set()
                    return
                # unsealed leftover from a failed earlier pull: reclaim
                self._store.delete(oid)
                try:
                    st.buf = self._store.create(oid, size, len(meta))
                except Exception as e:  # noqa: BLE001
                    st.error = f"create failed: {e}"
                    st.done.set()
                    return
            except Exception as e:  # noqa: BLE001 — e.g. store full
                st.error = f"create failed: {e}"
                st.done.set()
                return
            st.buf[size:] = meta
            if size == 0:
                st.buf = None
                self._store.seal(oid)
                st.done.set()
        elif mt == P.OBJ_PULL_CHUNK:
            self._expect[conn] = (ObjectID(msg[2]), msg[3])
        elif mt == P.RAW_FRAME:
            exp = self._expect.pop(conn, None)
            if exp is None:
                return
            oid, off = exp
            payload = msg[2]
            with self._lock:
                st = self._pending.get(oid)
            if st is not None:
                with st.buf_lock:
                    buf = st.buf
                    if buf is not None:
                        import numpy as np

                        # vectorized copy into the arena (~2x a memoryview
                        # slice assignment; this is the receive-side hot
                        # loop). payload may be a memoryview into the recv
                        # buffer (feed()'s zero-copy fast path) — consumed
                        # before returning.
                        np.copyto(
                            np.frombuffer(buf[off:off + len(payload)],
                                          np.uint8),
                            np.frombuffer(payload, np.uint8))
        elif mt == P.OBJ_PULL_DONE:
            oid = ObjectID(msg[2])
            with self._lock:
                st = self._pending.get(oid)
            if st is not None and st.buf is not None:
                st.buf = None  # drop the arena view before sealing
                try:
                    self._store.seal(oid)
                except KeyError:
                    st.error = "seal failed"
                st.done.set()

    def _on_conn_close(self, conn: P.Connection):
        """Peer died mid-pull: fail its pending pulls now, not at timeout."""
        with self._lock:
            stale = [st for st in self._pending.values() if st.conn is conn]
        for st in stale:
            st.error = "transfer connection lost"
            st.done.set()

    def close(self):
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            c.on_close = None
            c.close()
