"""Resource model: fixed-point resource vectors and per-node accounting.

Analog of the reference's scheduling resource model
(src/ray/common/scheduling/cluster_resource_data.h — ``ResourceRequest``,
``TaskResourceInstances``, ``NodeResources``; fixed_point.h). Resources are
fixed-point (1/10000 granularity) so fractional accelerators account exactly.

TPU-first: ``TPU`` is a first-class resource alongside CPU/memory, and nodes
carry TPU topology labels (accelerator type, slice name, worker index within
the slice, ICI coordinates) so placement groups can do ICI-topology-aware
STRICT_PACK — a pod-slice bundle maps to a contiguous slice of the torus.
The reference snapshot has no TPU resource at all (SURVEY.md §2.3); its GPU
handling lives in python/ray/_private/resource_spec.py:303 and
src/ray/common/scheduling/*.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, Optional

GRANULARITY = 10000

CPU = "CPU"
GPU = "GPU"
TPU = "TPU"
MEMORY = "memory"
OBJECT_STORE_MEMORY = "object_store_memory"

PREDEFINED = (CPU, GPU, TPU, MEMORY, OBJECT_STORE_MEMORY)


def _to_fp(v: float) -> int:
    return round(v * GRANULARITY)


def _from_fp(v: int) -> float:
    return v / GRANULARITY


class ResourceSet:
    """An immutable-ish map of resource name -> fixed-point quantity."""

    __slots__ = ("_fp",)

    def __init__(self, resources: Optional[Dict[str, float]] = None, _fp=None):
        if _fp is not None:
            self._fp = _fp
        else:
            self._fp = {}
            if resources:
                for k, v in resources.items():
                    if v < 0:
                        raise ValueError(f"Negative resource {k}={v}")
                    fp = _to_fp(v)
                    if fp:
                        self._fp[k] = fp

    def get(self, name: str) -> float:
        return _from_fp(self._fp.get(name, 0))

    def get_fp(self, name: str) -> int:
        return self._fp.get(name, 0)

    def names(self) -> Iterable[str]:
        return self._fp.keys()

    def is_empty(self) -> bool:
        return not self._fp

    def to_dict(self) -> Dict[str, float]:
        return {k: _from_fp(v) for k, v in self._fp.items()}

    def covers(self, request: "ResourceSet") -> bool:
        """True if self has at least the quantities in `request`."""
        for k, v in request._fp.items():
            if self._fp.get(k, 0) < v:
                return False
        return True

    def add(self, other: "ResourceSet") -> "ResourceSet":
        fp = dict(self._fp)
        for k, v in other._fp.items():
            fp[k] = fp.get(k, 0) + v
        return ResourceSet(_fp=fp)

    def subtract(self, other: "ResourceSet") -> "ResourceSet":
        fp = dict(self._fp)
        for k, v in other._fp.items():
            nv = fp.get(k, 0) - v
            if nv < 0:
                raise ValueError(f"Resource {k} would go negative")
            if nv:
                fp[k] = nv
            else:
                fp.pop(k, None)
        return ResourceSet(_fp=fp)

    def scaled(self, factor: float) -> "ResourceSet":
        return ResourceSet(_fp={k: round(v * factor) for k, v in self._fp.items()})

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._fp == other._fp

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"


@dataclass
class TpuTopology:
    """TPU topology attached to a node.

    ``coords`` is this host's position in the slice's host grid; ``chips``
    the number of chips local to the host. STRICT_PACK bundle scheduling uses
    these to pick hosts forming a contiguous ICI sub-torus.
    """

    accelerator_type: str = ""  # e.g. "v5p-64"
    slice_name: str = ""
    worker_index: int = 0
    num_workers: int = 1
    chips_per_host: int = 4
    coords: tuple = (0, 0, 0)

    @property
    def generation(self) -> str:
        return self.accelerator_type.split("-")[0] if self.accelerator_type else ""


@dataclass
class NodeResources:
    """Total and available resources on one node, plus labels.

    ``version`` increments on every availability change; the native
    scheduler core uses it to re-sync only dirty nodes before a
    placement decision."""

    node_id: object = None
    total: ResourceSet = field(default_factory=ResourceSet)
    available: ResourceSet = field(default_factory=ResourceSet)
    labels: Dict[str, str] = field(default_factory=dict)
    tpu: Optional[TpuTopology] = None
    version: int = 0
    # change listeners (native scheduler dirty tracking); excluded from
    # pickling — a node's resources cross the wire at registration
    listeners: list = field(default_factory=list, repr=False,
                            compare=False)

    def __getstate__(self):
        state = dict(self.__dict__)
        state["listeners"] = []
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)

    def is_feasible(self, request: ResourceSet) -> bool:
        return self.total.covers(request)

    def is_available(self, request: ResourceSet) -> bool:
        return self.available.covers(request)

    def allocate(self, request: ResourceSet):
        self.available = self.available.subtract(request)
        self.version += 1
        for cb in self.listeners:
            cb()

    def release(self, request: ResourceSet):
        # Validate BEFORE assigning: a double-release must not leave the
        # inflated availability behind (with version/listeners skipped,
        # the native scheduler table would silently disagree too).
        released = self.available.add(request)
        for k in released.names():
            if released.get_fp(k) > self.total.get_fp(k):
                raise ValueError(f"Released more {k} than total on node")
        self.available = released
        self.version += 1
        for cb in self.listeners:
            cb()

    def utilization(self) -> float:
        """Max utilization across critical resources — drives hybrid policy."""
        util = 0.0
        for k in (CPU, GPU, TPU, MEMORY):
            tot = self.total.get_fp(k)
            if tot:
                util = max(util, 1.0 - self.available.get_fp(k) / tot)
        return util


def detect_node_resources(num_cpus=None, num_tpus=None, memory=None,
                          object_store_memory=None, resources=None,
                          labels=None) -> NodeResources:
    """Autodetect this host's resources (analog of resource_spec.py).

    TPU detection: query jax for local device count when a TPU platform is
    present; honor explicit overrides first.
    """
    import os

    res = dict(resources or {})
    if num_cpus is None:
        num_cpus = os.cpu_count() or 1
    res[CPU] = num_cpus
    if num_tpus is None:
        num_tpus = _detect_tpu_chips()
    if num_tpus:
        res[TPU] = num_tpus
    if memory is None:
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemAvailable"):
                        memory = int(line.split()[1]) * 1024 // 2
                        break
        except OSError:
            memory = 4 * 1024 * 1024 * 1024
    res[MEMORY] = memory
    if object_store_memory is not None:
        res[OBJECT_STORE_MEMORY] = object_store_memory
    rs = ResourceSet(res)
    return NodeResources(total=rs, available=rs, labels=dict(labels or {}),
                         tpu=detect_tpu_topology())


_tpu_chips_cache = None

# Chips per host by TPU generation (v4/v5p have 4 chips per host; v5e/v6e
# hosts in the common 8-chip topology expose 8; override with TPU_CHIPS).
_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5p": 4, "v5e": 8,
                   "v5litepod": 8, "v6e": 8}


def _detect_tpu_chips() -> int:
    """Detect local TPU chips from the environment WITHOUT initializing any
    JAX backend (backend init grabs the accelerator and can block — the
    runtime must never do that as a side effect of ``init()``)."""
    global _tpu_chips_cache
    if _tpu_chips_cache is not None:
        return _tpu_chips_cache
    import os

    if os.environ.get("TPU_CHIPS"):
        _tpu_chips_cache = int(os.environ["TPU_CHIPS"])
        return _tpu_chips_cache
    topo = os.environ.get("TPU_TOPOLOGY", "")  # e.g. "2x2x1" (chips)
    if topo:
        try:
            n = 1
            for part in topo.lower().split("x"):
                n *= int(part)
            hosts = len(os.environ.get("TPU_WORKER_HOSTNAMES",
                                       "localhost").split(","))
            _tpu_chips_cache = max(1, n // max(1, hosts))
            return _tpu_chips_cache
        except ValueError:
            pass
    acc = os.environ.get("TPU_ACCELERATOR_TYPE", "")  # e.g. "v5p-64"
    if acc:
        gen = acc.split("-")[0].lower()
        _tpu_chips_cache = _CHIPS_PER_HOST.get(gen, 4)
        return _tpu_chips_cache
    # Single-chip tunneled dev environments (axon) expose the generation.
    if os.environ.get("PALLAS_AXON_TPU_GEN"):
        _tpu_chips_cache = 1
        return _tpu_chips_cache
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if "tpu" in platforms:
        _tpu_chips_cache = 4
        return _tpu_chips_cache
    _tpu_chips_cache = 0
    return 0


def detect_tpu_topology() -> Optional[TpuTopology]:
    import os

    acc = os.environ.get("TPU_ACCELERATOR_TYPE", "")
    if not acc:
        from .config import get_config

        acc = get_config().tpu_accelerator_type
    if not acc and not _detect_tpu_chips():
        return None
    hostname = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return TpuTopology(
        accelerator_type=acc or "unknown",
        slice_name=os.environ.get("TPU_NAME", ""),
        worker_index=int(os.environ.get("TPU_WORKER_ID", "0") or 0),
        num_workers=len(hostname.split(",")) if hostname else 1,
        chips_per_host=_detect_tpu_chips() or 4,
    )
