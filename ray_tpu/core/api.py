"""Public core API: init/shutdown/remote/get/put/wait/kill/cancel.

Analog of python/ray/_private/worker.py (ray.init :1125, ray.get :2440,
ray.put :2569, ray.wait :2632), python/ray/remote_function.py
(RemoteFunction._remote :246) and python/ray/actor.py (ActorClass :384,
ActorHandle :1025) in the reference.
"""

from __future__ import annotations

import atexit
import functools
import inspect
import os
import threading
import time
import uuid
from typing import Any, List, Optional, Sequence, Tuple, Union

from .context import CoreContext, get_context, get_context_if_exists, \
    set_context
from .head import Head
from .ids import ActorID, PlacementGroupID
from .object_ref import ObjectRef
from .task_spec import Bundle, PlacementGroupSpec, SchedulingStrategy

_head: Optional[Head] = None
_init_lock = threading.RLock()


def is_initialized() -> bool:
    return get_context_if_exists() is not None


def init(*, num_cpus: Optional[int] = None, num_tpus: Optional[int] = None,
         object_store_memory: Optional[int] = None, resources: dict = None,
         labels: dict = None, _system_config: dict = None,
         ignore_reinit_error: bool = False, log_to_driver: bool = True,
         namespace: str = "", address: Optional[str] = None,
         session_dir: Optional[str] = None,
         runtime_env: Optional[dict] = None) -> "RuntimeInfo":
    """Start (or connect to) a runtime.

    With no address, starts an embedded head (GCS-lite + one node) in this
    process — the reference's ``ray.init()`` local mode with real worker
    processes. ``address`` may name an existing head socket to attach to
    (multi-driver; the reference's ``ray.init(address=...)``).

    ``session_dir`` pins the session directory. Reusing a previous
    session's directory restores the head's durable control-plane state
    (KV, named actors, placement groups) from its write-ahead log — the
    reference's GCS restart from Redis (gcs fault tolerance docs;
    src/ray/gcs/store_client/).
    """
    global _head
    with _init_lock:
        if is_initialized():
            if ignore_reinit_error:
                return RuntimeInfo(get_context(), _head)
            raise RuntimeError("ray_tpu.init() called twice; use "
                              "ignore_reinit_error=True")
        from .config import get_config, reset_config

        reset_config()
        get_config().apply_overrides(_system_config)
        if address and address.startswith("tcp:"):
            # Remote driver (the reference's Ray Client, python/ray/util/
            # client/ — but as a full peer): an in-process node agent
            # joins the cluster over TCP, giving this host its own object
            # store and worker pool; the driver then runs node-local with
            # no proxying of object ops.
            from .node_agent import NodeAgent

            agent = NodeAgent(address, num_cpus=num_cpus or 0,
                              num_tpus=num_tpus or 0)
            threading.Thread(target=agent.run_forever, daemon=True,
                             name="driver-node-agent").start()
            os.environ["RAY_TPU_NODE_IP"] = agent.node_ip
            try:
                ctx = CoreContext(head_addr=address,
                                  session_dir=agent.session_dir,
                                  node_idx=agent.node_idx, is_driver=True)
            finally:
                os.environ.pop("RAY_TPU_NODE_IP", None)
            ctx._local_agent = agent  # torn down with the context
            set_context(ctx)
            if log_to_driver:
                _mirror_worker_logs(ctx)
            _apply_job_runtime_env(ctx, runtime_env)
            return RuntimeInfo(ctx, None)
        if address:
            session_dir = os.path.dirname(address.replace("unix:", ""))
            ctx = CoreContext(head_addr=address, session_dir=session_dir,
                              node_idx=0, is_driver=True)
            set_context(ctx)
            if log_to_driver:
                _mirror_worker_logs(ctx)
            _apply_job_runtime_env(ctx, runtime_env)
            return RuntimeInfo(ctx, None)
        session_name = uuid.uuid4().hex[:10]
        if session_dir is None:
            session_dir = f"/tmp/ray_tpu/session_{session_name}"
        os.makedirs(session_dir, exist_ok=True)
        from ray_tpu import usage_stats as _usage

        _usage.print_usage_stats_notice()
        _usage.record_library_usage("core")
        head = Head(session_dir, session_name)
        head.add_node(num_cpus=num_cpus, num_tpus=num_tpus,
                      object_store_memory=object_store_memory,
                      resources=resources, labels=labels)
        head.start()
        ctx = CoreContext(head_addr=head.addr, session_dir=session_dir,
                          node_idx=0, is_driver=True)
        set_context(ctx)
        if log_to_driver:
            _mirror_worker_logs(ctx)
        _apply_job_runtime_env(ctx, runtime_env)
        _head = head
        atexit.register(shutdown)
        return RuntimeInfo(ctx, head)


def _apply_job_runtime_env(ctx: CoreContext, runtime_env: Optional[dict]):
    """Job-level default env for every task/actor (reference:
    ray.init(runtime_env=...))."""
    if not runtime_env:
        return
    from ray_tpu.runtime_env import upload, validate

    ctx.job_runtime_env = upload(ctx, validate(runtime_env))


def _mirror_worker_logs(ctx: CoreContext):
    """Print worker log lines in the driver, prefixed with their source
    (reference: worker.py print_logs fed by log_monitor.py over pubsub)."""
    import sys as _sys

    def _print(data):
        src = data.get("source", "?")
        for line in data.get("lines", ()):
            print(f"({src}) {line}", file=_sys.stderr)

    ctx.subscribe("logs", _print)


class RuntimeInfo:
    def __init__(self, ctx: CoreContext, head: Optional[Head]):
        self.ctx = ctx
        self.head = head

    @property
    def address(self) -> str:
        return self.ctx.head_addr

    @property
    def session_dir(self) -> str:
        return self.ctx.session_dir


def shutdown():
    global _head
    with _init_lock:
        ctx = get_context_if_exists()
        if ctx is not None:
            try:  # usage report file sink (ref: usage_lib's reporter)
                from ray_tpu import usage_stats as _usage

                _usage.write_report(ctx.session_dir)
            except Exception:
                pass
            try:
                ctx.shutdown()
            finally:
                set_context(None)
        if _head is not None:
            try:
                _head.shutdown()
            finally:
                _head = None
        try:
            atexit.unregister(shutdown)
        except Exception:
            pass


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None):
    single = isinstance(refs, ObjectRef)
    lst = [refs] if single else list(refs)
    for r in lst:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRef(s), got {type(r)}")
    vals = get_context().get(lst, timeout)
    return vals[0] if single else vals


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    return get_context().put(value)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True
         ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds number of refs")
    return get_context().wait(list(refs), num_returns, timeout, fetch_local)


def cancel(ref: ObjectRef, *, force: bool = False):
    get_context().cancel(ref, force)


def kill(actor: "ActorHandle", *, no_restart: bool = True):
    get_context().kill_actor(actor._actor_id, no_restart)


# ============================================================ remote functions


class RemoteFunction:
    def __init__(self, fn, *, num_cpus=None, num_tpus=None, num_returns=1,
                 resources=None, max_retries=None, retry_exceptions=False,
                 scheduling_strategy=None, name=None, runtime_env=None,
                 prefetch_args=True):
        from ray_tpu.runtime_env import validate as _validate_env

        self._fn = fn
        self._num_returns = num_returns
        self._resources = _resource_dict(num_cpus, num_tpus, resources,
                                         default_cpus=1)
        self._max_retries = max_retries
        self._retry_exceptions = retry_exceptions
        self._strategy = scheduling_strategy
        self._name = name or getattr(fn, "__name__", "task")
        self._runtime_env = _validate_env(runtime_env)
        self._uploaded_env = None  # dirs packed/uploaded once, lazily
        # False opts this task's by-ref args out of dispatch-time
        # PREFETCH_HINT speculation (r17; the shuffle's hint A/B knob)
        self._prefetch_args = prefetch_args
        functools.update_wrapper(self, fn)

    def _resolved_env(self):
        if self._runtime_env is None:
            return None
        if self._uploaded_env is None:
            from ray_tpu.runtime_env import upload

            self._uploaded_env = upload(get_context(), self._runtime_env)
        return self._uploaded_env

    def __call__(self, *a, **k):
        raise TypeError(
            f"Remote function '{self._name}' cannot be called directly; use "
            f"'{self._name}.remote()' (or '.func()' to call the plain "
            "function).")

    @property
    def func(self):
        return self._fn

    def remote(self, *args, **kwargs):
        refs = get_context().submit_task(
            self._fn, args, kwargs,
            num_returns=self._num_returns,
            resources=self._resources,
            strategy=_to_strategy(self._strategy),
            max_retries=self._max_retries,
            retry_exceptions=self._retry_exceptions,
            name=self._name,
            runtime_env=self._resolved_env(),
            prefetch_args=self._prefetch_args)
        return refs[0] if self._num_returns == 1 else refs

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node instead of executing (reference:
        ray.dag, dag_node.py:23)."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(
            num_returns=self._num_returns,
            resources=None, max_retries=self._max_retries,
            retry_exceptions=self._retry_exceptions,
            scheduling_strategy=self._strategy, name=self._name,
            runtime_env=self._runtime_env,
            prefetch_args=self._prefetch_args)
        merged.update(opts)
        rf = RemoteFunction(self._fn, **{k: v for k, v in merged.items()
                                         if k in inspect.signature(
                                             RemoteFunction.__init__
                                         ).parameters})
        if "resources" not in opts and "num_cpus" not in opts \
                and "num_tpus" not in opts:
            rf._resources = self._resources
        return rf


# ============================================================ actors


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns=1,
                 task_name: str = ""):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._task_name = task_name

    def remote(self, *args, **kwargs):
        refs = get_context().submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs,
            num_returns=self._num_returns,
            max_retries=self._handle._max_task_retries,
            name=self._task_name)
        return refs[0] if self._num_returns == 1 else refs

    def options(self, num_returns=1, name: str = "", **_):
        """``name`` relabels the submitted task for observability (the
        func key of phase histograms / `summary tasks` / the straggler
        detector) without changing which method runs — pipeline stages
        submit ``fwd`` as ``stage{k}.fwd`` this way (r15)."""
        return ActorMethod(self._handle, self._name, num_returns,
                           task_name=name)

    def __call__(self, *a, **k):
        raise TypeError(f"Actor method '{self._name}' must be called with "
                        f".remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID, method_names,
                 max_task_retries: int = 0):
        self._actor_id = actor_id
        self._method_names = set(method_names)
        self._max_task_retries = max_task_retries

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if self._method_names and name not in self._method_names:
            raise AttributeError(
                f"actor has no method '{name}'")
        return ActorMethod(self, name)

    def __reduce__(self):
        return (ActorHandle,
                (self._actor_id, self._method_names, self._max_task_retries))

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()})"


class ActorClass:
    def __init__(self, cls, *, num_cpus=None, num_tpus=None, resources=None,
                 max_restarts=0, max_task_retries=0, max_concurrency=1,
                 name=None, scheduling_strategy=None, lifetime=None,
                 runtime_env=None):
        from ray_tpu.runtime_env import validate as _validate_env

        self._runtime_env = _validate_env(runtime_env)
        self._uploaded_env = None
        self._cls = cls
        self._resources = _resource_dict(num_cpus, num_tpus, resources,
                                         default_cpus=0)
        self._max_restarts = max_restarts
        self._max_task_retries = max_task_retries
        self._max_concurrency = max_concurrency
        self._name = name
        self._strategy = scheduling_strategy
        self._lifetime = lifetime

    def __call__(self, *a, **k):
        raise TypeError(
            f"Actor class {self._cls.__name__} cannot be instantiated "
            f"directly; use {self._cls.__name__}.remote()")

    def remote(self, *args, **kwargs) -> ActorHandle:
        ctx = get_context()
        renv = None
        if self._runtime_env is not None:
            if self._uploaded_env is None:
                from ray_tpu.runtime_env import upload

                self._uploaded_env = upload(ctx, self._runtime_env)
            renv = self._uploaded_env
        actor_id = ctx.create_actor(
            self._cls, args, kwargs,
            resources=self._resources,
            max_restarts=self._max_restarts,
            max_concurrency=self._max_concurrency,
            name=self._name or "",
            strategy=_to_strategy(self._strategy),
            max_task_retries=self._max_task_retries,
            runtime_env=renv)
        return ActorHandle(actor_id, _public_methods(self._cls),
                           self._max_task_retries)

    def bind(self, *args, **kwargs):
        """Build a lazy actor DAG node (reference: ray.dag class_node.py)."""
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs)

    def options(self, **opts) -> "ActorClass":
        base = dict(num_cpus=None, num_tpus=None, resources=None,
                    max_restarts=self._max_restarts,
                    max_task_retries=self._max_task_retries,
                    max_concurrency=self._max_concurrency, name=self._name,
                    scheduling_strategy=self._strategy,
                    lifetime=self._lifetime,
                    runtime_env=self._runtime_env)
        base.update(opts)
        ac = ActorClass(self._cls, **base)
        if "resources" not in opts and "num_cpus" not in opts \
                and "num_tpus" not in opts:
            ac._resources = self._resources
        return ac


def _public_methods(cls):
    return [n for n, m in inspect.getmembers(cls)
            if callable(m) and not n.startswith("_")]


def get_actor(name: str) -> ActorHandle:
    aid = get_context().get_named_actor(name)
    if aid is None:
        raise ValueError(f"no actor named '{name}'")
    return ActorHandle(aid, set())


# ============================================================ remote decorator


def remote(*args, **kwargs):
    """``@remote`` / ``@remote(num_cpus=..., num_tpus=...)`` for functions
    and classes (the reference's ``ray.remote``, python/ray/__init__.py)."""

    def decorate(obj):
        if inspect.isclass(obj):
            allowed = ("num_cpus", "num_tpus", "resources", "max_restarts",
                       "max_task_retries", "max_concurrency", "name",
                       "scheduling_strategy", "lifetime", "runtime_env")
            return ActorClass(obj, **{k: v for k, v in kwargs.items()
                                      if k in allowed})
        allowed = ("num_cpus", "num_tpus", "num_returns", "resources",
                   "max_retries", "retry_exceptions", "scheduling_strategy",
                   "name", "runtime_env", "prefetch_args")
        return RemoteFunction(obj, **{k: v for k, v in kwargs.items()
                                      if k in allowed})

    if len(args) == 1 and callable(args[0]) and not kwargs:
        return decorate(args[0])
    if args:
        raise TypeError("@remote takes keyword arguments only")
    return decorate


def _resource_dict(num_cpus, num_tpus, resources, default_cpus):
    res = dict(resources or {})
    res["CPU"] = num_cpus if num_cpus is not None else \
        res.get("CPU", default_cpus)
    if num_tpus is not None:
        res["TPU"] = num_tpus
    return {k: v for k, v in res.items() if v}


def _to_strategy(s) -> SchedulingStrategy:
    if s is None:
        return SchedulingStrategy()
    if isinstance(s, SchedulingStrategy):
        return s
    if isinstance(s, str):
        return SchedulingStrategy(kind=s)
    return s


# ============================================================ placement groups


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID):
        self.id = pg_id

    def ready(self, timeout: float = 30.0) -> bool:
        ctx = get_context()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            from . import protocol as P

            # poll head state via node info channel (cheap)
            state = _pg_state(self.id)
            if state == "CREATED":
                return True
            if state == "REMOVED":
                return False
            time.sleep(0.02)
        return False

    def wait(self, timeout: float = 30.0) -> bool:
        return self.ready(timeout)

    @property
    def bundle_specs(self):
        return []

    def __reduce__(self):
        return (PlacementGroup, (self.id,))


def _pg_state(pg_id: PlacementGroupID) -> str:
    # The embedded head is in-process for the driver; attached drivers query
    # over the wire via KV (head mirrors state there).
    from .api import _head

    if _head is not None:
        return _head.pg_state(pg_id)
    data = get_context().kv_get("pg_state", pg_id.hex())
    return data.decode() if data else "PENDING"


def placement_group(bundles: List[dict], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    from .serialization import dumps
    from . import protocol as P

    ctx = get_context()
    spec = PlacementGroupSpec(
        pg_id=PlacementGroupID.of(ctx.job_id),
        bundles=[Bundle(resources=b) for b in bundles],
        strategy=strategy, name=name, job_id=ctx.job_id)
    ctx.head.call(P.CREATE_PG, dumps(spec), timeout=60)
    return PlacementGroup(spec.pg_id)


def remove_placement_group(pg: PlacementGroup):
    from . import protocol as P

    get_context().head.call(P.REMOVE_PG, pg.id.binary(), timeout=30)


def placement_group_table(pg: PlacementGroup) -> dict:
    from .api import _head

    if _head is None:
        return {}
    return {
        "state": _head.pg_state(pg.id),
        "placement": _head.pg_placement(pg.id),
    }


class PlacementGroupSchedulingStrategy(SchedulingStrategy):
    def __init__(self, placement_group: PlacementGroup,
                 placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        super().__init__(
            kind="PLACEMENT_GROUP",
            placement_group_id=placement_group.id,
            bundle_index=placement_group_bundle_index,
            capture_child_tasks=placement_group_capture_child_tasks)


class NodeAffinitySchedulingStrategy(SchedulingStrategy):
    def __init__(self, node_id, soft: bool = False):
        super().__init__(kind="NODE_AFFINITY", node_id=str(node_id),
                         soft=soft)


def get_tpu_ids() -> List[int]:
    """Chip indices assigned to the current task/actor's lease (the
    reference's ``ray.get_gpu_ids()``, worker.py:888). Empty outside a
    TPU-resourced task."""
    return list(get_context().assigned_tpu_ids)


def nodes() -> list:
    return get_context().node_info()


def object_locations(ref: ObjectRef) -> dict:
    """Holder set of a plasma-resident object from the head's object
    directory (ref parity: ray.experimental.get_object_locations).
    Returns {"holders": [node_idx, ...], "addrs": [transfer_addr, ...],
    "size": int, "spilled": str}; ``holders`` and ``addrs`` are parallel
    — ``addrs[i]`` is the transfer server serving ``holders[i]`` ('' when
    unreachable), so head-local holders share one address."""
    from . import protocol as P

    holders, addrs, size, spilled = get_context().head.call(
        P.OBJ_LOCATION_LOOKUP, ref.id.binary(), timeout=30)
    return {"holders": holders, "addrs": addrs, "size": size,
            "spilled": spilled}


def warm_object(ref: ObjectRef, node_idx: int = -1, *,
                wait: bool = False) -> int:
    """Warm a plasma-resident object onto node(s) before any consumer
    task/actor is placed (r14; the proactive face of the reference
    PullManager's prefetch role). Fires the head's OBJECT_WARM: every
    targeted node missing the object gets a prefetch-flagged pull
    through the broadcast-aware planner — concurrent warms of one
    object form the r9 cooperative relay tree, and a later consumer's
    get() joins the in-flight pull instead of starting cold. The serve
    controller uses this to ship deployment weights at scale-up
    decision time, before the new replicas even exist.

    ``node_idx`` -1 targets every alive remote node. Fire-and-forget by
    default; ``wait=True`` blocks for the head's ack and returns how
    many pulls were issued (0 = every target already holds it, or
    prefetching is disabled/capped)."""
    from . import protocol as P

    ctx = get_context()
    if wait:
        (issued,) = ctx.head.call(P.OBJECT_WARM, ref.id.binary(),
                                  int(node_idx), timeout=30)
        return int(issued)
    # Never block on a head outage: a ReconnectingConnection PARKS
    # writes for the reconnect window, and fire-and-forget callers (the
    # serve controller decides scale-ups under its reconcile lock) must
    # not stall on speculation. Skipping just loses the warm-up.
    if not ctx.head.is_attached():
        return 0
    try:
        ctx.head.send(P.OBJECT_WARM, ref.id.binary(), int(node_idx))
    except P.ConnectionLost:
        pass  # speculation only: consumers still demand-pull
    return 0


def drain_node(node_idx: int, *, timeout: float = 30.0) -> bool:
    """Begin a GRACEFUL drain of a node (r16; reference: the
    NodeManager ``DrainNode`` RPC behind the autoscaler's planned
    scale-down). The head immediately stops granting leases /
    placements / prefetches onto the node, replicates its sole-copy
    objects to survivors, and publishes ``node_draining`` so running
    workloads (e.g. ``train.Pipeline`` stage migration) move their work
    off; once every in-flight lease completes — or ``drain_deadline_s``
    passes — the node is removed with the deliberate ``SHUTDOWN_NODE``
    (``node_drained`` / ``drain_forced`` cluster events). Returns True
    when the drain was started (or already in progress); False for an
    unknown/dead node or the head's bootstrap node (node 0 — draining
    it would escalate to removing the head host's own arena).
    Non-blocking: poll ``state.list_nodes`` for the ``draining`` flag /
    node removal."""
    from . import protocol as P

    (ok,) = get_context().head.call(P.DRAIN_NODE, int(node_idx),
                                    timeout=timeout)
    return bool(ok)


def cluster_resources() -> dict:
    total: dict = {}
    for n in nodes():
        for k, v in n["resources_total"].items():
            total[k] = total.get(k, 0) + v
    return total


def available_resources() -> dict:
    total: dict = {}
    for n in nodes():
        for k, v in n["resources_available"].items():
            total[k] = total.get(k, 0) + v
    return total
