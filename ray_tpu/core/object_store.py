"""Python client for the native shared-memory object store.

Wraps ray_tpu/native/shm_store.cc (the plasma analog —
src/ray/object_manager/plasma/client.cc in the reference) via ctypes. The
client maps the segment once; object payloads are read/written through
zero-copy memoryviews over that mapping. Serialization uses pickle protocol 5
with out-of-band buffers so numpy / jax host arrays round-trip without extra
copies.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import pickle
import threading
import time
import weakref
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from .ids import ObjectID

_ID_SIZE = 20


class ObjectStoreFullError(Exception):
    pass


class ObjectExistsError(Exception):
    pass


def _load_lib():
    from ray_tpu.native.build import lib_path

    lib = ctypes.CDLL(lib_path("libshm_store.so"))
    lib.shm_store_create.restype = ctypes.c_void_p
    lib.shm_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.shm_store_attach.restype = ctypes.c_void_p
    lib.shm_store_attach.argtypes = [ctypes.c_char_p]
    lib.shm_store_detach.argtypes = [ctypes.c_void_p]
    lib.shm_store_destroy.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_create_object.restype = ctypes.c_int64
    lib.shm_store_create_object.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
    lib.shm_store_seal.restype = ctypes.c_int
    lib.shm_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_get.restype = ctypes.c_int
    lib.shm_store_get.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)]
    lib.shm_store_contains.restype = ctypes.c_int
    lib.shm_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_release.restype = ctypes.c_int
    lib.shm_store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_delete.restype = ctypes.c_int
    lib.shm_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.shm_store_evict.restype = ctypes.c_int
    lib.shm_store_evict.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_int]
    lib.shm_store_bytes_in_use.restype = ctypes.c_uint64
    lib.shm_store_bytes_in_use.argtypes = [ctypes.c_void_p]
    lib.shm_store_capacity.restype = ctypes.c_uint64
    lib.shm_store_capacity.argtypes = [ctypes.c_void_p]
    lib.shm_store_num_objects.restype = ctypes.c_uint64
    lib.shm_store_num_objects.argtypes = [ctypes.c_void_p]
    lib.shm_store_list.restype = ctypes.c_int
    lib.shm_store_list.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int]
    lib.shm_store_memory_stats.restype = None
    lib.shm_store_memory_stats.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
    return lib


_lib = None
_lib_lock = threading.Lock()


def get_lib():
    global _lib
    if _lib is None:
        with _lib_lock:
            if _lib is None:
                _lib = _load_lib()
    return _lib


class PartialObject:
    """Chunk-availability map for an object whose pull is in progress.

    The cooperative-broadcast relay path (object_transfer.py): while an
    ``ObjectPuller`` streams chunks of an object into a created-but-
    unsealed arena buffer, this same host's ``TransferServer`` may
    already be re-serving those chunks to downstream pullers. The puller
    ``mark()``s each byte range as it lands; a relay ``wait_covered()``s
    the next range it needs and ``read()``s it out. Chunks may land at
    arbitrary offsets (multi-source striped upstream pulls), so
    availability is a set of merged disjoint intervals, not a high-water
    mark.

    Lifecycle: ``open`` while the pull runs; ``sealed`` once the object
    seals (relays switch to the normal pinned read path — the native
    store only evicts sealed *unpinned* objects, and unsealed buffers
    are never evicted at all, so both phases are eviction-safe);
    ``aborted`` when the pull fails (the arena view is dropped under the
    entry lock BEFORE the slot is freed, so an in-flight relay copy can
    never touch recycled arena memory)."""

    __slots__ = ("oid", "size", "meta", "buf", "lock", "_cond", "_avail",
                 "state")

    def __init__(self, oid: ObjectID, buf: memoryview, size: int,
                 meta: bytes):
        self.oid = oid
        self.size = size
        self.meta = meta
        self.buf = buf  # arena view (data + meta); None once finished
        self.lock = threading.Lock()
        self._cond = threading.Condition(self.lock)
        self._avail: List[List[int]] = []  # sorted disjoint [start, end)
        self.state = "open"  # open | sealed | aborted

    # -- puller side ---------------------------------------------------

    def mark(self, start: int, end: int):
        """Record [start, end) as arrived and wake waiting relays."""
        if end <= start:
            return
        with self._cond:
            iv = self._avail
            lo = 0
            while lo < len(iv) and iv[lo][1] < start:
                lo += 1
            hi = lo
            while hi < len(iv) and iv[hi][0] <= end:
                start = min(start, iv[hi][0])
                end = max(end, iv[hi][1])
                hi += 1
            iv[lo:hi] = [[start, end]]
            self._cond.notify_all()

    # -- relay side ----------------------------------------------------

    def _covered(self, start: int, end: int) -> bool:
        # intervals are merged (touching ranges coalesce), so one
        # interval must span the whole query
        if end <= start:
            return True
        for s, e in self._avail:
            if s <= start and e >= end:
                return True
            if s > start:
                return False
        return False

    def wait_covered(self, start: int, end: int,
                     timeout: float) -> str:
        """Block until [start, end) is readable; returns ``"ok"`` (read
        from ``buf``), ``"sealed"`` (read via the store's pinned get),
        ``"aborted"``, or ``"timeout"``."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                if self.state == "sealed":
                    return "sealed"
                if self.state == "aborted":
                    return "aborted"
                if self._covered(start, min(end, self.size)):
                    return "ok"
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    if self.state == "sealed":
                        return "sealed"
                    if self.state == "aborted":
                        return "aborted"
                    if self._covered(start, min(end, self.size)):
                        return "ok"
                    return "timeout"

    def read(self, start: int, end: int) -> Optional[bytes]:
        """Copy [start, end) out of the in-progress buffer; None if the
        pull aborted (buffer gone). The copy happens under the entry
        lock — finish() blocks on it, so the arena slot outlives every
        in-flight read."""
        with self.lock:
            if self.buf is None:
                return None
            return bytes(self.buf[start:end])

    # -- store side ----------------------------------------------------

    def finish(self, sealed: bool):
        with self._cond:
            self.state = "sealed" if sealed else "aborted"
            self.buf = None  # drop the arena view either way: sealed
            self._cond.notify_all()  # readers re-pin via store.get


class _BorrowEntry:
    """Live zero-copy views of one arena entry: a set of weakrefs to the
    frame-view wrappers handed out by ``get_frames(pin_borrows=True)``,
    plus whether a delete arrived while they were alive."""

    __slots__ = ("refs", "deferred_delete", "nbytes", "deferred_since")

    def __init__(self):
        # list, not set: weakrefs to ndarray views are unhashable
        # (ndarray defines array __eq__); removal is by identity
        self.refs: list = []
        self.deferred_delete = False
        # accounting for memory_stats(): payload bytes the pinned views
        # alias, and when a deferred delete started waiting (monotonic;
        # 0.0 while none is pending) — the deferred-delete-pileup doctor
        # warning ages entries off this stamp
        self.nbytes = 0
        self.deferred_since = 0.0


class ShmObjectStore:
    """One node's shared-memory object store (creator or attacher)."""

    def __init__(self, name: str, capacity: int = 0, create: bool = False):
        self.name = name
        self._cname = name.encode()
        lib = get_lib()
        if create:
            self._h = lib.shm_store_create(self._cname, capacity)
        else:
            self._h = lib.shm_store_attach(self._cname)
        if not self._h:
            raise RuntimeError(
                f"Failed to {'create' if create else 'attach'} shm store {name}")
        self._creator = create
        # Eviction hook: called with the evicted ObjectIDs so the process
        # can report lost copies to the head's object directory
        # (OBJ_LOCATION_REMOVE) — a stale directory entry would otherwise
        # only be discovered by a pull failing over off it.
        self.on_evict: Optional[callable] = None
        # In-progress pull availability (cooperative broadcast): oid ->
        # PartialObject for objects being streamed into unsealed buffers
        # by this process's ObjectPuller, readable by its TransferServer.
        # Aborted entries linger as TOMBSTONES (bounded FIFO) so a
        # relay-marked pull racing the abort fails fast instead of
        # polling the whole serve-wait budget for a buffer that will
        # never come back.
        self._partials: Dict[ObjectID, PartialObject] = {}
        self._aborted: "deque" = deque()
        self._partials_lock = threading.Lock()
        # Borrow-pin ledger (r13 zero-copy device path): consumers of
        # ``get_frames(pin_borrows=True)`` receive out-of-band frames as
        # weakref-able views; while ANY such view (or an array
        # reconstructed over it — numpy's oob unpickling and the
        # device-array rebuild both chain .base to the view) is alive,
        # the ledger holds one extra native pin on the entry, so
        # free/spill/evict can never recycle the arena slot under a
        # live zero-copy alias. A delete() that lands while borrows are
        # live is DEFERRED: it runs when the last view dies (the plasma
        # client's release-on-last-buffer semantics).
        self._borrows: Dict[ObjectID, "_BorrowEntry"] = {}
        self._borrow_lock = threading.Lock()
        # Dead-view processing runs on a dedicated reaper thread, NOT in
        # the weakref callback: callbacks fire from GC on ANY allocation
        # — including allocations made while _borrow_lock is held — and
        # taking the (non-reentrant) lock there would self-deadlock the
        # process. The callback only enqueues (deque.append is atomic)
        # and wakes the reaper; ledger entries stay in the map until the
        # reaper processes them, so a delete() racing the last view's
        # death always finds somewhere to record its deferral.
        self._borrow_reap_q: "deque" = deque()
        self._borrow_reap_wake = threading.Event()
        self._borrow_reap_busy: set = set()  # thread idents mid-release
        self._borrow_reaper: Optional[threading.Thread] = None
        self.borrow_pins_taken = 0
        self.borrow_deferred_deletes = 0
        # Map the segment for data access (metadata is managed by the C side).
        fd = os.open(f"/dev/shm/{name}", os.O_RDWR)
        try:
            self._mmap = mmap.mmap(fd, 0)
        finally:
            os.close(fd)
        self._closed = False
        self._lock = threading.Lock()
        # Populate the arena's tmpfs pages + this process's PTEs in the
        # BACKGROUND, in bounded chunks, for creators AND attachers:
        # lazy faulting costs ~25k minor faults (+ kernel zeroing, for
        # the first toucher) per 100 MiB on first writes — halves
        # measured put bandwidth in whichever process does the writing,
        # usually an attacher. A synchronous whole-arena
        # MADV_POPULATE_WRITE was measured to degrade from 0.2s to ~10s
        # per 512 MiB as populated segments accumulate on the deployment
        # kernel, serializing node registration (many_nodes fell to 0.2
        # nodes/s); chunked + off-thread keeps create/attach O(1).
        threading.Thread(target=self._populate_bg,
                         name=f"shm-populate-{name}",
                         daemon=True).start()

    # Sub-chunk per lock hold: a single madvise of 64 MiB can take ~1s+
    # on the deployment kernel as populated segments accumulate, and
    # close() (node teardown) blocks on this lock — 4 MiB holds keep the
    # worst-case stall in the low milliseconds while costing only ~16x
    # more (cheap) lock round-trips per arena.
    _POPULATE_CHUNK = 4 << 20

    def _populate_bg(self):
        # On kernels without MADV_POPULATE_WRITE this returns immediately
        # and the arena lazy-faults. An explicit page-touch fallback was
        # tried and REJECTED: every attaching process faulting 512 MiB
        # concurrently saturated a small host's cores for ~10 s after
        # init (measured 25x sync-task-latency inflation during the
        # storm), while the free-path's prompt local delete already keeps
        # the large-put cycle on the same warm arena offsets — the
        # steady-state put path never re-faults.
        advice = getattr(mmap, "MADV_POPULATE_WRITE", 23)
        off, total = 0, None
        while True:
            with self._lock:
                if self._closed:
                    return
                try:
                    if total is None:
                        total = len(self._mmap)
                    if off >= total:
                        return
                    n = min(self._POPULATE_CHUNK, total - off)
                    self._mmap.madvise(advice, off, n)
                except (OSError, ValueError):
                    return  # pre-5.14 kernel or racing close: lazy-fault
            off += n

    # -- raw object interface -------------------------------------------------

    def create(self, object_id: ObjectID, data_size: int, meta_size: int = 0
               ) -> memoryview:
        # every native entry point checks _closed: shutdown destroys the
        # C-side handle, and late daemon threads (GC grace timers, event
        # flushers) calling in afterwards would use-after-free it
        if self._closed:
            raise ObjectStoreFullError(f"store {self.name} is closed")
        lib = get_lib()
        off = lib.shm_store_create_object(
            self._h, object_id.binary(), data_size, meta_size)
        if off == -1:
            raise ObjectExistsError(object_id.hex())
        if off == 0:
            # Try eviction, then retry once.
            self.evict(data_size + meta_size)
            off = lib.shm_store_create_object(
                self._h, object_id.binary(), data_size, meta_size)
            if off <= 0:
                raise ObjectStoreFullError(
                    f"store {self.name} full: need {data_size + meta_size}, "
                    f"in use {self.bytes_in_use()}/{self.capacity()}")
        return memoryview(self._mmap)[off:off + data_size + meta_size]

    def seal(self, object_id: ObjectID):
        if self._closed:
            return
        # Finish the partial BEFORE the native seal: an in-flight relay
        # read drains while the entry is still unsealed (unsealed
        # objects are never evicted), so no raw-view copy can overlap
        # the sealed-unpinned window where any thread OR attached
        # process under memory pressure may evict and recycle the slot.
        # Relays that see state=="sealed" re-read through the pinned get
        # path (briefly polling for the native seal to land).
        self._finish_partial(object_id, sealed=True)
        if get_lib().shm_store_seal(self._h, object_id.binary()) != 0:
            raise KeyError(f"seal failed for {object_id.hex()}")

    def get(self, object_id: ObjectID) -> Optional[Tuple[memoryview, memoryview]]:
        """Returns (data, metadata) views, pinning the object; None if absent."""
        if self._closed:
            return None
        out = (ctypes.c_uint64 * 3)()
        rc = get_lib().shm_store_get(self._h, object_id.binary(), out)
        if rc != 0:
            return None
        off, dsize, msize = out[0], out[1], out[2]
        mv = memoryview(self._mmap)
        return mv[off:off + dsize], mv[off + dsize:off + dsize + msize]

    def contains(self, object_id: ObjectID) -> bool:
        if self._closed:
            return False
        return get_lib().shm_store_contains(self._h, object_id.binary()) == 1

    def release(self, object_id: ObjectID):
        if self._closed:
            return
        get_lib().shm_store_release(self._h, object_id.binary())

    def delete(self, object_id: ObjectID) -> bool:
        if self._closed:
            return False
        # An aborted pull (or an explicit free) deletes created-but-
        # unsealed entries; any relay still serving the partial must stop
        # touching the arena view BEFORE the slot is freed for reuse —
        # _finish_partial blocks on in-flight relay reads.
        self._finish_partial(object_id, sealed=False)
        ok = get_lib().shm_store_delete(self._h, object_id.binary()) == 0
        if not ok:
            # pinned — by a reader, or by the borrow ledger's extra pin
            # while zero-copy views are alive. If it's the ledger,
            # DEFER: the delete re-runs when the last view dies, so
            # free/spill racing a live alias pins instead of corrupting.
            # (Entries linger in the map until the reaper thread
            # processes dead views, so this always finds somewhere to
            # record the deferral.)
            retry = False
            with self._borrow_lock:
                entry = self._borrows.get(object_id)
                if entry is not None:
                    if not entry.deferred_delete:
                        entry.deferred_delete = True
                        entry.deferred_since = time.monotonic()
                        self.borrow_deferred_deletes += 1
                else:
                    # no ledger entry: the failing pin may have been the
                    # ledger's, released between the two calls — retry
                    # once so the delete isn't lost to that race
                    retry = True
            if retry:
                ok = get_lib().shm_store_delete(
                    self._h, object_id.binary()) == 0
        return ok

    def evict(self, need: int) -> List[ObjectID]:
        if self._closed:
            return []
        buf = ctypes.create_string_buffer(_ID_SIZE * 256)
        n = get_lib().shm_store_evict(self._h, need, buf, 256)
        evicted = [
            ObjectID(buf.raw[i * _ID_SIZE:(i + 1) * _ID_SIZE]) for i in range(n)
        ]
        if evicted and self.on_evict is not None:
            try:
                self.on_evict(evicted)
            except Exception:  # noqa: BLE001 — directory upkeep must never
                pass           # fail the allocation that triggered eviction
        return evicted

    def bytes_in_use(self) -> int:
        if self._closed:
            return 0
        return get_lib().shm_store_bytes_in_use(self._h)

    def capacity(self) -> int:
        if self._closed:
            return 0
        return get_lib().shm_store_capacity(self._h)

    def num_objects(self) -> int:
        if self._closed:
            return 0
        return get_lib().shm_store_num_objects(self._h)

    def memory_stats(self) -> Dict[str, int]:
        """Arena accounting snapshot — one native call (single lock
        acquisition + table scan) merged with the Python-side borrow
        ledger, cheap enough for the node-telemetry heartbeat. Keys:
        ``capacity`` / ``used_bytes`` (arena blocks incl. headers) /
        ``highwater_bytes`` / ``entries`` / ``sealed_count`` /
        ``sealed_bytes`` (data + frame-size metadata, the arena truth) /
        ``sealed_data_bytes`` (data only — the wire/dir size
        convention, exact vs. the directory's per-object sizes) /
        ``unsealed_count`` / ``unsealed_bytes`` /
        ``pinned_count`` / ``pinned_bytes`` (native reader pins) /
        ``borrow_pinned_count`` / ``borrow_pinned_bytes`` (zero-copy
        views alive in THIS process) / ``deferred_deletes`` (pending) /
        ``deferred_delete_oldest_s`` (age of the oldest one)."""
        if self._closed:
            return {}
        out = (ctypes.c_uint64 * 11)()
        get_lib().shm_store_memory_stats(self._h, out)
        borrow_count = borrow_bytes = 0
        deferred = 0
        oldest = 0.0
        now = time.monotonic()
        with self._borrow_lock:
            for entry in self._borrows.values():
                borrow_count += 1
                borrow_bytes += entry.nbytes
                if entry.deferred_delete:
                    deferred += 1
                    oldest = max(oldest, now - entry.deferred_since)
        return {
            "capacity": int(out[0]),
            "used_bytes": int(out[1]),
            "highwater_bytes": int(out[2]),
            "entries": int(out[3]),
            "sealed_count": int(out[4]),
            "sealed_bytes": int(out[5]),
            "sealed_data_bytes": int(out[10]),
            "unsealed_count": int(out[6]),
            "unsealed_bytes": int(out[7]),
            "pinned_count": int(out[8]),
            "pinned_bytes": int(out[9]),
            "borrow_pinned_count": borrow_count,
            "borrow_pinned_bytes": borrow_bytes,
            "deferred_deletes": deferred,
            "deferred_delete_oldest_s": oldest,
        }

    def list_objects(self, max_objects: int = 8192
                     ) -> List[Tuple[ObjectID, int]]:
        """Sealed objects currently in the arena as ``[(ObjectID,
        data+meta bytes)]`` — the holder report a re-registering node
        agent ships so a restarted head can rebuild its object directory
        from holder truth (the directory is deliberately not WAL'd)."""
        if self._closed:
            return []
        ids = ctypes.create_string_buffer(_ID_SIZE * max_objects)
        sizes = (ctypes.c_uint64 * max_objects)()
        n = get_lib().shm_store_list(self._h, ids, sizes, max_objects)
        return [(ObjectID(ids.raw[i * _ID_SIZE:(i + 1) * _ID_SIZE]),
                 int(sizes[i])) for i in range(n)]

    # -- in-progress pull availability (cooperative broadcast) ---------------

    def begin_partial(self, object_id: ObjectID, buf: memoryview,
                      size: int, meta: bytes) -> PartialObject:
        """Register an in-progress pull's unsealed buffer so this host's
        TransferServer can relay chunks as they arrive. The entry is
        finished automatically by ``seal`` (promoted) or ``delete``
        (aborted) of the same id."""
        part = PartialObject(object_id, buf, size, bytes(meta))
        with self._partials_lock:
            self._partials[object_id] = part
        return part

    def partial(self, object_id: ObjectID) -> Optional[PartialObject]:
        with self._partials_lock:
            return self._partials.get(object_id)

    _ABORT_TOMBSTONES = 256

    def _finish_partial(self, object_id: ObjectID, sealed: bool):
        with self._partials_lock:
            part = self._partials.get(object_id)
            if part is None or part.state == "aborted":
                return  # unknown, or already a tombstone
            if sealed:
                del self._partials[object_id]
            else:
                # leave the aborted entry queryable: a relay request
                # racing the abort gets an immediate "aborted" (->
                # OBJ_PULL_FAIL -> root failover) instead of burning
                # the full appear-wait poll. A re-pull's begin_partial
                # simply overwrites the tombstone.
                self._aborted.append((object_id, part))
                if len(self._aborted) > self._ABORT_TOMBSTONES:
                    old_oid, old_part = self._aborted.popleft()
                    if self._partials.get(old_oid) is old_part:
                        del self._partials[old_oid]
        part.finish(sealed)

    # -- serialized-value interface ------------------------------------------

    @staticmethod
    def sealed_nbytes(frames: List) -> int:
        """The exact payload bytes (data + metadata) put_serialized
        would seal for these frames — what the native entry's
        data_size + meta_size will read, and therefore what the head
        directory must record for per-node byte attribution to agree
        exactly with the store's own memory_stats()."""
        sizes = [len(f) for f in frames]
        return sum(sizes) + len(pickle.dumps(sizes, protocol=5))

    def put_serialized(self, object_id: ObjectID, frames: List) -> int:
        """Serialize-into-store put: reserve the shm object from a cheap
        size pass over the frames, then write the pickle stream and each
        out-of-band buffer straight into the mapped memoryview — frames
        are memoryviews of the source object's memory (serialization.py),
        so every byte moves exactly once, source to arena. Returns the
        sealed object's byte count."""
        sizes = [len(f) for f in frames]
        meta = pickle.dumps(sizes, protocol=5)
        total = sum(sizes)
        buf = self.create(object_id, total, len(meta))
        pos = 0
        for f, n in zip(frames, sizes):
            if n > (1 << 20):
                # numpy's vectorized copy moves ~2x the bytes/s of a Python
                # memoryview slice assignment — this IS the put-bandwidth
                # benchmark for large objects.
                np.copyto(np.frombuffer(buf[pos:pos + n], np.uint8),
                          np.frombuffer(f, np.uint8))
            else:
                buf[pos:pos + n] = f
            pos += n
        buf[total:] = meta
        self.seal(object_id)
        return total + len(meta)

    def put_raw(self, object_id: ObjectID, data: bytes) -> int:
        """Store raw bytes with NO metadata — the cross-language payload
        convention shared with the C++ client (native/ray_tpu_client.h);
        pickled Python objects use put_serialized instead."""
        buf = self.create(object_id, len(data), 0)
        buf[:len(data)] = data
        self.seal(object_id)
        return len(data)

    def get_raw(self, object_id: ObjectID) -> Optional[bytes]:
        """Raw-convention read (copies out + releases the pin)."""
        got = self.get(object_id)
        if got is None:
            return None
        data_v, meta_v = got
        try:
            return bytes(data_v)
        finally:
            del data_v, meta_v, got
            self.release(object_id)

    def get_frames(self, object_id: ObjectID, pin_borrows: bool = False
                   ) -> Optional[List]:
        """Frame views over the sealed entry (pins the object — the
        caller owns one ``release``). With ``pin_borrows``, out-of-band
        frames come back as weakref-able ndarray views registered with
        the borrow ledger: deserialized arrays that alias them (numpy
        oob reconstruction, the device-array rebuild) keep the views —
        and therefore one extra native pin on the entry — alive, so a
        racing free/spill defers instead of recycling the slot under
        the consumer (zero-copy read safety)."""
        got = self.get(object_id)
        if got is None:
            return None
        data, meta = got
        sizes = pickle.loads(bytes(meta))
        frames, pos = [], 0
        for s in sizes:
            frames.append(data[pos:pos + s])
            pos += s
        if pin_borrows and len(frames) > 1:
            wrapped = []
            for f in frames[1:]:
                w = np.frombuffer(f, dtype=np.uint8)
                # READONLY, like the reference plasma client's sealed
                # buffers: consumers alias SHARED arena memory, and an
                # in-place `arr *= 2` must raise, not silently corrupt
                # the object for every other reader (the device rebuild
                # copies on readonly via its dlpack fallback)
                w.setflags(write=False)
                wrapped.append(w)
            self._register_borrows(object_id, wrapped)
            frames = [frames[0]] + wrapped
        return frames

    # -- borrow-pin ledger (zero-copy read safety) ---------------------

    def _register_borrows(self, object_id: ObjectID, views: List):
        """One extra native pin per object-with-borrows, held until the
        last registered view dies (processed by the reaper thread)."""
        if self._closed:
            return
        with self._borrow_lock:
            if self._borrow_reaper is None:
                self._borrow_reaper = threading.Thread(
                    target=self._borrow_reap_loop, daemon=True,
                    name=f"borrow-reap-{self.name}")
                self._borrow_reaper.start()
            entry = self._borrows.get(object_id)
            fresh = entry is None
            if fresh:
                entry = self._borrows[object_id] = _BorrowEntry()
            for v in views:
                entry.refs.append(weakref.ref(
                    v, lambda r, oid=object_id: self._borrow_dead(oid, r)))
            if fresh:
                entry.nbytes = sum(len(v) for v in views)
        if fresh:
            # the ledger's own pin (independent of the caller's read
            # pin): bump the native refcount, drop the views
            out = (ctypes.c_uint64 * 3)()
            if get_lib().shm_store_get(self._h, object_id.binary(),
                                       out) == 0:
                self.borrow_pins_taken += 1
            else:  # entry vanished between get_frames' get and here
                with self._borrow_lock:
                    self._borrows.pop(object_id, None)

    def _borrow_dead(self, object_id: ObjectID, ref):
        """Weakref callback — runs inside GC, possibly on a thread that
        already holds _borrow_lock (callbacks fire on any allocation):
        must not lock or call into the native store. Enqueue only."""
        self._borrow_reap_q.append((object_id, ref))
        self._borrow_reap_wake.set()

    def _borrow_reap_loop(self):
        while not self._closed:
            self._borrow_reap_wake.wait(timeout=5.0)
            self._borrow_reap_wake.clear()
            self._drain_borrow_queue()

    def _drain_borrow_queue(self):
        """Process dead-view notifications: prune the ledger, release
        the pin when the last view of an object dies, and run any
        delete() that was deferred while views were alive. Safe to call
        from any thread (items pop atomically; the ledger mutates under
        its lock) — ``reap_borrows`` shares it with the reaper."""
        me = threading.get_ident()
        while True:
            with self._borrow_lock:
                try:
                    object_id, ref = self._borrow_reap_q.popleft()
                except IndexError:
                    return
                # mark in-progress UNDER the lock that popped the item:
                # reap_borrows must not observe empty-queue-and-idle
                # while another thread is mid-release
                self._borrow_reap_busy.add(me)
            try:
                do_delete = False
                with self._borrow_lock:
                    entry = self._borrows.get(object_id)
                    if entry is None:
                        continue
                    entry.refs = [r for r in entry.refs if r is not ref]
                    if entry.refs:
                        continue
                    del self._borrows[object_id]
                    do_delete = entry.deferred_delete
                if self._closed:
                    return
                self.release(object_id)
                if do_delete:
                    # plain native delete: the entry is sealed (no
                    # partial can exist) and delete() would re-consult
                    # the ledger entry just removed. A transient reader
                    # pin (a get() in flight on another thread) can
                    # fail it — retry briefly; past that, reclamation
                    # falls back to the normal directory-driven
                    # free/eviction paths (same contract as the
                    # owner-free local-delete optimization).
                    for _ in range(5):
                        if get_lib().shm_store_delete(
                                self._h, object_id.binary()) == 0:
                            break
                        time.sleep(0.01)
                        if self._closed:
                            return
            finally:
                with self._borrow_lock:
                    self._borrow_reap_busy.discard(me)

    def reap_borrows(self, timeout: float = 2.0) -> None:
        """Synchronously process every already-dead view's notification
        (the reaper thread normally does this asynchronously) — for
        tests and teardown paths that need deterministic reclamation."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self._drain_borrow_queue()
            with self._borrow_lock:
                if not self._borrow_reap_q and \
                        not self._borrow_reap_busy:
                    return
            time.sleep(0.001)

    def live_borrows(self, object_id: ObjectID) -> int:
        """How many zero-copy views of this entry are still alive."""
        with self._borrow_lock:
            entry = self._borrows.get(object_id)
            if entry is None:
                return 0
            return sum(1 for r in entry.refs if r() is not None)

    def close(self):
        if self._closed:
            return
        # wake + detach any relayed in-progress pulls first: a live
        # partial's arena view would BufferError the munmap below
        with self._partials_lock:
            parts, self._partials = list(self._partials.values()), {}
        for p in parts:
            p.finish(sealed=False)
        # _lock serializes against an in-flight background populate
        # chunk: munmap under a concurrent madvise would be a
        # use-after-free of the mapping
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                self._mmap.close()
            except BufferError:
                pass  # zero-copy views still alive; leave the map
        lib = get_lib()
        if self._creator:
            lib.shm_store_destroy(self._h, self._cname)
        else:
            lib.shm_store_detach(self._h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
