"""Cluster resource scheduling: node selection policies + bundle placement.

Analog of the reference's two-level scheduler
(src/ray/raylet/scheduling/cluster_resource_scheduler.h:44
``GetBestSchedulableNode``, policies under scheduling/policy/ — hybrid
:contentReference hybrid_scheduling_policy.h:50, spread, node-affinity,
bundle PACK/SPREAD/STRICT_* bundle_scheduling_policy.cc). Queueing/dispatch
lives with each node's worker pool (head.py); this module is the pure
placement math, unit-testable without any processes (mirroring
cluster_resource_scheduler_test.cc).

TPU-first addition: STRICT_PACK placement of TPU bundles is ICI-topology
aware — bundles requesting TPU chips prefer hosts of one slice, contiguous
by worker_index, so that the gang they host forms a connected ICI sub-torus.
"""

from __future__ import annotations

import ctypes
import random
from typing import Dict, List, Optional, Sequence

from .config import get_config
from .resources import (CPU, GPU, MEMORY, OBJECT_STORE_MEMORY, TPU,
                        NodeResources, ResourceSet)
from .task_spec import PlacementGroupSpec, SchedulingStrategy


class _NativeCore:
    """ctypes bridge to libsched_core.so (native/sched_core.cc): the
    per-lease feasibility scan + utilization ranking runs in C over a
    node table kept in sync lazily via NodeResources.version — only
    nodes whose availability changed since the last decision re-pack.

    Ref analog: the reference's scheduler IS native
    (cluster_resource_scheduler.cc); this brings the same hot path off
    the Python interpreter (measured ~100x on a 10k-node table).
    """

    # interning: the critical kinds (utilization drivers) get ids 0..3,
    # matching kCriticalKinds in sched_core.cc
    _PREDEF = {CPU: 0, GPU: 1, TPU: 2, MEMORY: 3, OBJECT_STORE_MEMORY: 4}

    def __init__(self):
        from ray_tpu.native.build import lib_path

        lib = ctypes.CDLL(lib_path("libsched_core.so"))
        lib.sched_create.restype = ctypes.c_void_p
        lib.sched_destroy.argtypes = [ctypes.c_void_p]
        I64P = ctypes.POINTER(ctypes.c_int64)
        lib.sched_set_node.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int, I64P, I64P, I64P]
        lib.sched_remove_node.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.sched_set_draining.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int]
        lib.sched_best_node.restype = ctypes.c_int64
        lib.sched_best_node.argtypes = [
            ctypes.c_void_p, ctypes.c_int, I64P, I64P, ctypes.c_int,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.sched_feasible_anywhere.restype = ctypes.c_int
        lib.sched_feasible_anywhere.argtypes = [
            ctypes.c_void_p, ctypes.c_int, I64P, I64P]
        self._lib = lib
        self._h = lib.sched_create()
        self._kind_ids: Dict[str, int] = dict(self._PREDEF)
        # push-based dirty tracking: add_node/NodeResources listeners
        # mark indices pending; sync() repacks ONLY those. A per-call
        # full-table scan (or per-call draining rebroadcast) would put
        # O(n) Python work in front of the O(n) C scan and erase the
        # native win.
        self._pending: set = set()
        self._rng_state = ctypes.c_uint64(0x2545F4914F6CDD1D)

    def __del__(self):
        try:
            self._lib.sched_destroy(self._h)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def _kind(self, name: str) -> int:
        kid = self._kind_ids.get(name)
        if kid is None:
            kid = len(self._kind_ids)
            self._kind_ids[name] = kid
        return kid

    def _pack(self, rs: ResourceSet):
        names = list(rs.names())
        n = len(names)
        kinds = (ctypes.c_int64 * n)(*[self._kind(k) for k in names])
        vals = (ctypes.c_int64 * n)(*[rs.get_fp(k) for k in names])
        return n, kinds, vals

    def mark_dirty(self, idx: int):
        self._pending.add(idx)

    def remove(self, idx: int):
        self._lib.sched_remove_node(self._h, idx)
        self._pending.discard(idx)

    def set_draining(self, idx: int, draining: bool):
        self._lib.sched_set_draining(self._h, idx, 1 if draining else 0)

    def sync(self, nodes: Dict[int, NodeResources], draining: set):
        if not self._pending:
            return
        pending, self._pending = self._pending, set()
        for idx in pending:
            res = nodes.get(idx)
            if res is None:
                self._lib.sched_remove_node(self._h, idx)
                continue
            names = list(set(res.total.names())
                         | set(res.available.names()))
            n = len(names)
            kinds = (ctypes.c_int64 * n)(*[self._kind(k) for k in names])
            avail = (ctypes.c_int64 * n)(
                *[res.available.get_fp(k) for k in names])
            total = (ctypes.c_int64 * n)(
                *[res.total.get_fp(k) for k in names])
            self._lib.sched_set_node(self._h, idx, n, kinds, avail, total)
            if idx in draining:
                self._lib.sched_set_draining(self._h, idx, 1)

    def best_node(self, request: ResourceSet, *, spread: bool,
                  local_idx: int, threshold: float,
                  topk_frac: float) -> Optional[int]:
        n, kinds, demand = self._pack(request)
        out = self._lib.sched_best_node(
            self._h, n, kinds, demand, 1 if spread else 0, local_idx,
            int(threshold * 10000), int(topk_frac * 10000),
            ctypes.byref(self._rng_state))
        return None if out < 0 else int(out)

    def feasible_anywhere(self, request: ResourceSet) -> bool:
        n, kinds, demand = self._pack(request)
        return bool(self._lib.sched_feasible_anywhere(
            self._h, n, kinds, demand))


def _load_native() -> Optional[_NativeCore]:
    try:
        return _NativeCore()
    except Exception:  # noqa: BLE001 — no toolchain: Python fallback
        return None


class ClusterResourceScheduler:
    """Maintains the resource view of every node and picks placements."""

    def __init__(self, use_native: bool = True):
        self.nodes: Dict[int, NodeResources] = {}
        self._draining: set = set()
        self._rng = random.Random(0)
        self._native = _load_native() if use_native else None
        self._change_cbs: Dict[int, object] = {}  # idx -> our listener

    def add_node(self, idx: int, res: NodeResources):
        self.nodes[idx] = res
        if self._native is not None:
            self._native.mark_dirty(idx)
            # availability changes flow as push notifications — a
            # per-decision table scan would cost more than the C scan
            cb = lambda core=self._native, i=idx: core.mark_dirty(i)  # noqa: E731
            self._change_cbs[idx] = cb
            res.listeners.append(cb)

    def remove_node(self, idx: int):
        res = self.nodes.pop(idx, None)
        self._draining.discard(idx)
        if self._native is not None:
            self._native.remove(idx)
            cb = self._change_cbs.pop(idx, None)
            if res is not None and cb is not None:
                try:
                    res.listeners.remove(cb)
                except ValueError:
                    pass

    def drain_node(self, idx: int):
        self._draining.add(idx)
        if self._native is not None and idx in self.nodes:
            self._native.set_draining(idx, True)

    def schedulable_nodes(self) -> List[int]:
        return [i for i in self.nodes if i not in self._draining]

    # -- single-task placement -------------------------------------------

    def best_node(self, request: ResourceSet, strategy: SchedulingStrategy,
                  local_idx: int = 0) -> Optional[int]:
        """Pick a node for one resource request; None if infeasible now.

        DEFAULT uses the hybrid policy: prefer the local node while its
        utilization is below ``scheduler_spread_threshold``, else pick from
        the top-k least-utilized feasible nodes at random (reference
        hybrid_scheduling_policy.h:50).
        """
        if strategy.kind == "NODE_AFFINITY":
            idx = int(strategy.node_id)
            node = self.nodes.get(idx)
            if node is None or idx in self._draining:
                # a DRAINING node takes no new work (r16) — without
                # this check an affinity-targeted lease would land on
                # the departing node, hold its drain open to the
                # deadline, and die in the forced shutdown the
                # graceful API exists to avoid. Soft affinity falls to
                # the policy; hard stays queued like a missing node.
                return None if not strategy.soft else self._hybrid(request, local_idx)
            if node.is_available(request):
                return idx
            if strategy.soft:
                return self._hybrid(request, local_idx)
            return idx if node.is_feasible(request) else None
        if strategy.kind == "SPREAD":
            return self._spread(request)
        return self._hybrid(request, local_idx)

    def _feasible_available(self, request: ResourceSet) -> List[int]:
        return [i for i in self.schedulable_nodes()
                if self.nodes[i].is_available(request)]

    def _hybrid(self, request: ResourceSet, local_idx: int) -> Optional[int]:
        cfg = get_config()
        if self._native is not None:
            self._native.sync(self.nodes, self._draining)
            return self._native.best_node(
                request, spread=False, local_idx=local_idx,
                threshold=cfg.scheduler_spread_threshold,
                topk_frac=cfg.scheduler_top_k_fraction)
        avail = self._feasible_available(request)
        if not avail:
            return None
        local = self.nodes.get(local_idx)
        if (local_idx in avail and local is not None
                and local.utilization() < cfg.scheduler_spread_threshold):
            return local_idx
        avail.sort(key=lambda i: (self.nodes[i].utilization(), i))
        k = max(1, int(len(avail) * cfg.scheduler_top_k_fraction))
        return self._rng.choice(avail[:k])

    def best_locality_node(self, request: ResourceSet,
                           arg_bytes_by_node: Dict[int, int]
                           ) -> Optional[int]:
        """Locality-aware placement (reference: LocalityAwareLeasePolicy,
        locality_aware_lease_policy.h + hybrid policy's locality hook):
        among schedulable nodes that can run ``request`` RIGHT NOW, pick
        the one already holding the most argument bytes. Returns None when
        no holder is feasible+available — the caller falls back to the
        hybrid/spread policies, so locality is a preference, never a
        constraint.
        """
        best, best_score = None, 0
        for i in self.schedulable_nodes():
            score = arg_bytes_by_node.get(i, 0)
            if score <= 0:
                continue
            node = self.nodes.get(i)
            if node is None or not node.is_available(request):
                continue
            if score > best_score or (score == best_score
                                      and best is not None and i < best):
                best, best_score = i, score
        return best

    def _spread(self, request: ResourceSet) -> Optional[int]:
        if self._native is not None:
            self._native.sync(self.nodes, self._draining)
            return self._native.best_node(
                request, spread=True, local_idx=0, threshold=0.0,
                topk_frac=0.0)
        avail = self._feasible_available(request)
        if not avail:
            return None
        return min(avail, key=lambda i: (self.nodes[i].utilization(), i))

    def is_feasible_anywhere(self, request: ResourceSet) -> bool:
        if self._native is not None:
            self._native.sync(self.nodes, self._draining)
            return self._native.feasible_anywhere(request)
        return any(self.nodes[i].is_feasible(request)
                   for i in self.schedulable_nodes())

    # -- placement-group bundle placement --------------------------------

    def place_bundles(self, spec: PlacementGroupSpec) -> Optional[List[int]]:
        """Return node index per bundle, or None if unplaceable now.

        Works against *available* resources; caller commits reservations.
        """
        reqs = [ResourceSet(b.resources) for b in spec.bundles]
        scratch = {i: self.nodes[i].available for i in self.schedulable_nodes()}

        def try_fit(order: Sequence[int], node_order: List[int],
                    one_per_node: bool) -> Optional[List[int]]:
            placement: List[Optional[int]] = [None] * len(reqs)
            avail = dict(scratch)
            used_nodes = set()
            for bi in order:
                placed = False
                for ni in node_order:
                    if one_per_node and ni in used_nodes:
                        continue
                    if avail[ni].covers(reqs[bi]):
                        avail[ni] = avail[ni].subtract(reqs[bi])
                        placement[bi] = ni
                        used_nodes.add(ni)
                        placed = True
                        break
                if not placed:
                    return None
            return placement  # type: ignore[return-value]

        # Largest bundles first for better packing.
        order = sorted(range(len(reqs)),
                       key=lambda i: -sum(reqs[i].to_dict().values()))
        nodes = list(scratch.keys())

        if spec.strategy == "STRICT_PACK":
            # All bundles on one node; for TPU bundles prefer the node whose
            # topology matches (slice-local).
            for ni in self._tpu_aware_order(nodes, reqs):
                avail = scratch[ni]
                ok = True
                for bi in order:
                    if not avail.covers(reqs[bi]):
                        ok = False
                        break
                    avail = avail.subtract(reqs[bi])
                if ok:
                    return [ni] * len(reqs)
            return None
        if spec.strategy == "STRICT_SPREAD":
            node_order = self._tpu_aware_order(nodes, reqs)
            return try_fit(order, node_order, one_per_node=True)
        if spec.strategy == "SPREAD":
            node_order = sorted(nodes, key=lambda i: self.nodes[i].utilization())
            out = try_fit(order, node_order, one_per_node=True)
            if out is not None:
                return out
            # Best-effort: least-loaded node per bundle, updating as we go.
            placement: List[Optional[int]] = [None] * len(reqs)
            avail = dict(scratch)
            for bi in order:
                fitting = [ni for ni in nodes if avail[ni].covers(reqs[bi])]
                if not fitting:
                    return None
                ni = max(fitting,
                         key=lambda n: sum(avail[n].to_dict().values()))
                avail[ni] = avail[ni].subtract(reqs[bi])
                placement[bi] = ni
            return placement  # type: ignore[return-value]
        # PACK: minimize node count — fill nodes greedily, most-available first.
        node_order = self._tpu_aware_order(nodes, reqs)
        return try_fit(order, node_order, one_per_node=False)

    def _tpu_aware_order(self, nodes: List[int], reqs: List[ResourceSet]
                         ) -> List[int]:
        """Order candidate nodes for packing. If the bundles want TPU chips,
        group hosts by slice and order by worker_index so a multi-host gang
        lands on a contiguous ICI sub-torus; otherwise most-available-first."""
        wants_tpu = any(r.get(TPU) > 0 for r in reqs)
        if not wants_tpu:
            return sorted(nodes, key=lambda i: -sum(
                self.nodes[i].available.to_dict().values()))

        def key(i):
            t = self.nodes[i].tpu
            if t is None:
                return (1, "", 0)
            return (0, t.slice_name, t.worker_index)

        return sorted(nodes, key=key)
