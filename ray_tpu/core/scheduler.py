"""Cluster resource scheduling: node selection policies + bundle placement.

Analog of the reference's two-level scheduler
(src/ray/raylet/scheduling/cluster_resource_scheduler.h:44
``GetBestSchedulableNode``, policies under scheduling/policy/ — hybrid
:contentReference hybrid_scheduling_policy.h:50, spread, node-affinity,
bundle PACK/SPREAD/STRICT_* bundle_scheduling_policy.cc). Queueing/dispatch
lives with each node's worker pool (head.py); this module is the pure
placement math, unit-testable without any processes (mirroring
cluster_resource_scheduler_test.cc).

TPU-first addition: STRICT_PACK placement of TPU bundles is ICI-topology
aware — bundles requesting TPU chips prefer hosts of one slice, contiguous
by worker_index, so that the gang they host forms a connected ICI sub-torus.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence

from .config import get_config
from .resources import NodeResources, ResourceSet, TPU
from .task_spec import PlacementGroupSpec, SchedulingStrategy


class ClusterResourceScheduler:
    """Maintains the resource view of every node and picks placements."""

    def __init__(self):
        self.nodes: Dict[int, NodeResources] = {}
        self._draining: set = set()
        self._rng = random.Random(0)

    def add_node(self, idx: int, res: NodeResources):
        self.nodes[idx] = res

    def remove_node(self, idx: int):
        self.nodes.pop(idx, None)
        self._draining.discard(idx)

    def drain_node(self, idx: int):
        self._draining.add(idx)

    def schedulable_nodes(self) -> List[int]:
        return [i for i in self.nodes if i not in self._draining]

    # -- single-task placement -------------------------------------------

    def best_node(self, request: ResourceSet, strategy: SchedulingStrategy,
                  local_idx: int = 0) -> Optional[int]:
        """Pick a node for one resource request; None if infeasible now.

        DEFAULT uses the hybrid policy: prefer the local node while its
        utilization is below ``scheduler_spread_threshold``, else pick from
        the top-k least-utilized feasible nodes at random (reference
        hybrid_scheduling_policy.h:50).
        """
        if strategy.kind == "NODE_AFFINITY":
            idx = int(strategy.node_id)
            node = self.nodes.get(idx)
            if node is None:
                return None if not strategy.soft else self._hybrid(request, local_idx)
            if node.is_available(request):
                return idx
            if strategy.soft:
                return self._hybrid(request, local_idx)
            return idx if node.is_feasible(request) else None
        if strategy.kind == "SPREAD":
            return self._spread(request)
        return self._hybrid(request, local_idx)

    def _feasible_available(self, request: ResourceSet) -> List[int]:
        return [i for i in self.schedulable_nodes()
                if self.nodes[i].is_available(request)]

    def _hybrid(self, request: ResourceSet, local_idx: int) -> Optional[int]:
        cfg = get_config()
        avail = self._feasible_available(request)
        if not avail:
            return None
        local = self.nodes.get(local_idx)
        if (local_idx in avail and local is not None
                and local.utilization() < cfg.scheduler_spread_threshold):
            return local_idx
        avail.sort(key=lambda i: (self.nodes[i].utilization(), i))
        k = max(1, int(len(avail) * cfg.scheduler_top_k_fraction))
        return self._rng.choice(avail[:k])

    def _spread(self, request: ResourceSet) -> Optional[int]:
        avail = self._feasible_available(request)
        if not avail:
            return None
        return min(avail, key=lambda i: (self.nodes[i].utilization(), i))

    def is_feasible_anywhere(self, request: ResourceSet) -> bool:
        return any(self.nodes[i].is_feasible(request)
                   for i in self.schedulable_nodes())

    # -- placement-group bundle placement --------------------------------

    def place_bundles(self, spec: PlacementGroupSpec) -> Optional[List[int]]:
        """Return node index per bundle, or None if unplaceable now.

        Works against *available* resources; caller commits reservations.
        """
        reqs = [ResourceSet(b.resources) for b in spec.bundles]
        scratch = {i: self.nodes[i].available for i in self.schedulable_nodes()}

        def try_fit(order: Sequence[int], node_order: List[int],
                    one_per_node: bool) -> Optional[List[int]]:
            placement: List[Optional[int]] = [None] * len(reqs)
            avail = dict(scratch)
            used_nodes = set()
            for bi in order:
                placed = False
                for ni in node_order:
                    if one_per_node and ni in used_nodes:
                        continue
                    if avail[ni].covers(reqs[bi]):
                        avail[ni] = avail[ni].subtract(reqs[bi])
                        placement[bi] = ni
                        used_nodes.add(ni)
                        placed = True
                        break
                if not placed:
                    return None
            return placement  # type: ignore[return-value]

        # Largest bundles first for better packing.
        order = sorted(range(len(reqs)),
                       key=lambda i: -sum(reqs[i].to_dict().values()))
        nodes = list(scratch.keys())

        if spec.strategy == "STRICT_PACK":
            # All bundles on one node; for TPU bundles prefer the node whose
            # topology matches (slice-local).
            for ni in self._tpu_aware_order(nodes, reqs):
                avail = scratch[ni]
                ok = True
                for bi in order:
                    if not avail.covers(reqs[bi]):
                        ok = False
                        break
                    avail = avail.subtract(reqs[bi])
                if ok:
                    return [ni] * len(reqs)
            return None
        if spec.strategy == "STRICT_SPREAD":
            node_order = self._tpu_aware_order(nodes, reqs)
            return try_fit(order, node_order, one_per_node=True)
        if spec.strategy == "SPREAD":
            node_order = sorted(nodes, key=lambda i: self.nodes[i].utilization())
            out = try_fit(order, node_order, one_per_node=True)
            if out is not None:
                return out
            # Best-effort: least-loaded node per bundle, updating as we go.
            placement: List[Optional[int]] = [None] * len(reqs)
            avail = dict(scratch)
            for bi in order:
                fitting = [ni for ni in nodes if avail[ni].covers(reqs[bi])]
                if not fitting:
                    return None
                ni = max(fitting,
                         key=lambda n: sum(avail[n].to_dict().values()))
                avail[ni] = avail[ni].subtract(reqs[bi])
                placement[bi] = ni
            return placement  # type: ignore[return-value]
        # PACK: minimize node count — fill nodes greedily, most-available first.
        node_order = self._tpu_aware_order(nodes, reqs)
        return try_fit(order, node_order, one_per_node=False)

    def _tpu_aware_order(self, nodes: List[int], reqs: List[ResourceSet]
                         ) -> List[int]:
        """Order candidate nodes for packing. If the bundles want TPU chips,
        group hosts by slice and order by worker_index so a multi-host gang
        lands on a contiguous ICI sub-torus; otherwise most-available-first."""
        wants_tpu = any(r.get(TPU) > 0 for r in reqs)
        if not wants_tpu:
            return sorted(nodes, key=lambda i: -sum(
                self.nodes[i].available.to_dict().values()))

        def key(i):
            t = self.nodes[i].tpu
            if t is None:
                return (1, "", 0)
            return (0, t.slice_name, t.worker_index)

        return sorted(nodes, key=key)
