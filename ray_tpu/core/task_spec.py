"""Task specifications — the unit shipped from submitter to executor.

Analog of the reference's ``TaskSpecification`` (src/ray/common/task/
task_spec.h:244) and ``SchedulingClassDescriptor`` (:75). A spec carries the
function descriptor (pointer into the function table exported to the head
KV), serialized args (inline values or object references), resource demands,
retry policy, and scheduling strategy. Actor creation and actor-call tasks
are the same type with extra fields, as in the reference.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID


class TaskType(enum.IntEnum):
    NORMAL = 0
    ACTOR_CREATION = 1
    ACTOR_TASK = 2


# Argument encodings inside a spec.
ARG_VALUE = 0   # ("v", frames)            — inline serialized value
ARG_REF = 1     # ("r", id_bytes, owner)   — pass by reference


@dataclass
class SchedulingStrategy:
    """DEFAULT / SPREAD / node-affinity / placement-group strategies
    (python/ray/util/scheduling_strategies.py:15,41,135 in the reference)."""

    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP
    node_id: Optional[str] = None
    soft: bool = False
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    name: str
    function_id: str                       # key into head KV function table
    args: List[Tuple] = field(default_factory=list)
    kwarg_names: List[str] = field(default_factory=list)  # trailing args are kwargs
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    max_retries: int = 0
    retry_exceptions: bool = False
    owner: str = ""                        # worker id hex of the submitter
    # actor fields
    actor_id: Optional[ActorID] = None
    class_name: str = ""                   # actor class, for observability
    method_name: str = ""
    seqno: int = 0
    max_restarts: int = 0
    max_concurrency: int = 1
    # options
    runtime_env: Optional[dict] = None
    # Dispatch-time speculative prefetch opt-out (r17): False excludes
    # this task's by-ref args from PREFETCH_HINT frames (grant-time
    # prefetch and demand fetches are unaffected). The data layer's
    # shuffle uses it as its hint A/B control
    # (`data_shuffle_prefetch_hints`).
    prefetch_args: bool = True
    # caller's active span context, (trace_id, parent_span_id), stamped at
    # submission so the executing worker parents its task span under the
    # submit site (reference: tracing_helper.py injecting the OpenTelemetry
    # context into the task spec's serialized runtime context)
    trace_ctx: Optional[Tuple[str, str]] = None
    # chip assignment stamped by the head at lease grant (the reference's
    # CUDA_VISIBLE_DEVICES resource-instance ids; exported to the task as
    # TPU_VISIBLE_CHIPS)
    tpu_ids: Optional[List[int]] = None

    def return_ids(self) -> List[ObjectID]:
        return [ObjectID.for_return(self.task_id, i + 1)
                for i in range(self.num_returns)]

    def scheduling_class(self) -> tuple:
        """Tasks with equal scheduling class can reuse each other's leased
        workers (reference: SchedulingClassDescriptor, task_spec.h:75)."""
        return (
            self.function_id if self.task_type == TaskType.NORMAL else self.task_id.hex(),
            tuple(sorted(self.resources.items())),
            self.strategy.kind,
            self.strategy.node_id,
            self.strategy.placement_group_id.hex()
            if self.strategy.placement_group_id else None,
            self.strategy.bundle_index,
        )


@dataclass
class Bundle:
    resources: Dict[str, float]


@dataclass
class PlacementGroupSpec:
    pg_id: PlacementGroupID
    bundles: List[Bundle]
    strategy: str = "PACK"  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    name: str = ""
    job_id: Optional[JobID] = None
