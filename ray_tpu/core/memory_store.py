"""In-process object store for resolved values and pending futures.

Analog of the reference's ``CoreWorkerMemoryStore``
(src/ray/core_worker/store_provider/memory_store/memory_store.h:43): holds
small/inlined objects and completed results locally so ``get`` on them never
touches the shared-memory store; unresolved ids carry waiter lists.

Waiting is count-based: a ``get`` on N refs registers ONE waiter carrying a
remaining-count on each missing id, and each arriving result decrements the
counts of that id's waiters. The waiting thread wakes exactly once — the
broadcast-and-rescan design this replaced cost O(results x N) rescans per
``get`` and dominated async task throughput at high rates.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from .ids import ObjectID


class _Entry:
    __slots__ = ("ready", "value", "is_error", "in_plasma", "node_idx",
                 "plasma_size")

    def __init__(self):
        self.ready = False
        self.value = None
        self.is_error = False
        self.in_plasma = False
        self.node_idx = -1
        self.plasma_size = 0  # sealed byte count when known (0 = unknown)


class _Waiter:
    __slots__ = ("needed", "event")

    def __init__(self, needed: int):
        self.needed = needed
        self.event = threading.Event()


class MemoryStore:
    def __init__(self):
        # RLock: evicting an entry can decref contained ObjectRefs whose
        # __del__ cascades (remove_local_ref -> borrow release -> evict)
        # back into this store while the lock is held.
        self._lock = threading.RLock()
        self._entries: Dict[ObjectID, _Entry] = {}
        self._callbacks: Dict[ObjectID, List[Callable]] = {}
        self._waiters: Dict[ObjectID, List[_Waiter]] = {}

    def _mark_ready_locked(self, oid: ObjectID):
        """Collect callbacks + satisfied waiters for a now-ready id.

        Caller holds the lock and must fire the returned items outside it.
        """
        cbs = self._callbacks.pop(oid, [])
        fired = []
        for w in self._waiters.pop(oid, ()):
            w.needed -= 1
            if w.needed <= 0:
                fired.append(w)
        return cbs, fired

    def put_value(self, oid: ObjectID, value: Any, is_error: bool = False):
        with self._lock:
            e = self._entries.setdefault(oid, _Entry())
            e.ready = True
            e.value = value
            e.is_error = is_error
            cbs, fired = self._mark_ready_locked(oid)
        for w in fired:
            w.event.set()
        for cb in cbs:
            cb()

    def put_plasma_location(self, oid: ObjectID, node_idx: int,
                            size: int = 0):
        """Record that the value lives in node `node_idx`'s shm store.
        ``size`` (when the caller knows it — the owner's put path does)
        lets the free path decide whether a prompt local arena delete is
        worth its syscall."""
        with self._lock:
            e = self._entries.setdefault(oid, _Entry())
            e.ready = True
            e.in_plasma = True
            e.node_idx = node_idx
            if size > 0:
                e.plasma_size = size
            cbs, fired = self._mark_ready_locked(oid)
        for w in fired:
            w.event.set()
        for cb in cbs:
            cb()

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(oid)
            return e is not None and e.ready

    def peek(self, oid: ObjectID) -> Optional[_Entry]:
        with self._lock:
            e = self._entries.get(oid)
            return e if (e and e.ready) else None

    def wait_ready(self, oids: Sequence[ObjectID], num_returns: int,
                   timeout: Optional[float]) -> List[ObjectID]:
        """Block until `num_returns` of `oids` are ready; returns ready list.

        Duplicate ids count once (callers compare against their unique set).
        """
        deadline = None if timeout is None else time.monotonic() + timeout
        uniq = list(dict.fromkeys(oids))
        num_returns = min(num_returns, len(uniq))
        with self._lock:
            missing = [o for o in uniq
                       if not ((e := self._entries.get(o)) and e.ready)]
            n_ready = len(uniq) - len(missing)
            if n_ready >= num_returns:
                ready = [o for o in uniq
                         if (e := self._entries.get(o)) and e.ready]
                return ready[:num_returns]
            w = _Waiter(num_returns - n_ready)
            for o in missing:
                self._waiters.setdefault(o, []).append(w)
        if deadline is None:
            w.event.wait()
        else:
            w.event.wait(max(0.0, deadline - time.monotonic()))
        with self._lock:
            for o in missing:
                lst = self._waiters.get(o)
                if lst is not None:
                    try:
                        lst.remove(w)
                    except ValueError:
                        pass
                    if not lst:
                        del self._waiters[o]
            ready = [o for o in uniq
                     if (e := self._entries.get(o)) and e.ready]
        return ready[:num_returns] if num_returns < len(ready) else ready

    def add_ready_callback(self, oid: ObjectID, cb: Callable):
        fire = False
        with self._lock:
            e = self._entries.get(oid)
            if e is not None and e.ready:
                fire = True
            else:
                self._callbacks.setdefault(oid, []).append(cb)
        if fire:
            cb()

    def evict(self, oid: ObjectID):
        with self._lock:
            self._entries.pop(oid, None)

    def num_entries(self) -> int:
        with self._lock:
            return len(self._entries)
