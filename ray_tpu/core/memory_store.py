"""In-process object store for resolved values and pending futures.

Analog of the reference's ``CoreWorkerMemoryStore``
(src/ray/core_worker/store_provider/memory_store/memory_store.h:43): holds
small/inlined objects and completed results locally so ``get`` on them never
touches the shared-memory store; unresolved ids carry waiter lists.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

from .ids import ObjectID


class _Entry:
    __slots__ = ("ready", "value", "is_error", "in_plasma", "node_idx")

    def __init__(self):
        self.ready = False
        self.value = None
        self.is_error = False
        self.in_plasma = False
        self.node_idx = -1


class MemoryStore:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._entries: Dict[ObjectID, _Entry] = {}
        self._callbacks: Dict[ObjectID, List[Callable]] = {}

    def put_value(self, oid: ObjectID, value: Any, is_error: bool = False):
        with self._cv:
            e = self._entries.setdefault(oid, _Entry())
            e.ready = True
            e.value = value
            e.is_error = is_error
            cbs = self._callbacks.pop(oid, [])
            self._cv.notify_all()
        for cb in cbs:
            cb()

    def put_plasma_location(self, oid: ObjectID, node_idx: int):
        """Record that the value lives in node `node_idx`'s shm store."""
        with self._cv:
            e = self._entries.setdefault(oid, _Entry())
            e.ready = True
            e.in_plasma = True
            e.node_idx = node_idx
            cbs = self._callbacks.pop(oid, [])
            self._cv.notify_all()
        for cb in cbs:
            cb()

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            e = self._entries.get(oid)
            return e is not None and e.ready

    def peek(self, oid: ObjectID) -> Optional[_Entry]:
        with self._lock:
            e = self._entries.get(oid)
            return e if (e and e.ready) else None

    def wait_ready(self, oids: Sequence[ObjectID], num_returns: int,
                   timeout: Optional[float]) -> List[ObjectID]:
        """Block until `num_returns` of `oids` are ready; returns ready list."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                ready = [o for o in oids
                         if (e := self._entries.get(o)) and e.ready]
                if len(ready) >= num_returns:
                    return ready[:num_returns] if num_returns < len(ready) else ready
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return ready
                    self._cv.wait(remaining)
                else:
                    self._cv.wait(1.0)

    def add_ready_callback(self, oid: ObjectID, cb: Callable):
        fire = False
        with self._lock:
            e = self._entries.get(oid)
            if e is not None and e.ready:
                fire = True
            else:
                self._callbacks.setdefault(oid, []).append(cb)
        if fire:
            cb()

    def evict(self, oid: ObjectID):
        with self._lock:
            self._entries.pop(oid, None)

    def num_entries(self) -> int:
        with self._lock:
            return len(self._entries)
