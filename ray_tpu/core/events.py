"""Task execution events: worker-side buffer -> head ring buffer.

The reference buffers task state transitions in each core worker and
flushes them to the GCS for the observability APIs
(src/ray/core_worker/task_event_buffer.h:199, flush period 1s, bounded
buffer with drop counting; surfaced by `ray list tasks` /
python/ray/util/state/api.py). Same shape here: every CoreContext owns a
TaskEventBuffer; a daemon flusher batches events to the head over the
existing connection (P.TASK_EVENTS), and the head keeps a bounded deque the
state API queries. Overflow drops the oldest events and counts the drops —
observability must never backpressure the task path.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from . import protocol as P
from .config import get_config

# task states (reference: src/ray/protobuf/common.proto TaskStatus)
SUBMITTED = "SUBMITTED"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"

FLUSH_PERIOD_S = 1.0


class TaskEventBuffer:
    """Owner/executor-side event buffer with periodic batched flush."""

    def __init__(self, head_conn, worker_id: str, node_idx: int):
        self._head = head_conn
        self._worker_id = worker_id
        self._node_idx = node_idx
        self._max = get_config().task_event_buffer_size
        # deque(maxlen): O(1) drop-oldest when the flusher falls behind.
        # append/popleft are GIL-atomic, so the hot path takes no lock
        # (a mutex here measurably dents the async-task benchmark).
        self._events: "deque" = deque(maxlen=self._max)
        self._dropped = 0  # approximate (see record)
        self._stop = threading.Event()
        self._flusher: Optional[threading.Thread] = None

    def start(self):
        self._flusher = threading.Thread(target=self._flush_loop,
                                         daemon=True, name="task-events")
        self._flusher.start()

    def record(self, task_id_hex: str, name: str, state: str,
               error: str = ""):
        ev = (task_id_hex, name, state, self._worker_id, self._node_idx,
              time.time(), error)
        if len(self._events) == self._max:
            self._dropped += 1  # deque(maxlen) evicts the oldest
        self._events.append(ev)

    def _flush_loop(self):
        while not self._stop.wait(FLUSH_PERIOD_S):
            self.flush()

    def flush(self):
        if not self._events:
            return
        batch = []
        try:
            while True:
                batch.append(self._events.popleft())
        except IndexError:
            pass
        dropped, self._dropped = self._dropped, 0
        try:
            self._head.send(P.TASK_EVENTS, batch, dropped)
        except P.ConnectionLost:
            pass

    def stop(self):
        self._stop.set()
        self.flush()
