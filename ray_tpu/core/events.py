"""Task execution events: worker-side buffer -> head ring buffer.

The reference buffers task state transitions in each core worker and
flushes them to the GCS for the observability APIs
(src/ray/core_worker/task_event_buffer.h:199, flush period 1s, bounded
buffer with drop counting; surfaced by `ray list tasks` /
python/ray/util/state/api.py). Same shape here: every CoreContext owns a
TaskEventBuffer; a daemon flusher batches events to the head over the
existing connection (P.TASK_EVENTS), and the head keeps a bounded deque the
state API queries. Overflow drops the oldest events and counts the drops —
observability must never backpressure the task path.

This module also owns the two companions of that channel:

* the ambient TRACE CONTEXT (reference: tracing_helper.py propagating
  OpenTelemetry span context across task submission) — a thread-local
  ``(trace_id, span_id)`` pair that task submission stamps into specs and
  task execution restores, so spans opened inside a remote task nest
  under the submitting span;
* the CLUSTER EVENT emitter (reference: the GCS structured event log
  behind ``ray list cluster-events``) — severity-tagged records any
  process can push to the head's bounded ring buffer.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional, Tuple

from . import protocol as P
from .config import get_config
from .ids import _random_bytes

# task states (reference: src/ray/protobuf/common.proto TaskStatus —
# PENDING_ARGS_AVAIL -> PENDING_NODE_ASSIGNMENT -> SUBMITTED_TO_WORKER ->
# RUNNING -> FINISHED). Each transition is stamped by the component that
# owns it; the head folds the stamps into per-task timelines with a
# per-phase latency breakdown (head.py task_timelines).
SUBMITTED = "SUBMITTED"                        # driver: task created
PENDING_ARGS_AVAIL = "PENDING_ARGS_AVAIL"      # driver: awaiting arg refs
PENDING_NODE_ASSIGNMENT = "PENDING_NODE_ASSIGNMENT"  # driver: queued for a
#                                                worker lease / actor conn
SUBMITTED_TO_WORKER = "SUBMITTED_TO_WORKER"    # driver: pushed to a worker
FETCHING_ARGS = "FETCHING_ARGS"                # worker: resolving by-ref args
RUNNING = "RUNNING"                            # worker: user code entered
FINISHED = "FINISHED"                          # worker: user code returned
FAILED = "FAILED"                              # worker raised, OR the
#                                                owner gave up (retries
#                                                exhausted / worker lost)
CANCELLED = "CANCELLED"                        # task cancelled
RETURNED = "RETURNED"                          # driver: result landed back

# Ordering of the lifecycle for "latest state" folding
# (FINISHED/FAILED/CANCELLED share a rank — all terminal execution
# states; RETURNED ranks past them but is never *displayed* as a task
# state, matching the reference's TaskStatus surface).
STATE_RANK = {
    SUBMITTED: 0,
    PENDING_ARGS_AVAIL: 1,
    PENDING_NODE_ASSIGNMENT: 2,
    SUBMITTED_TO_WORKER: 3,
    FETCHING_ARGS: 4,
    RUNNING: 5,
    FINISHED: 6,
    FAILED: 6,
    CANCELLED: 6,
    RETURNED: 7,
}

# THE phase definition table — the single source of truth shared by the
# head fold, `derive_phase_ms`, and timeline()'s chrome-trace
# sub-slices: (phase, start_states, end_states), first present stamp
# wins in order. Durations come from MONOTONIC stamps carried alongside
# the wall timestamps (wall is display-only); cross-node stamps are
# folded into the head's monotonic timebase via the per-node clock
# offsets before this math runs, and any residual skew clamps at 0 — a
# phase is never negative.
PHASE_BOUNDS = (
    ("sched_wait", (PENDING_NODE_ASSIGNMENT,), (SUBMITTED_TO_WORKER,)),
    ("dispatch", (SUBMITTED_TO_WORKER,), (FETCHING_ARGS,)),
    ("arg_fetch", (FETCHING_ARGS,), (RUNNING,)),
    ("exec", (RUNNING,), (FINISHED, FAILED)),
    ("result_return", (FINISHED, FAILED), (RETURNED,)),
    ("e2e", (SUBMITTED,), (RETURNED,)),
)
TASK_PHASES = tuple(name for name, _, _ in PHASE_BOUNDS)

# state -> the PHASE_BOUNDS entries that have this state as a start or
# an end. The head's fold only re-derives phases a newly-stamped state
# could have completed — deriving ALL six per folded event was a
# measurable slice of the fold thread's hot loop.
PHASES_TOUCHING = {}
for _pb in PHASE_BOUNDS:
    for _st in _pb[1] + _pb[2]:
        PHASES_TOUCHING.setdefault(_st, []).append(_pb)
del _pb, _st


def _first_stamp(stamps: dict, states) -> Optional[float]:
    for s in states:
        v = stamps.get(s)
        if v is not None:
            return v
    return None


def derive_phase_ms(monos: dict) -> dict:
    """Phase durations (ms, clamped >= 0) from a ``state -> monotonic``
    stamp map in ONE timebase. Only phases whose both endpoints are
    present appear — a running task shows sched_wait/dispatch/arg_fetch
    while exec/result_return/e2e fill in as it completes."""
    out = {}
    for name, starts, ends in PHASE_BOUNDS:
        a = _first_stamp(monos, starts)
        b = _first_stamp(monos, ends)
        if a is not None and b is not None:
            out[name] = max(0.0, (b - a) * 1000.0)
    return out

# cluster-event severities (reference: src/ray/protobuf/
# export_event.proto severity levels)
INFO = "INFO"
WARNING = "WARNING"
ERROR = "ERROR"

FLUSH_PERIOD_S = 1.0


# --------------------------------------------------------- trace context
#
# The ambient span context of the CURRENT thread: (trace_id, span_id).
# tracing.span() pushes/pops it; the executor installs the task's span
# for the duration of user code; submission reads it to stamp specs.

_trace_tls = threading.local()


def new_span_id() -> str:
    # pooled entropy, not uuid4: uuid4 hits os.urandom per call (~34 us
    # on the deployment kernel) and a span id is minted PER TASK
    return _random_bytes(8).hex()


def current_trace() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) of the active span, or None."""
    return getattr(_trace_tls, "ctx", None)


def set_trace(ctx: Optional[Tuple[str, str]]) -> Optional[Tuple[str, str]]:
    """Install an ambient span context; returns the previous one so the
    caller can restore it (executor entry/exit, span scopes)."""
    prev = getattr(_trace_tls, "ctx", None)
    _trace_tls.ctx = ctx
    return prev


def submit_trace_ctx() -> Tuple[str, str]:
    """Trace context to stamp into a task spec at submission: the active
    span's (trace_id, span_id), or a fresh root trace when the submit
    site has no span — every task then belongs to SOME trace, so spans
    opened inside it share one trace_id with the task."""
    ctx = current_trace()
    if ctx is not None:
        return ctx
    return (_random_bytes(16).hex(), "")


class TaskEventBuffer:
    """Owner/executor-side event buffer with periodic batched flush.

    Event tuples are ``(task_id_hex, name, state, worker_id, node_idx,
    ts, error, trace_id, span_id, parent_span_id, mono)`` — trace ids
    carry the cross-process trace tree (empty strings when untraced) and
    ``mono`` is the recorder's ``time.monotonic()``: wall ``ts`` is
    display-only, phase durations are computed from the monotonic stamps
    (folded into the head's timebase via per-node clock offsets).
    """

    def __init__(self, head_conn, worker_id: str, node_idx: int):
        self._head = head_conn
        self._worker_id = worker_id
        self._node_idx = node_idx
        self._max = get_config().task_event_buffer_size
        # deque(maxlen): O(1) drop-oldest when the flusher falls behind.
        # append/popleft are GIL-atomic, so the hot path takes no lock
        # (a mutex here measurably dents the async-task benchmark).
        self._events: "deque" = deque(maxlen=self._max)
        self._dropped = 0  # approximate (see record)
        # serializes drain+send across the periodic flusher and sync
        # flushes — without it a sync flush can find the deque already
        # drained by a preempted flusher whose send hasn't happened yet,
        # ack an empty batch, and break the ordering barrier
        self._flush_lock = threading.Lock()
        self._stop = threading.Event()
        self._flusher: Optional[threading.Thread] = None

    def start(self):
        self._flusher = threading.Thread(target=self._flush_loop,
                                         daemon=True, name="task-events")
        self._flusher.start()

    def record(self, task_id_hex: str, name: str, state: str,
               error: str = "", trace_id: str = "", span_id: str = "",
               parent_span_id: str = "", ts: Optional[float] = None,
               mono: Optional[float] = None):
        # ts/mono default to "now"; retroactive emitters (r19 comm
        # transfer spans, stamped at completion with the measured start)
        # pass both explicitly so the interval lands where it happened
        ev = (task_id_hex, name, state, self._worker_id, self._node_idx,
              time.time() if ts is None else ts, error, trace_id,
              span_id, parent_span_id,
              time.monotonic() if mono is None else mono)
        if len(self._events) == self._max:
            self._dropped += 1  # deque(maxlen) evicts the oldest
        self._events.append(ev)

    def _flush_loop(self):
        while not self._stop.wait(FLUSH_PERIOD_S):
            self.flush()

    def flush(self, sync: bool = False):
        """Push buffered events to the head. ``sync=True`` round-trips
        (the head replies only after ingesting the batch), making the
        flush an ordering barrier: a STATE_QUERY issued afterwards — on
        any connection — observes these events. Used by timeline() in
        place of the old sleep-and-hope."""
        if not self._events and not sync:
            return
        with self._flush_lock:
            batch = []
            try:
                while True:
                    batch.append(self._events.popleft())
            except IndexError:
                pass
            dropped, self._dropped = self._dropped, 0
            try:
                if sync:
                    self._head.call(P.TASK_EVENTS, batch, dropped,
                                    timeout=30)
                else:
                    self._head.send(P.TASK_EVENTS, batch, dropped)
            except P.ConnectionLost:
                pass

    def stop(self):
        self._stop.set()
        self.flush()


# --------------------------------------------------------- cluster events


def make_cluster_event(severity: str, source: str, event_type: str,
                       message: str, *, node_idx: int = -1,
                       entity_id: str = "", extra: Optional[dict] = None
                       ) -> tuple:
    """Wire tuple for one cluster event record."""
    return (time.time(), severity, source, node_idx, entity_id,
            event_type, message, dict(extra or {}))


def wire_backpressure_fields(peer: str, frames: int, nbytes: int) -> tuple:
    """(severity, source, type, message, extra) for a wire-saturation
    event — one source of truth for the two emit paths (a CoreContext
    sending to the head vs the head appending to its own ring)."""
    return ("WARNING", "wire", "wire_backpressure",
            f"write queue to {peer} hit its bound "
            f"({frames} frames / {nbytes} bytes queued)",
            {"peer": peer, "frames": frames, "bytes": nbytes})


def emit_cluster_event(severity: str, source: str, event_type: str,
                       message: str, *, node_idx: Optional[int] = None,
                       entity_id: str = "", extra: Optional[dict] = None):
    """Fire-and-forget a cluster event from any process with a live
    CoreContext (drivers, workers, actors — e.g. the job manager).
    Head-side code appends to the ring buffer directly instead."""
    from .context import get_context_if_exists

    ctx = get_context_if_exists()
    if ctx is None:
        return
    ev = make_cluster_event(
        severity, source, event_type, message,
        node_idx=ctx.node_idx if node_idx is None else node_idx,
        entity_id=entity_id, extra=extra)
    # never block the emitter on a head outage: a ReconnectingConnection
    # parks writes for the whole reconnect window, and this is called
    # from lock-held control paths (e.g. the serve reconcile thread)
    if not ctx.head.is_attached():
        return
    try:
        ctx.head.send(P.CLUSTER_EVENT, [ev], 0)
    except P.ConnectionLost:
        pass
