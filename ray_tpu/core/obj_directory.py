"""Sharded object directory: the head's holder-set map off the head lock.

Analog of the reference's ObjectDirectory
(src/ray/object_manager/object_directory.h) — but where r6 kept the map
as a plain dict guarded by the ONE head lock, every ``OBJ_LOCATION_ADD /
REMOVE / LOOKUP``, sealed report, locate, free, and broadcast-planner
holder query serialized against lease granting, PG math, and the event
fold on the head IO loop. This module extends the ``native/sched_core``
precedent of getting per-message hot paths off that lock: entries live
in N independently-locked shards (hash of the ObjectID picks the shard),
so directory traffic contends only with directory traffic for the same
shard — the GCS-vs-raylet split of the reference control plane, applied
to the object plane's metadata.

Invariants preserved from the r6/r9 design:

* per-object mutations (holders / waiters / inprog / serving) happen
  under that object's shard lock — the planner and
  ``_finish_pull_assignment`` share it, so an aborted puller can never
  be handed out as a relay after its failure is known
  (directory-staleness-on-abort guarantee);
* the LOST set (ids whose final copy is gone; owners must reconstruct)
  is a bounded FIFO with its own lock, checked/cleared by the same
  operations that touched it under the head lock before.

The head still owns everything that needs the NODE table (picking live
holder nodes, transfer addresses): those reads are GIL-atomic dict
lookups plus ``alive`` flags, tolerant of the same momentary staleness
the old lock-dropping paths already had.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .ids import ObjectID

# blocked-locate waiter: (connection, request_id)
Waiter = Tuple[object, int]

_LOST_CAP = 65536


@dataclass
class _ObjLoc:
    """Object directory entry (reference: ObjectDirectory,
    src/ray/object_manager/object_directory.h — the full HOLDER SET per
    object, not just the sealing node). ``node_idx`` stays the primary
    location for the single-location paths (locate replies, spill);
    ``holders`` is every node with a sealed copy and always contains
    ``node_idx`` while it is >= 0."""

    node_idx: int = -1
    size: int = 0
    owner: str = ""
    # memory-observatory attribution (stamped by record_sealed): the
    # sealing context's job id (hex), the wall-clock seal time (object
    # age in `ray_tpu memory` / list objects), and an optional reference
    # class tag ("checkpoint" — pipeline checkpoint refs — today;
    # empty = plain sealed object).
    job: str = ""
    sealed_at: float = 0.0
    tag: str = ""
    spilled_path: str = ""
    holders: Set[int] = field(default_factory=set)
    waiters: List[Waiter] = field(default_factory=list)
    # Cooperative broadcast (in-progress locations): nodes the head has
    # told to pull this object whose pull has not completed yet, mapped
    # to their transfer address — the planner may point LATER pullers at
    # them (chunk relay). Entries leave the moment the pull finishes
    # (promoted to ``holders``) or aborts (never handed out again).
    inprog: Dict[int, str] = field(default_factory=dict)
    # Stripe-weighted active downstream pulls per source transfer
    # address (sealed holders and relays alike): a pull striped across
    # k roots charges each 1/k — it only takes ~1/k of each uplink —
    # while a relay-served pull charges its one source a full 1.0. The
    # planner skips sources at the ``broadcast_fanout`` bound, which is
    # what bends N simultaneous pullers into a pipelined tree instead
    # of N streams off one uplink.
    serving: Dict[str, float] = field(default_factory=dict)


class ObjectDirectory:
    """N-sharded ``ObjectID -> _ObjLoc`` map with per-shard locks.

    The mapping surface (``in`` / ``[]`` / ``.get``) is lock-free reads
    of GIL-atomic dict ops — callers that MUTATE an entry or need a
    consistent read-modify-write take ``lock_for(oid)`` first (the same
    discipline the head lock provided, at per-shard granularity).
    """

    def __init__(self, n_shards: int = 16):
        self._n = n_shards
        self._shards: List[Dict[ObjectID, _ObjLoc]] = [
            {} for _ in range(n_shards)]
        self._locks = [threading.RLock() for _ in range(n_shards)]
        # ids sealed once whose last copy is gone (node death / eviction
        # with no spill): locates answer -2 so owners run lineage
        # reconstruction instead of blocking forever. FIFO-bounded — ids
        # whose owner died with the node would otherwise leak.
        self._lost: Dict[ObjectID, None] = {}
        self._lost_lock = threading.Lock()

    # ------------------------------------------------------ mapping surface

    def _shard(self, oid: ObjectID) -> Dict[ObjectID, _ObjLoc]:
        return self._shards[hash(oid) % self._n]

    def lock_for(self, oid: ObjectID) -> threading.RLock:
        return self._locks[hash(oid) % self._n]

    def __contains__(self, oid: ObjectID) -> bool:
        return oid in self._shard(oid)

    def __getitem__(self, oid: ObjectID) -> _ObjLoc:
        return self._shard(oid)[oid]

    def get(self, oid: ObjectID) -> Optional[_ObjLoc]:
        return self._shard(oid).get(oid)

    def __len__(self) -> int:
        return sum(len(s) for s in self._shards)

    def setdefault(self, oid: ObjectID) -> _ObjLoc:
        """Get-or-create under the shard lock (callers usually already
        hold it; RLock makes both call shapes safe)."""
        shard = self._shard(oid)
        loc = shard.get(oid)
        if loc is None:
            with self.lock_for(oid):
                loc = shard.get(oid)
                if loc is None:
                    loc = shard[oid] = _ObjLoc()
        return loc

    def pop(self, oid: ObjectID) -> Optional[_ObjLoc]:
        if oid not in self._shard(oid):  # lock-free miss fast path: the
            return None                  # free flood is mostly inline ids
        with self.lock_for(oid):
            return self._shard(oid).pop(oid, None)

    def values_snapshot(self) -> List[_ObjLoc]:
        """Point-in-time value list (per-shard consistent) for the
        state queries / spill candidate scans."""
        out: List[_ObjLoc] = []
        for shard, lock in zip(self._shards, self._locks):
            with lock:
                out.extend(shard.values())
        return out

    def items_snapshot(self) -> List[Tuple[ObjectID, _ObjLoc]]:
        out: List[Tuple[ObjectID, _ObjLoc]] = []
        for shard, lock in zip(self._shards, self._locks):
            with lock:
                out.extend(shard.items())
        return out

    def listing_rows(self) -> List[dict]:
        """state-API ``objects`` rows, with the mutable holder sets
        copied UNDER the shard locks — iterating a live entry's set
        after the snapshot lock is released can race a concurrent
        holder-add and raise mid-query."""
        rows: List[dict] = []
        now = time.time()
        for shard, lock in zip(self._shards, self._locks):
            with lock:
                for oid, loc in shard.items():
                    if loc.node_idx < 0 and not loc.spilled_path:
                        continue
                    rows.append({
                        "object_id": oid.hex(),
                        "node_idx": loc.node_idx,
                        "size": loc.size, "owner": loc.owner,
                        "job": loc.job, "tag": loc.tag,
                        "age_s": round(now - loc.sealed_at, 3)
                        if loc.sealed_at else 0.0,
                        "spilled": bool(loc.spilled_path),
                        "holders": sorted(loc.holders),
                    })
        return rows

    # ------------------------------------------------------------ LOST set

    def is_lost(self, oid: ObjectID) -> bool:
        return oid in self._lost

    def clear_lost(self, oid: ObjectID):
        if oid not in self._lost:  # lock-free miss fast path
            return
        with self._lost_lock:
            self._lost.pop(oid, None)

    def mark_lost(self, oids: Iterable[ObjectID]) -> List[Waiter]:
        """Drop directory entries whose final copy is gone and remember
        the ids as LOST (bounded FIFO) so later locates fail fast —
        owners react by re-executing the creating task (lineage
        reconstruction; reference: object_recovery_manager.h:41).
        Returns the blocked-locate waiters that must hear the LOST
        sentinel (reply OFF the caller's critical path)."""
        waiters: List[Waiter] = []
        for oid in oids:
            with self.lock_for(oid):
                loc = self._shard(oid).get(oid)
                if loc is not None and (loc.node_idx >= 0
                                        or loc.spilled_path):
                    # the lost-decision and this pop are separate lock
                    # holds now (the old head lock spanned both): a copy
                    # registered in the window (OBJ_LOCATION_ADD /
                    # re-seal racing a node death) means the object is
                    # NOT lost — keep the live entry
                    continue
                loc = self._shard(oid).pop(oid, None)
                if loc is not None:
                    waiters.extend(loc.waiters)
                    loc.waiters.clear()
            with self._lost_lock:
                self._lost[oid] = None
        with self._lost_lock:
            while len(self._lost) > _LOST_CAP:
                self._lost.pop(next(iter(self._lost)))
        return waiters

    # ------------------------------------------------- directory operations

    def record_sealed(self, oid: ObjectID, node_idx: int, size: int,
                      owner: str, job: str = ""
                      ) -> Tuple[int, int, List[Waiter]]:
        """OBJECT_SEALED bookkeeping; returns (node_idx, size, waiters
        to answer with the location)."""
        self.clear_lost(oid)  # a recovered object is found again
        with self.lock_for(oid):
            loc = self.setdefault(oid)
            loc.node_idx = node_idx
            loc.size = size
            loc.owner = owner
            if job:
                loc.job = job
            loc.sealed_at = time.time()
            loc.holders.add(node_idx)
            waiters = list(loc.waiters)
            loc.waiters.clear()
            return node_idx, size, waiters

    def tag_objects(self, oids: Iterable[ObjectID], tag: str):
        """Stamp a reference-class tag (e.g. ``"checkpoint"``) onto
        existing entries — the memory summary's class breakdown keys off
        it. Unknown ids are ignored (the object may have been freed)."""
        for oid in oids:
            with self.lock_for(oid):
                loc = self.get(oid)
                if loc is not None:
                    loc.tag = tag

    def add_location(self, oid: ObjectID, node_idx: int, size: int = 0
                     ) -> Tuple[int, int, List[Waiter]]:
        """A node gained a copy (pull completion / replica creation)."""
        self.clear_lost(oid)
        with self.lock_for(oid):
            loc = self.setdefault(oid)
            loc.holders.add(node_idx)
            if size > 0 and loc.size <= 0:
                loc.size = size
            if loc.node_idx < 0:
                loc.node_idx = node_idx
            waiters: List[Waiter] = []
            if loc.waiters:
                waiters = list(loc.waiters)
                loc.waiters.clear()
            return loc.node_idx, loc.size, waiters

    def remove_locations(self, oids: Iterable[ObjectID], node_idx: int
                         ) -> List[Waiter]:
        """Holder-set removal (arena eviction / local deletion); returns
        the blocked-locate waiters that must hear the LOST sentinel."""
        lost: List[ObjectID] = []
        for oid in oids:
            with self.lock_for(oid):
                loc = self.get(oid)
                # Only act when the node is a recorded holder: an
                # eviction report racing ahead of the sealing worker's
                # OBJECT_SEALED (different head connections —
                # cross-connection order is not guaranteed) must not
                # declare a never-sealed waiter entry LOST. The inverse
                # race (remove lands before the entry even exists,
                # leaving a stale holder once SEALED arrives) is benign:
                # pulls fail over off stale entries per-object.
                if loc is None or node_idx not in loc.holders:
                    continue
                loc.holders.discard(node_idx)
                if loc.node_idx == node_idx:
                    loc.node_idx = min(loc.holders) if loc.holders else -1
                if loc.node_idx < 0 and not loc.spilled_path:
                    # last copy evicted and nothing on disk: the object
                    # is LOST — same outcome as its node dying
                    lost.append(oid)
        return self.mark_lost(lost)

    def purge_node(self, idx: int, dead_addr: str = "") -> List[Waiter]:
        """Node death: drop the node from every holder set, retire its
        in-progress locations and its serving load (it can no longer be
        a relay), promote replicas, and mark sole-copy objects LOST."""
        lost: List[ObjectID] = []
        for shard, lock in zip(self._shards, self._locks):
            with lock:
                for oid, loc in shard.items():
                    loc.holders.discard(idx)
                    loc.inprog.pop(idx, None)
                    if dead_addr:
                        loc.serving.pop(dead_addr, None)
                    if loc.node_idx != idx:
                        continue
                    if loc.holders:
                        loc.node_idx = min(loc.holders)  # promote a replica
                    elif loc.spilled_path:
                        loc.node_idx = -1
                    else:
                        # location-less NOW: mark_lost's recheck (a copy
                        # registered between this hold and the pop keeps
                        # the entry alive) must see it as lost-unless-
                        # something-new-arrived
                        loc.node_idx = -1
                        lost.append(oid)
        return self.mark_lost(lost)

    def locality_scores(self, arg_ids) -> Tuple[Dict[int, int], int]:
        """Per-node bytes of the given args already resident there, plus
        the args' total size (read-only holder-set scan; GIL-atomic dict
        reads — momentary staleness is fine for a placement HINT)."""
        scores: Dict[int, int] = {}
        total = 0
        for ob in dict.fromkeys(arg_ids):  # a dup arg counts once
            loc = self.get(ObjectID(ob))
            if loc is None or loc.size <= 0:
                continue
            total += loc.size
            for h in list(loc.holders):
                scores[h] = scores.get(h, 0) + loc.size
        return scores, total
