"""CoreContext — the per-process core-worker runtime.

Analog of the reference's ``CoreWorker`` (src/ray/core_worker/core_worker.h:284
— Put :560, Get :667, Wait :706, SubmitTask :830, CreateActor :851,
SubmitActorTask :897) plus its direct task transport
(transport/direct_task_transport.h:75, direct_actor_task_submitter.h:67).
Every process — driver and workers alike — runs one CoreContext: a single IO
thread multiplexing the head connection (GCS+raylet client) and direct
worker-to-worker connections; an in-process memory store for futures; a
shared-memory store client for large objects; a submitter that leases workers
per scheduling class and pushes tasks directly to them; and (in workers) the
task executor.
"""

from __future__ import annotations

import itertools
import os
import queue as queue_mod
import threading
import time
import traceback
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import protocol as P
from .config import get_config
from .exceptions import (ActorDiedError, ActorUnavailableError, GetTimeoutError,
                         ObjectLostError, RayTaskError, TaskCancelledError,
                         TaskError, WorkerCrashedError)
from .function_manager import FunctionManager
from .ids import ActorID, JobID, ObjectID, TaskID, WorkerID
from .memory_store import MemoryStore
from .object_ref import ObjectRef
from .object_store import ShmObjectStore
from .ref_counter import ReferenceCounter
from .serialization import SerializedValue, deserialize, serialize
from . import events as task_events
from .task_spec import (ARG_REF, ARG_VALUE, SchedulingStrategy, TaskSpec,
                        TaskType)

_context: Optional["CoreContext"] = None
_context_lock = threading.Lock()


def get_context() -> "CoreContext":
    if _context is None:
        raise RuntimeError("ray_tpu not initialized — call ray_tpu.init()")
    return _context


def get_context_if_exists() -> Optional["CoreContext"]:
    return _context


def set_context(ctx: Optional["CoreContext"]):
    global _context
    _context = ctx


class _LeasedWorker:
    __slots__ = ("worker_id", "addr", "lease_id", "conn", "inflight",
                 "idle_since", "tpu_ids", "hinted")

    def __init__(self, worker_id, addr, lease_id, conn, tpu_ids=None):
        self.worker_id = worker_id
        self.addr = addr
        self.lease_id = lease_id
        self.conn = conn
        self.inflight: Dict[TaskID, TaskSpec] = {}
        self.idle_since = time.monotonic()
        self.tpu_ids = tpu_ids
        self.hinted = None  # recently PREFETCH_HINTed arg ids (r14 dedupe)


_HINT_CACHE_MAX = 512


def _filter_hint_ids(hinted: dict, ids, now: float, ttl: float) -> list:
    """PREFETCH_HINT dedupe filter (r14): drop ids hinted for this
    lease/actor within ``ttl`` seconds, stamp the survivors, and keep
    the per-holder cache bounded (expired entries evicted first, then
    oldest-stamped — insertion order tracks stamp order because
    re-stamps delete+reinsert)."""
    fresh = []
    for ab in ids:
        ts = hinted.get(ab)
        if ts is not None and now - ts < ttl:
            continue
        hinted.pop(ab, None)
        hinted[ab] = now
        fresh.append(ab)
    if len(hinted) > _HINT_CACHE_MAX:
        for k in [k for k, ts in hinted.items() if now - ts >= ttl]:
            del hinted[k]
        while len(hinted) > _HINT_CACHE_MAX:
            del hinted[next(iter(hinted))]
    return fresh


class _ClassState:
    __slots__ = ("queue", "workers", "pending_leases", "lease_req_ts")

    def __init__(self):
        self.queue: deque = deque()
        self.workers: List[_LeasedWorker] = []
        self.pending_leases = 0
        self.lease_req_ts = 0.0  # when leases were last requested


class _ActorState:
    __slots__ = ("actor_id", "state", "addr", "conn", "queue", "inflight",
                 "seqno", "lock", "resolving", "death_cause", "connecting",
                 "hinted")

    def __init__(self, actor_id):
        self.connecting = False
        self.actor_id = actor_id
        self.state = "UNKNOWN"
        self.addr = ""
        self.conn: Optional[P.Connection] = None
        self.queue: deque = deque()
        self.inflight: Dict[TaskID, TaskSpec] = {}
        self.seqno = itertools.count()
        self.lock = threading.Lock()
        self.resolving = False
        self.death_cause = ""
        self.hinted = None  # recently PREFETCH_HINTed arg ids (r14 dedupe)


class _InflightTask:
    __slots__ = ("spec", "arg_ids", "retries_left", "contained_holder",
                 "worker")

    def __init__(self, spec, arg_ids, retries_left, contained_holder):
        self.spec = spec
        self.arg_ids = arg_ids
        self.retries_left = retries_left
        self.contained_holder = contained_holder  # keeps ObjectRefs alive
        self.worker: Optional[_LeasedWorker] = None  # set when dispatched


class CoreContext:
    def __init__(self, head_addr: str, session_dir: str, node_idx: int,
                 worker_id: Optional[str] = None, is_driver: bool = False,
                 job_id: Optional[JobID] = None):
        self.head_addr = head_addr
        self.session_dir = session_dir
        self.node_idx = node_idx
        self.is_driver = is_driver
        self.worker_id = worker_id or WorkerID.from_random().hex()
        self.job_id = job_id or JobID.from_int(1)
        # thread-local: threaded actors (max_concurrency > 1) execute tasks
        # concurrently, and put() stamps ObjectIDs with the current task id
        self._task_tls = threading.local()
        self._default_task_id = TaskID.for_driver(self.job_id)
        self._put_index = itertools.count(1)

        self.memory_store = MemoryStore()
        self.ref_counter = ReferenceCounter(
            self.worker_id, self._free_owned_object, self._release_borrow)

        # executor / misc state (must exist before any thread starts)
        self.assigned_tpu_ids: List[int] = []
        self._exec_queue: "queue_mod.Queue" = queue_mod.Queue()
        # batched task completions (run_executor): per-connection reply
        # buffer shared with the reply-flusher thread
        self._reply_buf: Dict[P.Connection, list] = {}
        self._reply_n = 0
        self._reply_lock = threading.Lock()
        self._reply_event = threading.Event()
        self._actor_instance = None
        self._actor_spec: Optional[TaskSpec] = None
        self._cancelled: set = set()
        self._pinned: set = set()
        self._contained: Dict[ObjectID, list] = {}
        self._free_buf: list = []       # buffered OBJECT_FREE id bins
        self._free_lock = threading.Lock()
        # Borrow-handoff pins: refs we shipped inside a task RESULT stay
        # pinned here for a grace window, so our BORROW_REMOVE cannot
        # outrun the receiver's BORROW_ADD at the owner (chained borrow
        # handoff, e.g. queue actors relaying refs). The reference closes
        # this with borrow metadata embedded in replies; a TTL pin gives
        # the same practical guarantee.
        self._handoff_pins: deque = deque()
        self._handoff_lock = threading.Lock()
        self._shutdown = False
        self._async_loop = None
        self._actors: Dict[ActorID, _ActorState] = {}
        self._pub_handlers: Dict[str, List] = {}
        self._pub_lock = threading.Lock()
        # job-level runtime_env (init(runtime_env=...)): default for every
        # task/actor submitted by this process unless overridden per-spec
        self.job_runtime_env: Optional[dict] = None

        self.io = P.IOLoop(f"io-{self.worker_id[:6]}")
        # Own listener for direct pushes from peers. On a remote node
        # (RAY_TPU_NODE_IP set by its agent) listen on TCP so workers on
        # other hosts can push tasks directly (the reference's
        # CoreWorkerService over gRPC); same-host clusters use unix sockets.
        node_ip = os.environ.get("RAY_TPU_NODE_IP", "")
        if node_ip:
            self._listener = P.listen_tcp("0.0.0.0", 0)
            port = self._listener.getsockname()[1]
            self.listen_path = ""
            self.listen_addr = f"tcp:{node_ip}:{port}"
        else:
            self.listen_path = os.path.join(
                session_dir, f"w_{self.worker_id[:12]}.sock")
            self.listen_addr = f"unix:{self.listen_path}"
            self._listener = P.listen_unix(self.listen_path)
        self.io.add_listener(self._listener, self._on_accept)

        # Head connection (GCS + raylet client). Reconnecting (GCS-FT
        # analog: workers and drivers keep their GCS channel across a
        # gcs_server restart): on ConnectionLost the channel re-dials
        # with backoff up to head_reconnect_timeout_s, re-registers this
        # process (re-claiming its actor identity if it hosts one),
        # re-subscribes pubsub channels, and replays parked call()s —
        # only past the deadline does on_close fire with the old
        # fail-fast semantics (workers exit; driver calls raise).
        self.head = P.ReconnectingConnection(
            head_addr, client_id=self.worker_id, peer="head",
            on_reattach=self._on_head_reattach)
        self.head.on_close = self._on_head_close
        self.io.add_connection(self.head, self._on_head_message)
        self.io.start()

        reply = self.head.call(P.REGISTER, self.worker_id, os.getpid(),
                               self.listen_addr, node_idx, timeout=30)
        store_name = reply[0]
        self.store = ShmObjectStore(store_name)
        # arena evictions drop this node's copy: tell the object directory
        # so pulls stop being routed at a holder that no longer holds
        # (reference: ObjectDirectory location removal on eviction).
        # Async: evict() fires inside store.create on whatever thread is
        # allocating — including the puller IO thread under its buffer
        # lock — and a blocking socket write there would stall every
        # in-flight transfer on this host.
        self.store.on_evict = self._report_evictions_async
        self._stores_by_node: Dict[int, ShmObjectStore] = {node_idx: self.store}

        self.fn_manager = FunctionManager(self.kv_put, self.kv_get)

        # task-state events -> head ring buffer (state API / `list tasks`)
        self.events = task_events.TaskEventBuffer(
            self.head, self.worker_id, node_idx)
        self.events.start()

        # wire saturation -> cluster event log: a connection's write
        # queue hitting its bound means the socket isn't draining; the
        # events page should show it instead of it failing silently
        # (protocol rate-limits the callback per connection)
        P.set_backpressure_callback(self._on_wire_backpressure)
        # the metrics pusher normally starts with the first Metric object;
        # start it unconditionally so the wire fast-path counters
        # (frames coalesced, batched completions, zero-copy bytes) reach
        # the head aggregate from every process
        from ray_tpu import metrics as _metrics

        _metrics._ensure_pusher()

        # submitter
        self._classes: Dict[tuple, _ClassState] = {}
        self._inflight: Dict[TaskID, _InflightTask] = {}
        self._return_to_task: Dict[ObjectID, TaskID] = {}
        # Lineage cache: plasma-resident task results -> creating TaskSpec,
        # kept past task completion so a lost object can be reconstructed by
        # re-executing its task (reference: lineage pinning in the owner's
        # ReferenceCounter + ObjectRecoveryManager::RecoverObject,
        # object_recovery_manager.h:41). FIFO-capped; put() objects are
        # not reconstructable, matching the reference.
        self._lineage: "OrderedDict[ObjectID, TaskSpec]" = OrderedDict()
        self._recovering: set = set()  # TaskIDs being re-executed
        # borrowed-ref owners, for routing reconstruction requests
        self._known_owners: Dict[ObjectID, str] = {}
        self._dep_unready: set = set()  # actor tasks awaiting arg resolution
        # PREFETCH_HINT accounting (r14): frames actually sent vs arg
        # ids suppressed by the per-lease/per-actor dedupe window;
        # r15 adds coalescing — hints buffer per destination key and
        # flush from the submitter loop as ONE frame, so a pipeline hot
        # loop pushing fresh per-microbatch refs doesn't emit a frame
        # per pushed batch. prefetch_hints_coalesced counts the frames
        # saved (hint batches merged into an already-pending flush).
        self.prefetch_hints_sent = 0
        self.prefetch_hints_suppressed = 0
        self.prefetch_hints_coalesced = 0
        # r16: hint-buffer values are [arg_ids, inline_ids] — the
        # second list tags which ids are INLINE-PROMOTED objects
        # (_promote_if_needed materialized a tiny owner value into the
        # store only so a borrower could fetch it, e.g. a pipeline
        # backward cotangent); the head counts their pulls apart so
        # the prefetch waste-ratio check measures only real
        # speculation. Bounded id memory below.
        self._hint_buf: "OrderedDict[str, list]" = OrderedDict()
        self._hint_lock = threading.Lock()
        self._inline_promoted: "OrderedDict[bytes, None]" = OrderedDict()
        self._sub_lock = threading.RLock()
        self._submit_event = threading.Event()
        self._submitter = threading.Thread(target=self._submitter_loop,
                                           daemon=True, name="submitter")
        self._submitter.start()


    # ================================================== connections / IO

    def _on_accept(self, sock, addr):
        conn = P.Connection(sock, peer="peer-in")
        self.io.add_connection(conn, self._on_peer_message)

    def _on_peer_message(self, conn: P.Connection, msg):
        mt = msg[0]
        if mt == P.PUSH_TASK:
            self._exec_queue.put((msg[2], conn))
        elif mt == P.PUSH_TASK_BATCH:
            for spec in msg[2]:
                self._exec_queue.put((spec, conn))
        elif mt == P.PUSH_CANCEL:
            self._cancelled.add(TaskID(msg[2]))
        elif mt == P.TASK_REPLY:
            self._handle_task_reply(conn, *msg[2:])
        elif mt == P.TASK_DONE_BATCH:
            # one frame, many completions (the return-side mirror of
            # PUSH_TASK_BATCH) — unpickled once, bookkeeping cleared
            # under ONE lock hold, one submitter wakeup for the frame
            self._handle_task_reply_batch(conn, msg[2])

    def _on_head_message(self, conn: P.Connection, msg):
        mt = msg[0]
        if mt == P.PUSH_TASK:
            # actor creation task pushed by the head scheduler
            self._exec_queue.put((msg[2], conn))
        elif mt == P.LEASE_GRANT_BATCH:
            # one batched dispatch pass granted several of our queued
            # lease requests in ONE frame: complete each blocked
            # _request_lease call() with its LEASE_REPLY-shaped fields
            for rid, worker_id, addr, lease_id, tpu_ids in msg[2]:
                if not self.head.complete_reply(
                        rid, (True, worker_id, addr, lease_id, None,
                              tpu_ids)):
                    # requester thread gave up (shutdown): return the
                    # lease so the worker doesn't leak
                    try:
                        self.head.send(P.RETURN_WORKER, lease_id,
                                       worker_id)
                    except P.ConnectionLost:
                        pass
        elif mt == P.PUBLISH:
            channel, payload = msg[2], msg[3]
            with self._pub_lock:
                handlers = list(self._pub_handlers.get(channel, ()))
            from .serialization import loads

            data = loads(payload)
            for h in handlers:
                try:
                    h(data)
                except Exception:
                    traceback.print_exc()
        elif mt == P.BORROW_ADD:
            self.ref_counter.add_borrower(ObjectID(msg[2]), msg[3])
        elif mt == P.BORROW_REMOVE:
            self.ref_counter.remove_borrower(ObjectID(msg[2]), msg[3])
        elif mt == P.RECOVER_OBJECT:
            # a borrower hit a lost object we own — reconstruct off the IO
            # thread (recovery does blocking head calls)
            oid = ObjectID(msg[2])
            threading.Thread(target=self._recover_object, args=(oid,),
                             daemon=True).start()
        elif mt == P.KILL_ACTOR:
            os._exit(0)

    def _on_wire_backpressure(self, peer: str, frames: int, nbytes: int):
        """protocol.set_backpressure_callback target (already off the
        send hot path, on a short-lived thread)."""
        if self._shutdown:
            return
        try:
            sev, src, etype, msg, extra = \
                task_events.wire_backpressure_fields(peer, frames, nbytes)
            task_events.emit_cluster_event(sev, src, etype, msg,
                                           extra=extra)
        except Exception:  # noqa: BLE001 — observability must never wedge
            pass

    def _on_head_close(self, conn):
        # fires only once the reconnecting channel gives up (reconnect
        # window expired) or on deliberate shutdown — transient head
        # loss within head_reconnect_timeout_s never reaches here
        if not self._shutdown and not self.is_driver:
            # head gone — worker exits (reference: raylet death kills workers)
            os._exit(1)

    def _on_head_reattach(self, conn):
        """Reconnector-thread hook: the head channel came back — the
        peer may be a RESTARTED head with empty worker/actor tables.
        Re-register this process (with its actor spec, so a surviving
        actor worker re-claims its identity and named actors keep their
        state), re-subscribe every pubsub channel, and nudge the
        submitter so queued work re-requests leases. Runs BEFORE parked
        senders and replayed call()s resume.

        The node this process lives on may itself still be
        re-registering (its agent races us on an independent channel):
        REGISTER is retried while the head answers "no node"."""
        if self._shutdown:
            return
        aspec = None
        if self._actor_spec is not None:
            from .serialization import dumps as _dumps

            aspec = _dumps(self._actor_spec)
        deadline = time.monotonic() + \
            get_config().head_reconnect_timeout_s
        while True:
            try:
                conn.call(P.REGISTER, self.worker_id, os.getpid(),
                          self.listen_addr, self.node_idx, aspec,
                          timeout=10)
                break
            except P.ConnectionLost:
                raise  # socket died again: the reconnector retries
            except Exception:
                # most likely "no node N" — our agent hasn't finished
                # its own re-registration yet
                if time.monotonic() > deadline or self._shutdown:
                    raise
                time.sleep(0.2)
        with self._pub_lock:
            channels = list(self._pub_handlers)
        for ch in channels:
            conn.send(P.SUBSCRIBE, ch)
        ev = getattr(self, "_submit_event", None)
        if ev is not None:  # a reattach can race __init__'s tail
            ev.set()

    def subscribe(self, channel: str, handler, *, ack: bool = True):
        """``ack=False`` sends the subscription one-way — frames on this
        connection are processed in order, so anything we send AFTER it
        is sequenced behind the registration. The actor-watch hot path
        uses it: a blocking round trip per created actor serializes
        mass actor creation behind a busy head (and the initial-state
        race it would close is already covered by the GET_ACTOR fallback
        in _resolve_actor)."""
        with self._pub_lock:
            first = channel not in self._pub_handlers
            self._pub_handlers.setdefault(channel, []).append(handler)
        if first:
            if ack:
                self.head.call(P.SUBSCRIBE, channel, timeout=30)
            else:
                self.head.send(P.SUBSCRIBE, channel)

    def unsubscribe(self, channel: str, handler) -> None:
        """Remove one handler registered via ``subscribe``. The head
        subscription itself stays (cheap; channels are few and other
        handlers may share it) — this exists so long-lived drivers
        that register per-object handlers (e.g. a Pipeline's drain
        watchers) can drop them at shutdown instead of growing the
        handler list forever."""
        with self._pub_lock:
            lst = self._pub_handlers.get(channel)
            if lst is not None:
                try:
                    lst.remove(handler)
                except ValueError:
                    pass

    def publish(self, channel: str, data):
        from .serialization import dumps

        self.head.send(P.PUBLISH, channel, dumps(data))

    # ================================================== KV

    def kv_put(self, ns, key, value, overwrite=True) -> bool:
        return self.head.call(P.KV_PUT, ns, key, value, overwrite,
                              timeout=30)[0]

    def kv_get(self, ns, key):
        return self.head.call(P.KV_GET, ns, key, timeout=30)[0]

    def kv_del(self, ns, key) -> bool:
        return self.head.call(P.KV_DEL, ns, key, timeout=30)[0]

    def kv_keys(self, ns, prefix="") -> list:
        return self.head.call(P.KV_KEYS, ns, prefix, timeout=30)[0]

    # ================================================== put / get / wait

    @property
    def current_task_id(self):
        return getattr(self._task_tls, "task_id", self._default_task_id)

    @current_task_id.setter
    def current_task_id(self, tid):
        self._task_tls.task_id = tid

    @property
    def current_job_id(self):
        """The job whose code is running on THIS thread: the executing
        task's spec.job_id inside a task/actor method, this context's
        own job otherwise (driver puts). Seal reports stamp it onto
        directory entries for per-job memory attribution."""
        return getattr(self._task_tls, "job_id", None) or self.job_id

    @current_job_id.setter
    def current_job_id(self, jid):
        self._task_tls.job_id = jid

    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.for_put(self.current_task_id, next(self._put_index))
        sv = serialize(value)
        self.ref_counter.add_owned(oid)
        if sv.contained_refs:
            # Inner refs stay alive at least as long as the outer object is
            # tracked by this owner (simplified containment pinning; the
            # reference tracks contained ids in the outer's metadata).
            # They also count as SHARED: a peer that fetches the outer
            # object deserializes them and its BORROW_ADD may still be in
            # flight when our containment pin drops — the free must take
            # the grace window.
            self._contained[oid] = list(sv.contained_refs)
            for r in sv.contained_refs:
                self.ref_counter.mark_shared(r.id)
        total = self.store.put_serialized(oid, sv.frames)
        # size on the wire is DATA bytes (sv.total_bytes): the whole
        # transfer plane (stripe ranges, pull buffers, relay parts)
        # keys on it; store-exact accounting compares against
        # memory_stats()["sealed_data_bytes"], which counts the same
        self.head.send(P.OBJECT_SEALED, oid.binary(), self.node_idx,
                       sv.total_bytes, self.worker_id,
                       self.current_job_id.hex())
        self.memory_store.put_plasma_location(oid, self.node_idx,
                                              size=total)
        return ObjectRef(oid, self.worker_id)

    def tag_objects(self, refs, tag: str):
        """Stamp a reference-class tag (memory observatory) onto the
        head directory entries behind ``refs`` — e.g. the pipeline
        tags its held checkpoint refs "checkpoint" so `ray_tpu memory`
        can split resident bytes by what is holding them. One-way and
        advisory: unsealed/freed ids are ignored by the head."""
        oid_bins = [(r.id if hasattr(r, "id") else r).binary()
                    for r in refs]
        if not oid_bins:
            return
        try:
            self.head.send(P.OBJ_TAG, oid_bins, tag)
        except P.ConnectionLost:
            pass

    def _report_evictions_async(self, oids: Sequence[ObjectID]):
        """store.on_evict hook: report off-thread so the allocating thread
        (often the puller IO thread) never blocks on a head socket write."""
        from .object_transfer import send_eviction_report_async

        if self._shutdown:
            return
        send_eviction_report_async(self.head, self.node_idx, oids)

    def _report_evictions(self, oids: Sequence[ObjectID]):
        """Synchronous variant — deterministic for tests that must observe
        the directory update before their next head call."""
        from .object_transfer import send_eviction_report

        if self._shutdown:
            return
        send_eviction_report(self.head, self.node_idx, oids)

    def get(self, refs: Sequence[ObjectRef], timeout: Optional[float] = None
            ) -> List[Any]:
        oids = [r.id for r in refs]
        self._ensure_resolution(refs)
        ready = self.memory_store.wait_ready(oids, len(oids), timeout)
        if len(ready) < len(set(oids)):
            raise GetTimeoutError(
                f"get() timed out after {timeout}s; "
                f"{len(set(oids)) - len(ready)} objects pending")
        return [self._resolve_value(oid) for oid in oids]

    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float], fetch_local: bool = True
             ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        self._ensure_resolution(refs)
        ready_ids = set(self.memory_store.wait_ready(
            [r.id for r in refs], num_returns, timeout))
        ready, rest = [], []
        for r in refs:
            if r.id in ready_ids and len(ready) < num_returns:
                ready.append(r)
            else:
                rest.append(r)
        return ready, rest

    def as_future(self, ref: ObjectRef):
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._ensure_resolution([ref])

        def _cb():
            try:
                fut.set_result(self._resolve_value(ref.id))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        self.memory_store.add_ready_callback(ref.id, _cb)
        return fut

    def _resolve_value(self, oid: ObjectID) -> Any:
        attempts = get_config().object_recovery_max_attempts
        last_err: Optional[Exception] = None
        for attempt in range(attempts + 1):
            e = self.memory_store.peek(oid)
            if e is None:
                # a concurrent _recover_object evicts the entry before the
                # re-executed task reseals it — wait, don't declare lost
                with self._sub_lock:
                    pending = oid in self._return_to_task
                if pending:
                    if not self.memory_store.wait_ready([oid], 1,
                                                        timeout=120):
                        raise GetTimeoutError(
                            f"timed out reconstructing {oid.hex()}")
                    continue
                raise ObjectLostError(oid.hex())
            if e.is_error:
                err = e.value
                if isinstance(err, TaskError):
                    raise RayTaskError(err)
                raise err
            if not e.in_plasma or e.value is not None:
                return e.value
            try:
                e.value = self._fetch_from_plasma(oid, e.node_idx)
                return e.value
            except GetTimeoutError:
                raise
            except Exception as fetch_err:  # noqa: BLE001 — copies lost
                last_err = fetch_err
                if attempt >= attempts:
                    break
                if self._recover_object(oid):
                    if not self.memory_store.wait_ready([oid], 1,
                                                        timeout=120):
                        raise GetTimeoutError(
                            f"timed out reconstructing {oid.hex()}")
                    continue
                owner = self._known_owners.get(oid)
                if not owner or owner == self.worker_id:
                    break
                # borrowed ref: the lineage lives with the owner — ask it
                # to reconstruct, then re-locate (blocking) from scratch
                self.memory_store.evict(oid)
                self._pinned.discard(oid)
                self._background_fetch(oid)
        raise ObjectLostError(
            f"{oid.hex()}: all copies lost and not reconstructable "
            f"({last_err})") from last_err

    def _fetch_from_plasma(self, oid: ObjectID, node_idx: int) -> Any:
        if not self.store.contains(oid):
            # Pull to the local node's store (reference: PullManager). The
            # contains() probe comes FIRST: with locality-aware placement
            # this node is often already a holder even when the locate
            # reply named another node as primary — a local sealed copy
            # means zero transfer RPCs and zero bytes moved.
            self.head.call(P.OBJECT_TRANSFER, oid.binary(), self.node_idx,
                           timeout=120)
        # pin_borrows: out-of-band frames come back as ledger-tracked
        # views, so a value that ALIASES arena memory (numpy oob
        # reconstruction, the r13 device-array rebuild) keeps the entry
        # pinned for its own lifetime — a free/spill racing the live
        # view defers instead of recycling the slot under it
        frames = self.store.get_frames(oid, pin_borrows=True)
        if frames is None:
            raise ObjectLostError(f"{oid.hex()} not in local store")
        self._pinned.add(oid)
        return deserialize(frames)

    def _ensure_resolution(self, refs: Sequence[ObjectRef]):
        """For refs we don't own and aren't already expecting, fetch in the
        background so wait_ready can complete."""
        for r in refs:
            oid = r.id
            if self.memory_store.contains(oid):
                continue
            with self._sub_lock:
                expected = oid in self._return_to_task
            if expected:
                continue
            t = threading.Thread(target=self._background_fetch, args=(oid,),
                                 daemon=True)
            t.start()

    def _background_fetch(self, oid: ObjectID):
        attempts = get_config().object_recovery_max_attempts
        for attempt in range(attempts + 1):
            try:
                node_idx, size, spilled = self.head.call(
                    P.OBJECT_LOCATE, oid.binary(), True, timeout=None)
            except Exception:
                return
            if node_idx != -2:
                self.memory_store.put_plasma_location(oid, node_idx)
                return
            # lost with its node — reconstruct (we own it) or ask the
            # owner, who holds the lineage, to (we borrowed it)
            if self._recover_object(oid):
                return  # re-execution repopulates the entry on reply
            owner = self._known_owners.get(oid)
            if owner and owner != self.worker_id and attempt < attempts:
                try:
                    self.head.send(P.RECOVER_OBJECT, oid.binary(), owner)
                except P.ConnectionLost:
                    break
                # give the owner a beat to clear the LOST marker, then the
                # blocking locate above waits for the re-seal
                time.sleep(0.2 * (attempt + 1))
                continue
            break
        self.memory_store.put_value(
            oid, ObjectLostError(
                f"{oid.hex()}: all copies lost and no lineage"),
            is_error=True)

    def _recover_object(self, oid: ObjectID) -> bool:
        """Lineage reconstruction (reference: ObjectRecoveryManager::
        RecoverObject, object_recovery_manager.h:41): re-execute the task
        that created a lost object, reusing its TaskID so the re-sealed
        results land under the same ObjectIDs consumers already hold.
        Returns False when the object has no retained lineage (e.g. a
        put() object, or evicted from the FIFO lineage cache)."""
        with self._sub_lock:
            spec = self._lineage.get(oid)
            if spec is None:
                return False
            if spec.task_id in self._recovering or \
                    spec.task_id in self._inflight:
                return True  # re-execution already underway
            self._recovering.add(spec.task_id)
        returns = spec.return_ids()
        # Un-mark LOST head-side so consumers' blocking locates queue for
        # the re-seal instead of failing fast.
        try:
            self.head.send(P.OBJECT_RECOVERING,
                           [r.binary() for r in returns])
        except P.ConnectionLost:
            with self._sub_lock:
                self._recovering.discard(spec.task_id)
            return False
        # Recover lost plasma args first (recursive lineage walk): the
        # executing worker's blocking locate then waits for their re-seal.
        # An arg that is lost AND unrecoverable (freed, or lineage evicted)
        # aborts the whole recovery — enqueueing anyway would wedge the
        # executing worker on a locate that can never be answered.
        for enc in spec.args:
            if enc[0] != ARG_REF:
                continue
            aid = ObjectID(enc[1])
            e = self.memory_store.peek(aid)
            if e is not None and not e.in_plasma:
                continue  # inline value still in the in-process store
            try:
                node_idx, _, spilled = self.head.call(
                    P.OBJECT_LOCATE, aid.binary(), False, timeout=30)
            except Exception:  # noqa: BLE001
                continue
            if node_idx == -2 or (node_idx < 0 and not spilled):
                if not self._recover_object(aid):
                    with self._sub_lock:
                        self._recovering.discard(spec.task_id)
                    return False
        # Register the re-execution BEFORE evicting the stale entries:
        # concurrent getters that peek a missing entry check
        # _return_to_task and wait instead of raising ObjectLostError.
        if spec.strategy.kind == "NODE_AFFINITY":
            # the original placement may name a dead node — reconstruction
            # is free to run anywhere
            spec.strategy = SchedulingStrategy()
        inflight = _InflightTask(spec, [], spec.max_retries, [])
        cls = spec.scheduling_class()
        with self._sub_lock:
            self._inflight[spec.task_id] = inflight
            for roid in returns:
                self._return_to_task[roid] = spec.task_id
        for roid in returns:
            self.memory_store.evict(roid)
            self._pinned.discard(roid)
        with self._sub_lock:
            st = self._classes.setdefault(cls, _ClassState())
            st.queue.append(spec)
        self._submit_event.set()
        return True

    # ================================================== GC callbacks

    def _free_owned_object(self, oid: ObjectID):
        if self._shutdown:
            return  # late GC-grace timer; stores/conns are torn down
        self._contained.pop(oid, None)
        with self._sub_lock:
            self._lineage.pop(oid, None)
        entry = self.memory_store.peek(oid)
        # any-node shm residency: freeing promptly lets that arena
        # reclaim; peeking the in-process entry is far cheaper than
        # probing the shm index on every small free
        shm_resident = bool(entry is not None and entry.in_plasma)
        # Large local copies are reclaimed NOW rather than when the head
        # gets around to processing our OBJECT_FREE: under a large-put
        # flood the head lags, bytes_in_use rides the spill threshold,
        # and the head then spills objects that are already free —
        # measured collapsing put bandwidth by an order of magnitude.
        # Size-gated: the native delete costs a ~0.2 ms locked call on
        # the deployment kernel, which for small objects (negligible
        # arena pressure) is pure overhead on the free path. Idempotent
        # with the head's directory-driven delete; a copy pinned by an
        # in-flight transfer just fails the delete and falls back there.
        local_delete = (shm_resident and entry.node_idx == self.node_idx
                        and entry.plasma_size >= (1 << 20))
        self.memory_store.evict(oid)
        if oid in self._pinned:
            self._pinned.discard(oid)
            try:
                self.store.release(oid)
            except Exception:
                pass
        if local_delete:
            try:
                self.store.delete(oid)
            except Exception:
                pass
        # Small (inline / memory-store) objects: buffer the head
        # notification — at high call rates one OBJECT_FREE frame per
        # freed return-ref doubles the driver->head message count
        # (measured in the n_n actor microbench), and for these the
        # message is pure GC accounting. Shm-resident objects flush
        # IMMEDIATELY: delaying their free keeps arena bytes_in_use high
        # and trips the head's spill threshold (measured 4x put-bandwidth
        # collapse with a 0.2 s delay).
        with self._free_lock:
            self._free_buf.append(oid.binary())
            flush = shm_resident or len(self._free_buf) >= 64
        if flush:
            self._flush_frees()

    def _flush_frees(self):
        with self._free_lock:
            batch, self._free_buf = self._free_buf, []
        if not batch:
            return
        try:
            self.head.send(P.OBJECT_FREE, batch)
        except P.ConnectionLost:
            pass

    def _release_borrow(self, oid: ObjectID, owner: str):
        self._known_owners.pop(oid, None)
        self.memory_store.evict(oid)
        if oid in self._pinned:
            self._pinned.discard(oid)
            try:
                self.store.release(oid)
            except Exception:
                pass
        try:
            self.head.send(P.BORROW_REMOVE, oid.binary(), owner,
                           self.worker_id)
        except P.ConnectionLost:
            pass

    def notify_deserialized_ref(self, ref: ObjectRef):
        if ref.owner and ref.owner != self.worker_id:
            self._known_owners[ref.id] = ref.owner
            try:
                self.head.send(P.BORROW_ADD, ref.id.binary(), ref.owner,
                               self.worker_id)
            except P.ConnectionLost:
                pass

    # ================================================== task submission

    def submit_task(self, fn, args, kwargs, *, num_returns=1, resources=None,
                    strategy=None, max_retries=None, retry_exceptions=False,
                    name="", runtime_env=None,
                    prefetch_args=True) -> List[ObjectRef]:
        cfg = get_config()
        fn_id = self.fn_manager.export(fn)
        task_id = TaskID.for_normal_task(self.job_id)
        spec = TaskSpec(
            task_id=task_id, job_id=self.job_id, task_type=TaskType.NORMAL,
            name=name or getattr(fn, "__name__", "task"),
            function_id=fn_id,
            num_returns=num_returns,
            resources=resources if resources is not None else {"CPU": 1},
            strategy=strategy or SchedulingStrategy(),
            max_retries=(cfg.task_max_retries_default
                         if max_retries is None else max_retries),
            retry_exceptions=retry_exceptions,
            owner=self.worker_id,
            runtime_env=runtime_env or self.job_runtime_env,
            prefetch_args=prefetch_args,
            trace_ctx=task_events.submit_trace_ctx(),
        )
        arg_ids, holder = self._encode_args(spec, args, kwargs)
        self.events.record(task_id.hex(), spec.name, task_events.SUBMITTED,
                           trace_id=spec.trace_ctx[0],
                           parent_span_id=spec.trace_ctx[1])
        return self._enqueue_spec(spec, arg_ids, holder)

    def _encode_args(self, spec: TaskSpec, args, kwargs):
        encoded = []
        arg_ids: List[ObjectID] = []
        holder: list = []
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, ObjectRef):
                self._promote_if_needed(a)
                encoded.append((ARG_REF, a.id.binary(), a.owner or
                               self.worker_id))
                arg_ids.append(a.id)
                holder.append(a)
                self.ref_counter.add_task_arg(a.id)
            else:
                sv = serialize(a)
                for r in sv.contained_refs:
                    self._promote_if_needed(r)
                    arg_ids.append(r.id)
                    holder.append(r)
                    self.ref_counter.add_task_arg(r.id)
                encoded.append((ARG_VALUE,
                                [bytes(f) if isinstance(f, memoryview)
                                 else f for f in sv.frames]))
        spec.args = encoded
        spec.kwarg_names = list(kwargs.keys())
        return arg_ids, holder

    def _promote_if_needed(self, ref: ObjectRef):
        """Ensure a ref being lent out is materialized in the shm store so
        borrowers can fetch it (reference: inline-object promotion)."""
        e = self.memory_store.peek(ref.id)
        if e is None or e.in_plasma or e.is_error:
            return
        if (ref.owner or self.worker_id) != self.worker_id:
            return
        sv = serialize(e.value)
        try:
            self.store.put_serialized(ref.id, sv.frames)
        except Exception:
            return
        self.head.send(P.OBJECT_SEALED, ref.id.binary(), self.node_idx,
                       sv.total_bytes, self.worker_id,
                       self.current_job_id.hex())
        e.in_plasma = True
        e.node_idx = self.node_idx
        e.plasma_size = sv.total_bytes
        # remember the id so dispatch-time prefetch hints can tag it:
        # pulls of inline-promoted tiny values are not the speculation
        # the head's waste-ratio accounting should judge (r16)
        with self._hint_lock:
            ip = self._inline_promoted
            ip[ref.id.binary()] = None
            while len(ip) > 4096:
                ip.popitem(last=False)

    def _enqueue_spec(self, spec: TaskSpec, arg_ids, holder) -> List[ObjectRef]:
        refs = [ObjectRef(oid, self.worker_id, _register=False)
                for oid in spec.return_ids()]
        for r in refs:
            self.ref_counter.add_owned(r.id)
            self.ref_counter.add_local_ref(r)
            r._registered = True
        inflight = _InflightTask(spec, arg_ids, spec.max_retries, holder)
        cls = spec.scheduling_class()
        wake = True
        with self._sub_lock:
            self._inflight[spec.task_id] = inflight
            for oid in spec.return_ids():
                self._return_to_task[oid] = spec.task_id
            if not holder:
                # No arg refs → nothing to resolve: queue directly under
                # the same lock acquisition (the high-rate submission path).
                st = self._classes.setdefault(cls, _ClassState())
                # wake the submitter only when the queue was idle: with
                # work already queued the drain loop re-checks the queue
                # under this same lock before sleeping, so it cannot
                # miss this append — and an Event.set() per submit was
                # a measurable lock ping-pong at flood rates
                wake = not st.queue
                st.queue.append(spec)
        if not holder:
            self.events.record(spec.task_id.hex(), spec.name,
                               task_events.PENDING_NODE_ASSIGNMENT)
            if wake:
                self._submit_event.set()
            return refs
        self.events.record(spec.task_id.hex(), spec.name,
                           task_events.PENDING_ARGS_AVAIL)
        self._resolve_then(spec, holder,
                           lambda: self._enqueue_ready(spec, cls))
        return refs

    def _enqueue_ready(self, spec: TaskSpec, cls):
        with self._sub_lock:
            st = self._classes.setdefault(cls, _ClassState())
            st.queue.append(spec)
        self.events.record(spec.task_id.hex(), spec.name,
                           task_events.PENDING_NODE_ASSIGNMENT)
        self._submit_event.set()

    def _resolve_then(self, spec: TaskSpec, holder, on_ready, on_error=None):
        """Submitter-side dependency resolution (the reference's
        LocalDependencyResolver, core_worker/transport/dependency_resolver.h):
        hold the task until every *owned* arg object is ready, propagate an
        upstream error straight to this task's returns, and promote
        inline-only values into the shm store so the executing worker can
        fetch them by location. Borrowed refs resolve via the owner's
        promotion at lend time + head locate."""
        owned: Dict[ObjectID, ObjectRef] = {}
        for ref in holder:
            if (ref.owner or self.worker_id) == self.worker_id:
                owned.setdefault(ref.id, ref)

        def finalize():
            err = None
            for oid, ref in owned.items():
                e = self.memory_store.peek(oid)
                if e is None:
                    continue
                if e.is_error:
                    err = e.value
                    break
                if not e.in_plasma:
                    self._promote_if_needed(ref)
            if err is not None:
                if on_error is not None:
                    on_error(err)
                else:
                    self._complete_task_error(spec, err)
                    self._submit_event.set()
            else:
                on_ready()

        pending = [oid for oid in owned
                   if not self.memory_store.contains(oid)]
        if not pending:
            finalize()
            return
        state = {"n": len(pending)}
        lock = threading.Lock()

        def cb():
            with lock:
                state["n"] -= 1
                done = state["n"] == 0
            if done:
                finalize()

        for oid in pending:
            self.memory_store.add_ready_callback(oid, cb)

    def _submitter_loop(self):
        while not self._shutdown:
            self._submit_event.wait(0.2)
            self._submit_event.clear()
            self._purge_handoff_pins()
            try:
                with self._sub_lock:
                    classes = list(self._classes.items())
                for cls, st in classes:
                    self._drain_class(cls, st)
                self._flush_prefetch_hints()
                self._reap_idle_leases()
                self._flush_frees()
            except Exception:
                traceback.print_exc()

    def _drain_class(self, cls, st: _ClassState):
        """Dispatch queued tasks of one scheduling class.

        Policy (replaces the reference's lease-per-task + spillback cycle,
        direct_task_transport.h:177): aim for one leased worker per queued
        task up to ``max_workers_per_node`` — the head queues ungrantable
        lease requests and `_request_lease` hands back grants that arrive
        after the queue empties. Dispatch fills workers least-loaded-first
        up to an even share ``T`` of the outstanding work, batching each
        worker's refill into ONE framed message (one pickle, one syscall),
        and leaves the remainder queued for leases still in flight — so a
        burst of a few long tasks spreads across workers while a flood of
        tiny tasks still pipelines ``max_tasks_in_flight_per_worker`` deep.
        """
        cfg = get_config()
        cap = cfg.max_tasks_in_flight_per_worker
        to_release: List[_LeasedWorker] = []
        while True:
            with self._sub_lock:
                if not st.queue:
                    break
                total_inflight = sum(len(w.inflight) for w in st.workers)
                demand = len(st.queue) + total_inflight
                wanted = min(
                    min(demand, cfg.max_workers_per_node)
                    - len(st.workers) - st.pending_leases,
                    cfg.max_pending_lease_requests_per_class
                    - st.pending_leases)
                if wanted > 0:
                    st.lease_req_ts = time.monotonic()
                for _ in range(max(0, wanted)):
                    st.pending_leases += 1
                    threading.Thread(
                        target=self._request_lease, args=(cls, st),
                        daemon=True).start()
                if wanted > 0 and not st.workers:
                    # Starved class: give back idle leases held by OTHER
                    # classes now, not after the 2s idle reap — their held
                    # resources are exactly what blocks our lease grants.
                    for ocls, ost in self._classes.items():
                        if ocls == cls or ost.queue:
                            continue
                        keep = []
                        for w in ost.workers:
                            (to_release if not w.inflight
                             else keep).append(w)
                        ost.workers = keep
                worker = None
                n_free = 0
                for w in st.workers:
                    if len(w.inflight) < cap:
                        n_free += 1
                        if worker is None or \
                                len(w.inflight) < len(worker.inflight):
                            worker = w
                if worker is None:
                    break
                # Even share across free workers plus leases that are
                # FRESH (requested < 1s ago): hold work back for workers
                # about to arrive, but a pending lease can be ungrantable
                # forever on a saturated node — once stale, stop counting
                # it, or the share shrinks to ~1 and a small burst
                # serializes into one round-trip per task.
                fresh = (st.pending_leases
                         if time.monotonic() - st.lease_req_ts < 1.0 else 0)
                targets = n_free + fresh
                share = max(1, (demand + targets - 1) // targets)
                slots = min(cap, share) - len(worker.inflight)
                if slots <= 0:
                    break  # all workers at their share; wait for leases
                batch = []
                while st.queue and len(batch) < slots:
                    spec = st.queue.popleft()
                    if spec.task_id in self._cancelled:
                        self._finish_cancelled(spec)
                        continue
                    spec.tpu_ids = worker.tpu_ids
                    worker.inflight[spec.task_id] = spec
                    inf = self._inflight.get(spec.task_id)
                    if inf is not None:
                        inf.worker = worker
                    batch.append(spec)
                worker.idle_since = time.monotonic()
            if not batch:
                continue
            self._send_prefetch_hint(worker, batch, worker.lease_id)
            try:
                if len(batch) == 1:
                    worker.conn.send(P.PUSH_TASK, batch[0], 0)
                else:
                    worker.conn.send(P.PUSH_TASK_BATCH, batch)
                for spec in batch:
                    self.events.record(spec.task_id.hex(), spec.name,
                                       task_events.SUBMITTED_TO_WORKER)
            except P.ConnectionLost:
                self._on_lease_worker_lost(cls, st, worker)
        for w in to_release:
            try:
                self.head.send(P.RETURN_WORKER, w.lease_id, w.worker_id)
            except P.ConnectionLost:
                pass
            w.conn.on_close = None
            w.conn.close()

    def _send_prefetch_hint(self, holder, batch, lease_key: str) -> None:
        """Dispatch-time speculative prefetch (r13): name the pushed
        batch's by-ref args for the executing node so the head can
        start any missing pulls while the batch is still in flight to
        the worker — leases are long-lived, so the grant-time hint
        covers only the first task. One one-way frame per
        batch-with-refs (coalesced by the wire layer); tasks without
        by-ref args (the common case at high rates) pay nothing.

        r14: ``holder`` is whichever object pins the destination — a
        ``_LeasedWorker`` (``lease_key`` = its lease id) or an
        ``_ActorState`` (``lease_key`` = ``actor:<hex>``, resolved to
        the actor's node head-side) — so actor-task hot loops (the
        serve-handle pattern) get dispatch-time prefetch too. Hints
        are DEDUPED per holder across consecutive batches: re-passing
        the same refs on every call (handle payload/weights args)
        would otherwise re-name the same ids to the head once per
        pushed batch, and the head's own dedupe only saves the pull,
        not the frame or the IO-loop wakeup. Each holder remembers the
        arg ids it hinted within ``prefetch_hint_dedupe_ttl_s``; only
        novel (or expired) ids ship. Suppressions are counted in
        ``self.prefetch_hints_suppressed``."""
        cfg = get_config()
        if not cfg.arg_prefetch_enabled:
            return
        # NEVER block dispatch on the head channel: during a head
        # outage a ReconnectingConnection PARKS writes for the whole
        # reconnect window, and this send runs on the submitter thread
        # right before pushing tasks to healthy leased workers — a
        # parked hint would stall all dispatch for the outage, undoing
        # the r12 availability. Speculation just skips the window.
        if not self.head.is_attached():
            return
        ids = list(dict.fromkeys(
            enc[1] for spec in batch
            if getattr(spec, "prefetch_args", True)
            for enc in spec.args if enc[0] == ARG_REF))[:64]
        if not ids:
            return
        if cfg.prefetch_hint_dedupe_ttl_s > 0:
            # _hint_lock: concurrent drains of the same holder (proxy
            # thread pool + resolver ready-callbacks) would otherwise
            # race the dict eviction in _filter_hint_ids.
            with self._hint_lock:
                hinted = holder.hinted
                if hinted is None:
                    hinted = holder.hinted = {}
                ids, n_in = _filter_hint_ids(
                    hinted, ids, time.monotonic(),
                    cfg.prefetch_hint_dedupe_ttl_s), len(ids)
                self.prefetch_hints_suppressed += n_in - len(ids)
            if not ids:
                return
        if cfg.prefetch_hint_coalesce:
            # r15: buffer per destination; the submitter loop's next
            # wakeup flushes EVERYTHING pending as one frame
            # (_flush_prefetch_hints). A batch landing on a key that
            # already has a pending flush merges into it — that is one
            # whole frame saved, counted in prefetch_hints_coalesced.
            with self._hint_lock:
                inline = [ab for ab in ids
                          if ab in self._inline_promoted]
                buf = self._hint_buf.get(lease_key)
                if buf is None:
                    self._hint_buf[lease_key] = [list(ids), inline]
                else:
                    self.prefetch_hints_coalesced += 1
                    seen = set(buf[0])
                    buf[0].extend(ab for ab in ids if ab not in seen)
                    seen = set(buf[1])
                    buf[1].extend(ab for ab in inline
                                  if ab not in seen)
            self._submit_event.set()
            return
        with self._hint_lock:
            self.prefetch_hints_sent += 1
            inline = [ab for ab in ids if ab in self._inline_promoted]
        try:
            # the inline-tag field ships only when non-empty: the
            # common no-inline frame stays byte-identical to r15's
            if inline:
                self.head.send(P.PREFETCH_HINT, lease_key, ids, inline)
            else:
                self.head.send(P.PREFETCH_HINT, lease_key, ids)
        except P.ConnectionLost:
            pass  # speculation only: the demand path still works

    def _flush_prefetch_hints(self):
        """Ship every buffered prefetch hint in ONE frame (r15 hint
        coalescing). Driven by the submitter loop — each submit wakes
        it, so the added latency is one thread wakeup, paid only by
        speculation whose whole point is overlapping multi-ms
        transfers. Single-destination flushes reuse the plain
        PREFETCH_HINT frame so an r14 head decodes them unchanged."""
        with self._hint_lock:
            if not self._hint_buf:
                return
            # entries keep the 2-tuple shape unless a destination has
            # inline-tagged ids (r16) — no-inline frames stay
            # byte-identical to r15's, and r15 heads decode 2-tuples
            entries = [(k, v[0], v[1]) if v[1] else (k, v[0])
                       for k, v in self._hint_buf.items()]
            self._hint_buf.clear()
        if not self.head.is_attached():
            return  # head outage: drop — demand path still works
        try:
            if len(entries) == 1:
                self.head.send(P.PREFETCH_HINT, *entries[0])
            else:
                self.head.send(P.PREFETCH_HINT_BATCH, entries)
        except P.ConnectionLost:
            return  # dropped, not sent
        with self._hint_lock:
            self.prefetch_hints_sent += 1

    def _request_lease(self, cls, st: _ClassState):
        from .serialization import dumps

        sample: Optional[TaskSpec] = None
        with self._sub_lock:
            if st.queue:
                sample = st.queue[0]
        if sample is None:
            with self._sub_lock:
                st.pending_leases -= 1
            return
        # Arg-locality hint: binary ids of the sample task's by-reference
        # args. The head scores feasible nodes by how many of those bytes
        # they already hold (its object directory knows sizes + holder
        # sets) and prefers the best one — the reference ships the same
        # hint via LocalityAwareLeasePolicy on lease requests.
        # deduped: f.remote(x, x) must not double-count x's bytes toward
        # the locality threshold
        arg_ids = list(dict.fromkeys(
            enc[1] for enc in sample.args if enc[0] == ARG_REF))[:32]
        try:
            reply = self.head.call(
                P.LEASE_REQUEST, cls, sample.resources, self.job_id.hex(),
                dumps(sample.strategy), arg_ids, timeout=None)
            ok, worker_id, addr, lease_id, err = reply[:5]
            tpu_ids = reply[5] if len(reply) > 5 else None
        except Exception as e:  # noqa: BLE001
            with self._sub_lock:
                st.pending_leases -= 1
            self._fail_queued(st, e)
            return
        with self._sub_lock:
            still_needed = bool(st.queue)
        if not still_needed:
            # The queue drained while this lease request was in flight at
            # the head (it queues ungrantable requests indefinitely) — hand
            # the worker straight back instead of holding an idle lease.
            with self._sub_lock:
                st.pending_leases -= 1
            try:
                self.head.send(P.RETURN_WORKER, lease_id, worker_id)
            except P.ConnectionLost:
                pass  # shutting down
            return
        try:
            sock = P.connect_addr(addr)
        except OSError as e:
            with self._sub_lock:
                st.pending_leases -= 1
            self.head.send(P.RETURN_WORKER, lease_id, worker_id, True)
            self._submit_event.set()
            return
        conn = P.Connection(sock, peer=f"lease:{worker_id[:8]}")
        lw = _LeasedWorker(worker_id, addr, lease_id, conn, tpu_ids)
        conn.on_close = lambda c, cls=cls, st=st, lw=lw: \
            self._on_lease_worker_lost(cls, st, lw)
        self.io.add_connection(conn, self._on_peer_message)
        with self._sub_lock:
            st.pending_leases -= 1
            st.workers.append(lw)
        self._submit_event.set()

    def _fail_queued(self, st: _ClassState, err: Exception):
        with self._sub_lock:
            specs = list(st.queue)
            st.queue.clear()
        for spec in specs:
            self._complete_task_error(spec, WorkerCrashedError(str(err)))

    def _reap_idle_leases(self):
        now = time.monotonic()
        with self._sub_lock:
            for cls, st in self._classes.items():
                keep = []
                for w in st.workers:
                    if not w.inflight and not st.queue and \
                            now - w.idle_since > 2.0:
                        try:
                            self.head.send(P.RETURN_WORKER, w.lease_id,
                                           w.worker_id)
                        except P.ConnectionLost:
                            pass
                        w.conn.on_close = None
                        w.conn.close()
                    else:
                        keep.append(w)
                st.workers = keep

    def _on_lease_worker_lost(self, cls, st: _ClassState, lw: _LeasedWorker):
        with self._sub_lock:
            if lw in st.workers:
                st.workers.remove(lw)
            lost = list(lw.inflight.values())
            lw.inflight.clear()
        for spec in lost:
            self._maybe_retry(spec, WorkerCrashedError(
                f"worker {lw.worker_id[:8]} died"), count_retry=True)
        self._submit_event.set()

    def _maybe_retry(self, spec: TaskSpec, err: Exception, count_retry: bool):
        with self._sub_lock:
            inf = self._inflight.get(spec.task_id)
            if inf is None:
                return
            # negative retries_left means infinite retries (reference
            # semantics for max_retries=-1, python/ray/remote_function.py)
            if count_retry and inf.retries_left != 0:
                if inf.retries_left > 0:
                    inf.retries_left -= 1
                st = self._classes.setdefault(spec.scheduling_class(),
                                              _ClassState())
                st.queue.append(spec)
                retry = True
            else:
                retry = False
        if retry:
            self._submit_event.set()
        else:
            self._complete_task_error(spec, err)

    def _complete_task_error(self, spec: TaskSpec, err: Exception,
                             state: str = task_events.FAILED):
        # Owner-side terminal stamp: a task can die WITHOUT a worker
        # ever recording FAILED (worker crash with retries exhausted,
        # dep-resolution failure, actor death) — without this the folded
        # timeline wedges at RUNNING and the straggler detector flags a
        # task the caller already received an error for.
        self.events.record(spec.task_id.hex(),
                           spec.name or spec.method_name, state,
                           error=repr(err))
        aborted = []
        for oid in spec.return_ids():
            # don't clobber results that already arrived (e.g. an actor
            # killed right after its last reply was stored)
            if not self.memory_store.contains(oid):
                self.memory_store.put_value(oid, err, is_error=True)
                aborted.append(oid.binary())
        if aborted and spec.task_type == TaskType.NORMAL:
            # borrowers may be blocked in a head-side locate for these
            # returns (esp. after a failed lineage re-execution) — tell
            # the head they will never seal
            try:
                self.head.send(P.SEAL_ABORTED, aborted)
            except P.ConnectionLost:
                pass
        self._finalize_task(spec)

    def _finalize_task(self, spec: TaskSpec):
        with self._sub_lock:
            inf = self._inflight.pop(spec.task_id, None)
            self._recovering.discard(spec.task_id)
            for oid in spec.return_ids():
                self._return_to_task.pop(oid, None)
        if inf is not None:
            for oid in inf.arg_ids:
                self.ref_counter.remove_task_arg(oid)

    def _finish_cancelled(self, spec: TaskSpec):
        self._complete_task_error(spec,
                                  TaskCancelledError(spec.task_id.hex()),
                                  state=task_events.CANCELLED)

    def cancel(self, ref: ObjectRef, force: bool = False):
        with self._sub_lock:
            task_id = self._return_to_task.get(ref.id)
            if task_id is None:
                return
            self._cancelled.add(task_id)
            inf = self._inflight.get(task_id)
            spec = inf.spec if inf else None
            target = None
            if spec is not None:
                st = self._classes.get(spec.scheduling_class())
                if st:
                    if spec in st.queue:
                        st.queue.remove(spec)
                        self._finish_cancelled(spec)
                        return
                    for w in st.workers:
                        if task_id in w.inflight:
                            target = w
                            break
        if target is not None:
            try:
                target.conn.send(P.PUSH_CANCEL, task_id.binary(), force)
            except P.ConnectionLost:
                pass

    # -------------------------------------------------- task replies

    def _handle_task_reply_batch(self, conn, replies):
        """Batched completion handling: the per-reply path cost ~5 lock
        round-trips per task (inflight clear, RETURNED record, result
        store, finalize, submitter wakeup) while the submitting thread
        fought for the same locks — at high completion rates the lock
        convoy between this IO thread and the submit path was a
        measured slice of the e2e task budget. One _sub_lock hold
        clears every reply's dispatch bookkeeping; one _submit_event
        wakeup covers the whole frame."""
        now = time.monotonic()
        normal = []
        other = []
        with self._sub_lock:
            for reply in replies:
                task_id = TaskID(reply[0])
                inf = self._inflight.get(task_id)
                spec = inf.spec if inf else None
                w = inf.worker if inf is not None else None
                if w is not None:
                    w.inflight.pop(task_id, None)
                    w.idle_since = now
                    inf.worker = None
                if spec is None or spec.task_type == TaskType.ACTOR_TASK:
                    other.append((task_id, reply))
                else:
                    normal.append((task_id, spec, reply))
        for task_id, reply in other:
            self._handle_actor_reply(task_id, *reply[1:])
        for task_id, spec, (tb, status, result_meta, err) in normal:
            self.events.record(task_id.hex(), spec.name,
                               task_events.RETURNED)
            if status == "ok":
                self._store_results(spec, result_meta)
                self._finalize_task(spec)
            elif status == "cancelled":
                self._finish_cancelled(spec)
            elif spec.retry_exceptions:
                self._maybe_retry(spec, err, count_retry=True)
            else:
                self._complete_task_error(spec, err)
        self._submit_event.set()

    def _handle_task_reply(self, conn, task_id_bin, status, result_meta, err):
        task_id = TaskID(task_id_bin)
        with self._sub_lock:
            inf = self._inflight.get(task_id)
            spec = inf.spec if inf else None
            # clear from the lease worker that carried it (direct backref —
            # scanning every worker of every class is O(workers) per reply)
            w = inf.worker if inf is not None else None
            if w is not None:
                w.inflight.pop(task_id, None)
                w.idle_since = time.monotonic()
                inf.worker = None
        if spec is None or spec.task_type == TaskType.ACTOR_TASK:
            # Actor replies must ALSO clear the actor state's inflight map,
            # or a completed call lingers there and is replayed (or failed)
            # when the actor restarts.
            self._handle_actor_reply(task_id, status, result_meta, err)
            return
        # result_return / e2e phase endpoint: the reply landed back at
        # the owner (recorded whatever the status — an error "returns"
        # too; retries re-open the timeline from their own dispatch)
        self.events.record(task_id.hex(), spec.name, task_events.RETURNED)
        if status == "ok":
            self._store_results(spec, result_meta)
            self._finalize_task(spec)
        elif status == "cancelled":
            self._finish_cancelled(spec)
        else:
            if spec.retry_exceptions:
                self._maybe_retry(spec, err, count_retry=True)
            else:
                self._complete_task_error(spec, err)
        self._submit_event.set()

    def _store_results(self, spec: TaskSpec, result_meta):
        any_plasma = False
        for oid, entry in zip(spec.return_ids(), result_meta):
            kind = entry[0]
            if kind == "v":
                self.memory_store.put_value(oid, deserialize(entry[1]))
            else:
                self.memory_store.put_plasma_location(oid, entry[1])
                any_plasma = True
        if any_plasma and spec.task_type == TaskType.NORMAL:
            self._record_lineage(spec)

    def _record_lineage(self, spec: TaskSpec):
        cap = get_config().lineage_cache_max_entries
        with self._sub_lock:
            self._recovering.discard(spec.task_id)
            for oid in spec.return_ids():
                self._lineage[oid] = spec
                self._lineage.move_to_end(oid)
            while len(self._lineage) > cap:
                self._lineage.popitem(last=False)

    # ================================================== actor submission

    def create_actor(self, cls, args, kwargs, *, num_cpus=0, resources=None,
                     max_restarts=0, max_concurrency=1, name="",
                     strategy=None, max_task_retries=0,
                     runtime_env=None) -> "ActorID":
        from .serialization import dumps

        fn_id = self.fn_manager.export(cls)
        actor_id = ActorID.of(self.job_id)
        task_id = TaskID.for_actor_task(actor_id)
        res = dict(resources or {})
        if num_cpus:
            res["CPU"] = num_cpus
        spec = TaskSpec(
            task_id=task_id, job_id=self.job_id,
            task_type=TaskType.ACTOR_CREATION,
            name=name, function_id=fn_id,
            class_name=getattr(cls, "__name__", ""),
            resources=res,
            strategy=strategy or SchedulingStrategy(),
            owner=self.worker_id, actor_id=actor_id,
            max_restarts=max_restarts, max_concurrency=max_concurrency,
            max_retries=max_task_retries,
            runtime_env=runtime_env or self.job_runtime_env,
            trace_ctx=task_events.submit_trace_ctx(),
        )
        self._encode_args(spec, args, kwargs)
        self.head.call(P.CREATE_ACTOR, dumps(spec), timeout=60)
        st = _ActorState(actor_id)
        with self._sub_lock:
            self._actors[actor_id] = st
        self._watch_actor(actor_id)
        return actor_id

    def _watch_actor(self, actor_id: ActorID):
        def on_state(data):
            state, addr = data
            self._on_actor_state_change(actor_id, state, addr)

        self.subscribe(f"actor:{actor_id.hex()}", on_state, ack=False)

    def _actor_state(self, actor_id: ActorID) -> _ActorState:
        with self._sub_lock:
            st = self._actors.get(actor_id)
            if st is None:
                st = _ActorState(actor_id)
                self._actors[actor_id] = st
                self._watch_actor(actor_id)
            return st

    def submit_actor_task(self, actor_id: ActorID, method_name: str, args,
                          kwargs, *, num_returns=1, max_retries=0,
                          name: str = "") -> List[ObjectRef]:
        """``name`` overrides the task's observability label (defaults
        to the method name): the func key under which the r10 phase
        histograms, straggler detector and `summary tasks` aggregate
        this call. Pipeline stage actors use it (``stage{k}.fwd``) so
        per-stage bubble/transfer time is separable with no new
        plumbing."""
        st = self._actor_state(actor_id)
        task_id = TaskID.for_actor_task(actor_id)
        spec = TaskSpec(
            task_id=task_id, job_id=self.job_id, task_type=TaskType.ACTOR_TASK,
            name=name or method_name, function_id="",
            method_name=method_name,
            num_returns=num_returns, owner=self.worker_id,
            actor_id=actor_id, max_retries=max_retries,
            trace_ctx=task_events.submit_trace_ctx(),
        )
        arg_ids, holder = self._encode_args(spec, args, kwargs)
        self.events.record(task_id.hex(), spec.name, task_events.SUBMITTED,
                           trace_id=spec.trace_ctx[0],
                           parent_span_id=spec.trace_ctx[1])
        if holder:
            self.events.record(task_id.hex(), spec.name,
                               task_events.PENDING_ARGS_AVAIL)
        refs = [ObjectRef(oid, self.worker_id, _register=False)
                for oid in spec.return_ids()]
        for r in refs:
            self.ref_counter.add_owned(r.id)
            self.ref_counter.add_local_ref(r)
            r._registered = True
        inflight = _InflightTask(spec, arg_ids, max_retries, holder)
        with self._sub_lock:
            self._inflight[spec.task_id] = inflight
            for oid in spec.return_ids():
                self._return_to_task[oid] = spec.task_id
        with st.lock:
            spec.seqno = next(st.seqno)
            st.queue.append(spec)
            self._dep_unready.add(spec.task_id)

        def ready():
            self._dep_unready.discard(spec.task_id)
            # args resolved: the task now waits only for the actor's
            # connection + head-of-line order (its "node assignment")
            self.events.record(task_id.hex(), spec.name,
                               task_events.PENDING_NODE_ASSIGNMENT)
            self._drain_actor(st)

        def failed(err):
            self._dep_unready.discard(spec.task_id)
            with st.lock:
                try:
                    st.queue.remove(spec)
                except ValueError:
                    pass
            self._complete_task_error(spec, err)
            self._drain_actor(st)

        self._resolve_then(spec, holder, ready, failed)
        return refs

    def _drain_actor(self, st: _ActorState):
        with st.lock:
            if st.state == "DEAD":
                dead = list(st.queue)
                st.queue.clear()
            else:
                dead = []
        for spec in dead:
            self._complete_task_error(
                spec, ActorDiedError(st.death_cause or "actor died"))
        if dead:
            return
        with st.lock:
            if st.conn is None:
                if not st.resolving and st.state != "DEAD":
                    st.resolving = True
                    threading.Thread(target=self._resolve_actor, args=(st,),
                                     daemon=True).start()
                return
            to_send = []
            while st.queue:
                # head-of-line gate: actor-task order is by seqno, so a task
                # whose deps are still resolving blocks those behind it
                if st.queue[0].task_id in self._dep_unready:
                    break
                spec = st.queue.popleft()
                st.inflight[spec.task_id] = spec
                to_send.append(spec)
            conn = st.conn
            # one frame, one pickle, one syscall for the whole drain —
            # specs carry their seqno (the r3 PUSH_TASK_BATCH
            # optimization, now on the actor path too). The send happens
            # UNDER st.lock: two concurrent drains pop in order but would
            # otherwise race to the socket, delivering actor tasks out of
            # seqno order (the receiver executes in arrival order).
            try:
                if len(to_send) == 1:
                    conn.send(P.PUSH_TASK, to_send[0], to_send[0].seqno)
                elif to_send:
                    conn.send(P.PUSH_TASK_BATCH, to_send)
                for spec in to_send:
                    self.events.record(spec.task_id.hex(), spec.name,
                                       task_events.SUBMITTED_TO_WORKER)
            except P.ConnectionLost:
                pass  # conn.on_close handles re-resolution
        if to_send:
            # dispatch-time prefetch for ACTOR tasks (r14): the head
            # resolves the actor key to its worker's node. Outside
            # st.lock — speculation must not extend the dispatch
            # critical section, and ordering is irrelevant to it.
            self._send_prefetch_hint(
                st, to_send, "actor:" + st.actor_id.hex())

    def _resolve_actor(self, st: _ActorState):
        try:
            state, addr = self.head.call(P.GET_ACTOR, st.actor_id.binary(),
                                         timeout=None)
        except Exception as e:  # noqa: BLE001
            state, addr = "DEAD", str(e)
        self._on_actor_state_change(st.actor_id, state, addr, resolved=True)

    def _on_actor_state_change(self, actor_id: ActorID, state: str, addr: str,
                               resolved: bool = False):
        st = self._actor_state(actor_id)
        with st.lock:
            st.resolving = False
            if (state == "ALIVE" and st.state == "ALIVE"
                    and st.conn is not None and st.addr == addr):
                return  # duplicate notification (pubsub + resolution race)
            prev_conn = st.conn
            st.conn = None
            # In-flight calls are lost only when we had a live connection
            # that is now invalid, or the actor is gone.
            if prev_conn is not None or state in ("DEAD", "NOT_FOUND",
                                                  "RESTARTING"):
                lost = list(st.inflight.values())
                st.inflight.clear()
            else:
                lost = []
            if state == "ALIVE":
                st.state = "ALIVE"
                st.addr = addr
            elif state in ("DEAD", "NOT_FOUND"):
                st.state = "DEAD"
                st.death_cause = addr
            else:  # RESTARTING
                st.state = "RESTARTING"
        if prev_conn is not None:
            prev_conn.on_close = None
            prev_conn.close()
        # in-flight tasks: retry if allowed, else fail
        for spec in lost:
            if st.state in ("ALIVE", "RESTARTING") and spec.max_retries != 0:
                with st.lock:
                    st.queue.appendleft(spec)
            elif st.state == "DEAD":
                self._complete_task_error(
                    spec, ActorDiedError(st.death_cause or "actor died"))
            else:
                self._complete_task_error(spec, ActorUnavailableError(
                    f"actor {actor_id.hex()} restarting; in-flight call lost"))
        if st.state == "ALIVE":
            with st.lock:
                if st.conn is not None or st.connecting:
                    return
                st.connecting = True
            try:
                sock = P.connect_addr(addr)
            except OSError:
                with st.lock:
                    st.connecting = False
                return
            conn = P.Connection(sock, peer=f"actor:{actor_id.hex()[:8]}")
            conn.on_close = lambda c: self._on_actor_conn_close(st)
            self.io.add_connection(conn, self._on_peer_message)
            with st.lock:
                st.conn = conn
                st.connecting = False
            self._drain_actor(st)
        elif st.state == "DEAD":
            self._drain_actor(st)

    def _on_actor_conn_close(self, st: _ActorState):
        with st.lock:
            st.conn = None
            if st.state != "DEAD" and not st.resolving:
                st.resolving = True
                threading.Thread(target=self._resolve_actor, args=(st,),
                                 daemon=True).start()

    def _handle_actor_reply(self, task_id, status, result_meta, err):
        spec = None
        with self._sub_lock:
            inf = self._inflight.get(task_id)
            if inf is not None:
                spec = inf.spec
        if spec is None:
            return
        st = self._actor_state(spec.actor_id)
        with st.lock:
            st.inflight.pop(task_id, None)
        if spec.task_type == TaskType.ACTOR_TASK:
            self.events.record(task_id.hex(),
                               spec.name or spec.method_name,
                               task_events.RETURNED)
        if status == "ok":
            self._store_results(spec, result_meta)
            self._finalize_task(spec)
        elif status == "cancelled":
            self._finish_cancelled(spec)
        else:
            self._complete_task_error(spec, err)

    def actor_state(self, actor_id: ActorID) -> str:
        """This process's current view of an actor's lifecycle state:
        ``"ALIVE" | "RESTARTING" | "DEAD" | "UNKNOWN"`` (UNKNOWN =
        never watched, or no notification yet). Driven by the head's
        ``actor:<id>`` pubsub — DEAD lands the moment the head marks
        the death, i.e. the same signal that fails pending calls with
        ``ActorDiedError``. The supported death-detection query for
        callers like the pipeline repair planner (do not reach into
        ``_actors`` directly)."""
        with self._sub_lock:
            st = self._actors.get(actor_id)
        return st.state if st is not None else "UNKNOWN"

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self.head.call(P.KILL_ACTOR, actor_id.binary(), no_restart,
                       timeout=30)

    def get_named_actor(self, name: str) -> Optional[ActorID]:
        state, addr = self.head.call(P.GET_ACTOR, name, timeout=30)
        if state == "NOT_FOUND":
            return None
        # name lookup returns only existence; the id comes via kv
        data = self.kv_get("named_actor", name)
        if data is None:
            return None
        return ActorID(data)

    # ================================================== executor (workers)

    def run_executor(self):
        """Worker main loop: execute pushed tasks until shutdown.

        Actors created with ``max_concurrency > 1`` (the reference's threaded
        actors, core_worker concurrency groups) run their method calls on a
        thread pool of that size; everything else executes inline, in push
        order.
        """
        pool = None
        # Batched completions (TASK_DONE_BATCH, the return-side mirror of
        # PUSH_TASK_BATCH): replies buffer per pushing connection while
        # MORE tasks are already queued, and flush the moment the queue
        # empties (or the batch cap is hit) — so a noop flood acks
        # hundreds of tasks per frame while a lone task's reply is never
        # deferred. A finished result can never be withheld behind a
        # long-running next task either: the reply flusher thread sends
        # anything still buffered ~1 ms after the executor moves on, so
        # the deferral window is bounded by milliseconds, not by the
        # next task's duration.
        batch_cap = get_config().task_done_batch_max
        if batch_cap:
            threading.Thread(target=self._reply_flusher_loop,
                             daemon=True, name="reply-flusher").start()
        while not self._shutdown:
            try:
                item = self._exec_queue.get(timeout=1.0)
            except queue_mod.Empty:
                self._flush_task_replies()  # paranoia: nothing lingers
                continue
            if item is None:
                break
            spec, conn = item
            aspec = self._actor_spec
            if (aspec is not None and aspec.max_concurrency > 1
                    and spec.task_type == TaskType.ACTOR_TASK
                    and spec.method_name != "__ray_terminate__"):
                self._flush_task_replies()
                if pool is None:
                    import concurrent.futures as cf

                    pool = cf.ThreadPoolExecutor(
                        max_workers=aspec.max_concurrency,
                        thread_name_prefix="actor-exec")
                pool.submit(self._execute_safe, spec, conn)
            else:
                if spec.method_name == "__ray_terminate__":
                    # terminate replies inline then os._exit's — anything
                    # still buffered would be lost with the process
                    self._flush_task_replies()
                    if pool is not None:
                        # Drain in-flight pooled tasks before
                        # _graceful_exit's os._exit — otherwise their
                        # callers see 'worker died' instead of results
                        # (same semantics as serial actors, where
                        # terminate queues behind pending tasks).
                        pool.shutdown(wait=True)
                        pool = None
                    self._execute_safe(spec, conn)
                    continue
                reply = self._execute_guarded(spec, conn)
                if reply is None:
                    # inline-replied (actor creation) or crashed — flush
                    # so nothing waits behind a reply that never comes
                    self._flush_task_replies()
                    continue
                if not batch_cap:
                    self._send_task_reply(conn, reply)
                    continue
                with self._reply_lock:
                    self._reply_buf.setdefault(conn, []).append(reply)
                    self._reply_n += 1
                    n = self._reply_n
                if n >= batch_cap or self._exec_queue.empty():
                    self._flush_task_replies()
                else:
                    # more tasks queued: defer — the flusher bounds how
                    # long, in case the next task runs for minutes
                    self._reply_event.set()
        self._flush_task_replies()

    def _reply_flusher_loop(self):
        """Bounds the completion-batching deferral window: the serial
        executor only defers a reply while more tasks are queued; if the
        NEXT task runs long, this thread ships the already-finished
        results ~1 ms later instead of letting them ride out that
        execution (preserving the pre-batching guarantee that a slow
        task never withholds an earlier task's finished result)."""
        while not self._shutdown:
            if not self._reply_event.wait(0.5):
                continue
            time.sleep(0.001)  # let a fast burst accumulate
            self._flush_task_replies()
            with self._reply_lock:
                if not self._reply_n:
                    self._reply_event.clear()

    def _send_task_reply(self, conn: P.Connection, reply):
        try:
            conn.send(P.TASK_REPLY, *reply)
        except P.ConnectionLost:
            pass

    def _flush_task_replies(self):
        """Send buffered completions — one TASK_DONE_BATCH frame per
        connection (plain TASK_REPLY when only one is pending). Called
        from the executor and the reply flusher; the buffer swap under
        the lock makes it safe from both."""
        with self._reply_lock:
            if not self._reply_n:
                return
            pending = self._reply_buf
            self._reply_buf = {}
            self._reply_n = 0
        for conn, replies in pending.items():
            try:
                if len(replies) == 1:
                    conn.send(P.TASK_REPLY, *replies[0])
                else:
                    conn.send(P.TASK_DONE_BATCH, replies)
                    P.WIRE.task_done_batches += 1
                    P.WIRE.task_done_batched += len(replies)
            except P.ConnectionLost:
                pass  # conn.on_close / lease loss handles the fallout

    def _execute_safe(self, spec: TaskSpec, conn: P.Connection):
        """Execute and reply immediately (threaded-actor pool path and
        terminate; the serial executor loop batches instead). Immediate
        replies keep concurrent pooled calls independent — a slow pooled
        task never withholds a finished sibling's result."""
        reply = self._execute_guarded(spec, conn)
        if reply is not None:
            self._send_task_reply(conn, reply)

    def _execute_guarded(self, spec: TaskSpec, conn: P.Connection):
        try:
            return self._execute(spec, conn)
        except P.ConnectionLost:
            pass
        except Exception:
            traceback.print_exc()
        return None

    def _mark_running(self, spec: TaskSpec):
        """Stamp RUNNING once this task's args are materialized (the
        FETCHING_ARGS->RUNNING gap is the arg_fetch phase). Trace ids
        come from the TLS stash _execute set, so the RUNNING event pairs
        with the same span FINISHED closes."""
        info = getattr(self._task_tls, "exec_trace", None)
        label, trace_id, span_id, parent_id = info or (
            spec.name or spec.method_name or spec.function_id, "", "", "")
        self.events.record(spec.task_id.hex(), label, task_events.RUNNING,
                           trace_id=trace_id, span_id=span_id,
                           parent_span_id=parent_id)

    def _decode_args(self, spec: TaskSpec):
        vals = []
        for entry in spec.args:
            if entry[0] == ARG_VALUE:
                v = deserialize(entry[1])
                vals.append(v)
            else:
                ref = ObjectRef(ObjectID(entry[1]), entry[2])
                self.notify_deserialized_ref(ref)
                vals.append(self.get([ref])[0])
        nk = len(spec.kwarg_names)
        if nk:
            pos, kw_vals = vals[:-nk], vals[-nk:]
            kwargs = dict(zip(spec.kwarg_names, kw_vals))
        else:
            pos, kwargs = vals, {}
        return pos, kwargs

    def _execute(self, spec: TaskSpec, conn: P.Connection):
        """Run one task; returns the TASK_REPLY fields (or None when the
        reply was already sent inline — creation/terminate paths).

        The execution is auto-wrapped in a trace span parented to the
        submit site (spec.trace_ctx): the task's RUNNING->FINISHED pair
        IS the span, and the ambient trace context is installed for the
        duration so tracing.span() inside user code nests under it
        (reference: tracing_helper.py _inject_tracing_into_function).

        FETCHING_ARGS is stamped on entry and RUNNING only after the
        by-ref args resolved (_mark_running, called from each
        _decode_args site) — the gap IS the arg_fetch phase, so a task
        stalled pulling a remote arg is distinguishable from one
        executing slowly."""
        label = spec.name or spec.method_name or spec.function_id
        trace_id, parent_id = spec.trace_ctx or ("", "")
        span_id = task_events.new_span_id() if trace_id else ""
        self.events.record(spec.task_id.hex(), label,
                           task_events.FETCHING_ARGS,
                           trace_id=trace_id, span_id=span_id,
                           parent_span_id=parent_id)
        self._task_tls.exec_trace = (label, trace_id, span_id, parent_id)
        prev = task_events.set_trace(
            (trace_id, span_id) if trace_id else None)
        try:
            out = self._execute_inner(spec, conn)
        finally:
            task_events.set_trace(prev)
            self._task_tls.exec_trace = None
        if out is None or out[1] == "ok":
            self.events.record(spec.task_id.hex(), label,
                               task_events.FINISHED,
                               trace_id=trace_id, span_id=span_id,
                               parent_span_id=parent_id)
        else:
            self.events.record(
                spec.task_id.hex(), label,
                task_events.FAILED if out[1] == "error" else out[1].upper(),
                error=repr(out[3]) if out[3] is not None else "",
                trace_id=trace_id, span_id=span_id,
                parent_span_id=parent_id)
        return out

    def _execute_inner(self, spec: TaskSpec, conn: P.Connection):
        if spec.task_id in self._cancelled:
            return (spec.task_id.binary(), "cancelled", None, None)
        self.current_task_id = spec.task_id
        self.current_job_id = spec.job_id
        if spec.tpu_ids is not None:
            # Export the head-assigned chips before user code imports JAX
            # (the reference sets CUDA_VISIBLE_DEVICES the same way,
            # worker.py:888).
            self.assigned_tpu_ids = list(spec.tpu_ids)
            os.environ["TPU_VISIBLE_CHIPS"] = ",".join(
                str(i) for i in spec.tpu_ids)
        try:
            if spec.task_type == TaskType.ACTOR_CREATION:
                if spec.runtime_env:
                    # actor env persists for the actor process's lifetime
                    # (the worker is dedicated) — enter without exit
                    from ray_tpu import runtime_env as _renv

                    _renv.applied(self, spec.runtime_env).__enter__()
                cls = self.fn_manager.fetch(spec.function_id)
                args, kwargs = self._decode_args(spec)
                self._mark_running(spec)
                self._actor_instance = cls(*args, **kwargs)
                self._actor_spec = spec
                if spec.name:
                    self.kv_put("named_actor", spec.name,
                                spec.actor_id.binary(), True)
                conn.send(P.TASK_REPLY, spec.task_id.binary(), "ok", [], None)
                return None
            if spec.task_type == TaskType.ACTOR_TASK:
                if self._actor_instance is None:
                    raise RuntimeError("actor not initialized")
                if spec.method_name == "__ray_terminate__":
                    conn.send(P.TASK_REPLY, spec.task_id.binary(), "ok",
                              [("v", [bytes(f) for f in
                                      serialize(None).frames])], None)
                    self._graceful_exit()
                    return None
                fn = getattr(self._actor_instance, spec.method_name)
                args, kwargs = self._decode_args(spec)
                self._mark_running(spec)
                result = self._call(fn, args, kwargs)
            elif spec.runtime_env:
                from ray_tpu import runtime_env as _renv

                fn = self.fn_manager.fetch(spec.function_id)
                args, kwargs = self._decode_args(spec)
                self._mark_running(spec)
                with _renv.applied(self, spec.runtime_env):
                    result = self._call(fn, args, kwargs)
            else:
                fn = self.fn_manager.fetch(spec.function_id)
                args, kwargs = self._decode_args(spec)
                self._mark_running(spec)
                result = self._call(fn, args, kwargs)
        except Exception as e:  # noqa: BLE001
            te = TaskError(repr(e), traceback.format_exc(), e)
            if spec.task_type == TaskType.ACTOR_CREATION:
                try:
                    conn.send(P.TASK_REPLY, spec.task_id.binary(), "error",
                              None, te)
                except P.ConnectionLost:
                    pass
                try:
                    self.head.send(P.ACTOR_DEAD, spec.actor_id.binary(),
                                   repr(e))
                finally:
                    os._exit(1)
            return (spec.task_id.binary(), "error", None, te)
        try:
            result_meta = self._encode_results(spec, result)
        except Exception as e:  # noqa: BLE001 — e.g. unserializable return
            te = TaskError(repr(e), traceback.format_exc(), None)
            return (spec.task_id.binary(), "error", None, te)
        return (spec.task_id.binary(), "ok", result_meta, None)

    def _call(self, fn, args, kwargs):
        import inspect

        result = fn(*args, **kwargs)
        if inspect.iscoroutine(result):
            result = self._run_async(result)
        return result

    def _run_async(self, coro):
        import asyncio

        if self._async_loop is None:
            self._async_loop = asyncio.new_event_loop()
            t = threading.Thread(target=self._async_loop.run_forever,
                                 daemon=True, name="async-actor")
            t.start()
        fut = asyncio.run_coroutine_threadsafe(coro, self._async_loop)
        return fut.result()

    def _encode_results(self, spec: TaskSpec, result):
        cfg = get_config()
        if spec.num_returns == 1:
            results = [result]
        else:
            results = list(result)
            if len(results) != spec.num_returns:
                raise ValueError(
                    f"task declared num_returns={spec.num_returns} but "
                    f"returned {len(results)} values")
        meta = []
        for oid, value in zip(spec.return_ids(), results):
            sv = serialize(value)
            if sv.contained_refs:
                self._pin_for_handoff(sv.contained_refs)
            if sv.total_bytes < cfg.max_inline_object_size and \
                    not sv.contained_refs:
                # out-of-band frames may be memoryviews (PickleBuffer.raw);
                # materialize them — the reply itself is pickled in-band
                meta.append(("v", [bytes(f) if isinstance(f, memoryview)
                                   else f for f in sv.frames]))
            else:
                # contains() guard: lineage reconstruction can re-run a task
                # on a node that still holds the previous copy of its result
                if not self.store.contains(oid):
                    self.store.put_serialized(oid, sv.frames)
                self.head.send(P.OBJECT_SEALED, oid.binary(), self.node_idx,
                               sv.total_bytes, spec.owner,
                               spec.job_id.hex())
                meta.append(("p", self.node_idx))
        return meta

    def _pin_for_handoff(self, refs, ttl_s: float = 5.0):
        with self._handoff_lock:
            self._handoff_pins.append((time.monotonic() + ttl_s,
                                       list(refs)))
        self._purge_handoff_pins()

    def _purge_handoff_pins(self):
        """Also driven by the submitter loop's wakeups, so the LAST batch
        of pinned refs releases on time instead of leaking until exit."""
        now = time.monotonic()
        with self._handoff_lock:
            while self._handoff_pins and self._handoff_pins[0][0] < now:
                self._handoff_pins.popleft()

    def _graceful_exit(self):
        self._shutdown = True
        try:
            self.head.send(P.WORKER_EXIT)
        except P.ConnectionLost:
            pass
        os._exit(0)

    # ================================================== lifecycle

    def node_info(self) -> list:
        return self.head.call(P.NODE_INFO, timeout=30)[0]

    def shutdown(self):
        self._flush_frees()  # before _shutdown flips: conns still up
        self._shutdown = True
        self.events.stop()
        self._submit_event.set()
        with self._sub_lock:
            for st in self._classes.values():
                for w in st.workers:
                    try:
                        self.head.send(P.RETURN_WORKER, w.lease_id,
                                       w.worker_id)
                    except P.ConnectionLost:
                        pass
                    w.conn.on_close = None
                    w.conn.close()
        try:
            self.head.close()
        except Exception:
            pass
        agent = getattr(self, "_local_agent", None)
        if agent is not None:  # remote-driver mode: our in-process node
            try:
                agent.shutdown()
            except Exception:
                pass
        self.io.stop()
        try:
            self._listener.close()
            if self.listen_path:
                os.unlink(self.listen_path)
        except OSError:
            pass
        try:
            self.store.close()
        except Exception:
            pass
