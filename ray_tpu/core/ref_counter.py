"""Distributed reference counting for object GC.

Analog of the reference's ``ReferenceCounter``
(src/ray/core_worker/reference_count.h:61, ~1.6k LoC) — the owner of each
object tracks (a) its own process-local Python refs, (b) submitted-task
arguments in flight, and (c) remote borrowers. When all three hit zero the
object is freed from the shared-memory store cluster-wide. Borrowers report
via BORROW_ADD/BORROW_REMOVE control messages (the reference uses the
WaitForRefRemoved pubsub protocol).

Freeing an OWNED object is deferred by a short grace window: BORROW_ADD
from a process that just deserialized the ref (task executor, queue
actor, chained borrower) races the release that drops our last pin on a
DIFFERENT connection, and an immediate free would delete an object a
peer is about to use (the reference closes this by shipping borrow
metadata inside task replies; the grace re-check achieves the same
safety with bounded extra lifetime).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional, Set

from .ids import ObjectID


class _Count:
    __slots__ = ("local", "task_args", "borrowers", "owned", "freed",
                 "ever_shared")

    def __init__(self):
        self.local = 0
        self.task_args = 0
        self.borrowers: Set[str] = set()
        self.owned = False
        self.freed = False
        # ever lent out (task arg / borrower): only shared objects need
        # the grace-deferred free — an object that never left this
        # process cannot have a BORROW_ADD in flight, and deferring its
        # free keeps arena space pinned (put-churn bandwidth collapses)
        self.ever_shared = False

    def total(self) -> int:
        return self.local + self.task_args + len(self.borrowers)


class ReferenceCounter:
    def __init__(self, my_id: str,
                 free_callback: Callable[[ObjectID], None],
                 borrow_release_callback: Callable[[ObjectID, str], None]):
        """free_callback: invoked (owner side) when an owned object's count
        hits zero. borrow_release_callback(oid, owner): invoked (borrower
        side) when our local refs on a borrowed object hit zero."""
        self._my_id = my_id
        # RLock: ObjectRef.__del__ can fire from the GC during an
        # allocation made INSIDE a locked section (observed: _Count()
        # in add_local_ref) and re-enter via remove_local_ref — a plain
        # Lock self-deadlocks the whole process there.
        self._lock = threading.RLock()
        self._counts: Dict[ObjectID, _Count] = {}
        self._free_cb = free_callback
        self._borrow_release_cb = borrow_release_callback
        self._owners: Dict[ObjectID, Optional[str]] = {}
        self._grace_s = 1.0  # in-flight BORROW_ADD window
        # one reaper thread drains the deferred-free queue (a Timer per
        # object would spawn a thread per free — hundreds under data
        # workloads)
        self._deferred: "deque" = deque()  # (deadline, oid)
        self._reaper_wake = threading.Event()
        self._reaper: Optional[threading.Thread] = None

    def _schedule_free(self, oid: ObjectID):
        """Free after the grace window IF the count is still zero (a
        late-arriving borrow resurrects the entry and cancels the free)."""
        self._deferred.append((time.monotonic() + self._grace_s, oid))
        if self._reaper is None:
            with self._lock:
                if self._reaper is None:
                    self._reaper = threading.Thread(
                        target=self._reap_loop, daemon=True,
                        name="ref-reaper")
                    self._reaper.start()
        self._reaper_wake.set()

    def _reap_loop(self):
        while True:
            if not self._deferred:
                self._reaper_wake.wait(timeout=5.0)
                self._reaper_wake.clear()
                continue
            deadline, oid = self._deferred[0]
            delay = deadline - time.monotonic()
            if delay > 0:
                time.sleep(min(delay, 0.2))
                continue
            self._deferred.popleft()
            self._free_if_still_zero(oid)

    def _free_if_still_zero(self, oid: ObjectID):
        to_free = None
        with self._lock:
            c = self._counts.get(oid)
            if c is not None and c.total() <= 0 and c.owned and \
                    not c.freed:
                c.freed = True
                to_free = oid
                self._counts.pop(oid, None)
        if to_free is not None:
            self._free_cb(to_free)

    def add_owned(self, oid: ObjectID):
        with self._lock:
            c = self._counts.setdefault(oid, _Count())
            c.owned = True

    def add_local_ref(self, ref) -> None:
        with self._lock:
            c = self._counts.setdefault(ref.id, _Count())
            c.local += 1
            if not c.owned:
                self._owners[ref.id] = ref.owner

    def remove_local_ref(self, ref) -> None:
        free_now = None
        defer_free = None
        borrow_release = None
        with self._lock:
            c = self._counts.get(ref.id)
            if c is None:
                return
            c.local -= 1
            if c.local <= 0 and c.task_args == 0:
                if c.owned and not c.borrowers and not c.freed:
                    if c.ever_shared:
                        defer_free = ref.id
                    else:  # never left this process: free immediately
                        c.freed = True
                        free_now = ref.id
                        self._counts.pop(ref.id, None)
                elif not c.owned:
                    owner = self._owners.pop(ref.id, None)
                    self._counts.pop(ref.id, None)
                    if owner:
                        borrow_release = (ref.id, owner)
        if free_now is not None:
            self._free_cb(free_now)
        if defer_free is not None:
            self._schedule_free(defer_free)
        if borrow_release is not None:
            self._borrow_release_cb(*borrow_release)

    def add_task_arg(self, oid: ObjectID):
        with self._lock:
            c = self._counts.setdefault(oid, _Count())
            c.task_args += 1
            c.ever_shared = True

    def mark_shared(self, oid: ObjectID):
        """The ref escaped this process by some path other than a task
        arg/borrow registration (e.g. serialized inside a put() object a
        peer may deserialize) — its free must take the grace window."""
        with self._lock:
            c = self._counts.get(oid)
            if c is not None:
                c.ever_shared = True

    def remove_task_arg(self, oid: ObjectID):
        defer_free = None
        with self._lock:
            c = self._counts.get(oid)
            if c is None:
                return
            c.task_args -= 1
            if c.total() <= 0 and c.owned and not c.freed:
                defer_free = oid
        if defer_free is not None:
            self._schedule_free(defer_free)

    # owner side: borrower registration
    def add_borrower(self, oid: ObjectID, borrower: str):
        with self._lock:
            c = self._counts.setdefault(oid, _Count())
            c.owned = True
            c.borrowers.add(borrower)
            c.ever_shared = True

    def remove_borrower(self, oid: ObjectID, borrower: str):
        defer_free = None
        with self._lock:
            c = self._counts.get(oid)
            if c is None:
                return
            c.borrowers.discard(borrower)
            if c.total() <= 0 and c.owned and not c.freed:
                defer_free = oid
        if defer_free is not None:
            self._schedule_free(defer_free)

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._counts)
