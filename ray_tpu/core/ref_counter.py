"""Distributed reference counting for object GC.

Analog of the reference's ``ReferenceCounter``
(src/ray/core_worker/reference_count.h:61, ~1.6k LoC) — the owner of each
object tracks (a) its own process-local Python refs, (b) submitted-task
arguments in flight, and (c) remote borrowers. When all three hit zero the
object is freed from the shared-memory store cluster-wide. Borrowers report
via BORROW_ADD/BORROW_REMOVE control messages (the reference uses the
WaitForRefRemoved pubsub protocol).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Set

from .ids import ObjectID


class _Count:
    __slots__ = ("local", "task_args", "borrowers", "owned", "freed")

    def __init__(self):
        self.local = 0
        self.task_args = 0
        self.borrowers: Set[str] = set()
        self.owned = False
        self.freed = False

    def total(self) -> int:
        return self.local + self.task_args + len(self.borrowers)


class ReferenceCounter:
    def __init__(self, my_id: str,
                 free_callback: Callable[[ObjectID], None],
                 borrow_release_callback: Callable[[ObjectID, str], None]):
        """free_callback: invoked (owner side) when an owned object's count
        hits zero. borrow_release_callback(oid, owner): invoked (borrower
        side) when our local refs on a borrowed object hit zero."""
        self._my_id = my_id
        self._lock = threading.Lock()
        self._counts: Dict[ObjectID, _Count] = {}
        self._free_cb = free_callback
        self._borrow_release_cb = borrow_release_callback
        self._owners: Dict[ObjectID, Optional[str]] = {}

    def add_owned(self, oid: ObjectID):
        with self._lock:
            c = self._counts.setdefault(oid, _Count())
            c.owned = True

    def add_local_ref(self, ref) -> None:
        with self._lock:
            c = self._counts.setdefault(ref.id, _Count())
            c.local += 1
            if not c.owned:
                self._owners[ref.id] = ref.owner

    def remove_local_ref(self, ref) -> None:
        to_free = None
        borrow_release = None
        with self._lock:
            c = self._counts.get(ref.id)
            if c is None:
                return
            c.local -= 1
            if c.local <= 0 and c.task_args == 0:
                if c.owned and not c.borrowers and not c.freed:
                    c.freed = True
                    to_free = ref.id
                    self._counts.pop(ref.id, None)
                elif not c.owned:
                    owner = self._owners.pop(ref.id, None)
                    self._counts.pop(ref.id, None)
                    if owner:
                        borrow_release = (ref.id, owner)
        if to_free is not None:
            self._free_cb(to_free)
        if borrow_release is not None:
            self._borrow_release_cb(*borrow_release)

    def add_task_arg(self, oid: ObjectID):
        with self._lock:
            c = self._counts.setdefault(oid, _Count())
            c.task_args += 1

    def remove_task_arg(self, oid: ObjectID):
        to_free = None
        with self._lock:
            c = self._counts.get(oid)
            if c is None:
                return
            c.task_args -= 1
            if c.total() <= 0 and c.owned and not c.freed:
                c.freed = True
                to_free = oid
                self._counts.pop(oid, None)
        if to_free is not None:
            self._free_cb(to_free)

    # owner side: borrower registration
    def add_borrower(self, oid: ObjectID, borrower: str):
        with self._lock:
            c = self._counts.setdefault(oid, _Count())
            c.owned = True
            c.borrowers.add(borrower)

    def remove_borrower(self, oid: ObjectID, borrower: str):
        to_free = None
        with self._lock:
            c = self._counts.get(oid)
            if c is None:
                return
            c.borrowers.discard(borrower)
            if c.total() <= 0 and c.owned and not c.freed:
                c.freed = True
                to_free = oid
                self._counts.pop(oid, None)
        if to_free is not None:
            self._free_cb(to_free)

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._counts)
