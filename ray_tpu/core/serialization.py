"""Serialization: pickle protocol 5 with out-of-band buffers.

Analog of python/ray/_private/serialization.py in the reference (pickle5 +
zero-copy buffer support + custom reducers). We rely on stock pickle (3.12)
plus cloudpickle for closures/lambdas in function descriptors. ObjectRefs
embedded in values are collected during serialization so the borrower
protocol can register them with their owners.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, List, Tuple

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    from ray_tpu.utils import _cloudpickle_stub as cloudpickle  # type: ignore


class SerializedValue:
    """A value serialized into frames: frame 0 is the pickle stream, frames
    1..n are out-of-band buffers (e.g. numpy array payloads).

    Frames may be memoryviews (frame 0 is the BytesIO's exported buffer,
    out-of-band frames are ``PickleBuffer.raw()`` views of the source
    object's memory) — nothing is flattened to bytes at serialize time,
    so a consumer that writes frames straight into a mapped destination
    (``ShmObjectStore.put_serialized``) moves each byte exactly once.
    Consumers that embed frames in a pickled message must materialize
    them (``bytes(f)``) first."""

    __slots__ = ("frames", "contained_refs")

    def __init__(self, frames: List[bytes], contained_refs: List[Any]):
        self.frames = frames
        self.contained_refs = contained_refs

    @property
    def total_bytes(self) -> int:
        return sum(len(f) for f in self.frames)


_ref_cls = None  # lazy: object_ref imports back into core modules


class _RefCollectingPickler(cloudpickle.CloudPickler):
    """Module-level pickler subclass: defining this class INSIDE
    serialize() (the old shape) cost ~20 us of class creation per call
    — the dominant cost of serializing a small task result."""

    def __init__(self, file, buffer_callback, contained_refs):
        super().__init__(file, protocol=5,
                         buffer_callback=buffer_callback)
        self._contained_refs = contained_refs

    def persistent_id(self, obj):
        return None

    def reducer_override(self, obj):
        if isinstance(obj, _ref_cls):
            self._contained_refs.append(obj)
            return (_ref_cls._deserialize, (obj.id.binary(), obj.owner))
        # delegate (NOT NotImplemented): cloudpickle's own
        # reducer_override is what pickles closures/lambdas by value
        return super().reducer_override(obj)


# Types that can never contain an ObjectRef or need out-of-band
# buffers: stock-pickled in one shot, skipping the BytesIO +
# CloudPickler machinery entirely (a no-op task's `return 0` is THE
# common small result at high task rates).
_SCALAR_TYPES = (type(None), bool, int, float)


def serialize(value: Any) -> SerializedValue:
    t = type(value)
    if t in _SCALAR_TYPES or (t is bytes or t is str) and len(value) < 8192:
        return SerializedValue([pickle.dumps(value, protocol=5)], [])
    global _ref_cls
    if _ref_cls is None:
        from .object_ref import ObjectRef as _ref_cls_  # noqa: N813

        _ref_cls = _ref_cls_
    buffers: List[pickle.PickleBuffer] = []
    contained_refs: List[Any] = []
    sio = io.BytesIO()
    p = _RefCollectingPickler(sio, buffers.append, contained_refs)
    p.dump(value)
    # getbuffer(), not getvalue(): the pickle stream stays a zero-copy
    # view of the BytesIO's internal buffer. For in-band-heavy values
    # (bytes/str payloads) getvalue() was a full second traversal of the
    # data before the store copy even started.
    frames = [sio.getbuffer()]
    for b in buffers:
        frames.append(b.raw())
    return SerializedValue(frames, contained_refs)


def deserialize(frames: List) -> Any:
    return pickle.loads(frames[0], buffers=frames[1:])


def dumps(value: Any) -> bytes:
    """One-shot in-band serialization (for control messages)."""
    return cloudpickle.dumps(value, protocol=5)


def loads(data: bytes) -> Any:
    return pickle.loads(data)
