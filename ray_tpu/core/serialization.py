"""Serialization: pickle protocol 5 with out-of-band buffers.

Analog of python/ray/_private/serialization.py in the reference (pickle5 +
zero-copy buffer support + custom reducers). We rely on stock pickle (3.12)
plus cloudpickle for closures/lambdas in function descriptors. ObjectRefs
embedded in values are collected during serialization so the borrower
protocol can register them with their owners.
"""

from __future__ import annotations

import io
import pickle
import sys
from typing import Any, List, Tuple

import numpy as np

try:
    import cloudpickle
except ImportError:  # pragma: no cover
    from ray_tpu.utils import _cloudpickle_stub as cloudpickle  # type: ignore


class SerializedValue:
    """A value serialized into frames: frame 0 is the pickle stream, frames
    1..n are out-of-band buffers (e.g. numpy array payloads).

    Frames may be memoryviews (frame 0 is the BytesIO's exported buffer,
    out-of-band frames are ``PickleBuffer.raw()`` views of the source
    object's memory) — nothing is flattened to bytes at serialize time,
    so a consumer that writes frames straight into a mapped destination
    (``ShmObjectStore.put_serialized``) moves each byte exactly once.
    Consumers that embed frames in a pickled message must materialize
    them (``bytes(f)``) first."""

    __slots__ = ("frames", "contained_refs")

    def __init__(self, frames: List[bytes], contained_refs: List[Any]):
        self.frames = frames
        self.contained_refs = contained_refs

    @property
    def total_bytes(self) -> int:
        return sum(len(f) for f in self.frames)


_ref_cls = None  # lazy: object_ref imports back into core modules


# ------------------------------------------- device-array fast path (r13)
#
# The plasma-analog zero-copy path for accelerator arrays: a jax.Array
# pickles IN-BAND by default (its __reduce__ materializes the host copy
# into the pickle stream — a full extra traversal of the payload before
# the arena copy even starts, measured 0.45 GB/s for the dumps alone at
# 64 MiB). The typed reducer below instead emits dtype/shape metadata in
# frame 0 and the payload as an out-of-band PickleBuffer VIEW of the
# array's host buffer (np.asarray of a committed CPU array aliases the
# XLA buffer; on TPU it is the one unavoidable device->host transfer),
# so put_serialized moves each byte exactly once, source to arena.

# non-contiguous ndarrays below this stay on the stock (in-band) path:
# the contiguity normalization is a copy, only worth skipping the
# in-band stream copy for payloads that dominate serialize time
_NDARRAY_OOB_MIN_BYTES = 1 << 20


def _rebuild_device_array(dtype, shape, f_order, buf):
    """Inverse of the jax.Array reducer: rebuild from the (possibly
    arena-backed) out-of-band buffer. The dlpack import is zero-copy
    where XLA supports aliasing host buffers; platforms that do not
    (and readonly wire frames, and dtypes dlpack can't express, e.g.
    bfloat16) pay exactly one copy — the host->device transfer analog.
    The numpy view keeps the buffer (and through the borrow-pin ledger,
    the arena slice) alive for as long as the consumer aliases it."""
    arr = np.frombuffer(buf, dtype=dtype).reshape(
        shape, order="F" if f_order else "C")
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:  # consumer process never imported jax
        try:
            import jax as jax_mod  # noqa: F811
        except ImportError:  # pragma: no cover — cpu-only consumer
            return arr
    try:
        return jax_mod.numpy.from_dlpack(arr)
    except (BufferError, TypeError, ValueError, RuntimeError):
        # readonly buffer / dtype outside the dlpack spec: one copy
        return jax_mod.numpy.asarray(arr)


def _rebuild_host_array(dtype, shape, f_order, buf):
    return np.frombuffer(buf, dtype=dtype).reshape(
        shape, order="F" if f_order else "C")


def _payload_buffer(host: "np.ndarray") -> pickle.PickleBuffer:
    """Zero-copy byte view of a contiguous array's memory. Exported as
    flat uint8: dtypes outside the buffer-protocol spec (bfloat16 and
    friends — 'cannot include dtype in a buffer') carry their type in
    frame 0's dtype arg instead, and the rebuild's np.frombuffer
    interprets raw bytes under any registered dtype."""
    f_order = host.flags.f_contiguous and not host.flags.c_contiguous
    flat = host.reshape(-1, order="F" if f_order else "C")
    return pickle.PickleBuffer(flat.view(np.uint8))


def _device_reduce(obj):
    """Typed reducer for device arrays (and large non-contiguous host
    arrays); None delegates to the default pickling path. Gated by
    ``serialization_device_zero_copy`` (the bench A/B control)."""
    from .config import get_config

    if not get_config().serialization_device_zero_copy:
        return None
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None and isinstance(obj, jax_mod.Array):
        try:
            host = np.asarray(obj)
            if not (host.flags.c_contiguous or host.flags.f_contiguous):
                host = np.ascontiguousarray(host)
            return (_rebuild_device_array,
                    (host.dtype, host.shape,
                     bool(host.flags.f_contiguous
                          and not host.flags.c_contiguous),
                     _payload_buffer(host)))
        except Exception:  # noqa: BLE001 — non-addressable shards,
            return None    # exotic dtypes: the default path still works
    if type(obj) is np.ndarray and obj.nbytes >= _NDARRAY_OOB_MIN_BYTES \
            and not (obj.flags.c_contiguous or obj.flags.f_contiguous):
        # stock pickle5 already ships contiguous ndarrays out-of-band;
        # strided views would go IN-BAND via tobytes() — normalize once
        # and ship the contiguous copy out-of-band instead
        try:
            host = np.ascontiguousarray(obj)
            return (_rebuild_host_array,
                    (host.dtype, host.shape, False,
                     _payload_buffer(host)))
        except Exception:  # noqa: BLE001
            return None
    return None


class _RefCollectingPickler(cloudpickle.CloudPickler):
    """Module-level pickler subclass: defining this class INSIDE
    serialize() (the old shape) cost ~20 us of class creation per call
    — the dominant cost of serializing a small task result."""

    def __init__(self, file, buffer_callback, contained_refs):
        super().__init__(file, protocol=5,
                         buffer_callback=buffer_callback)
        self._contained_refs = contained_refs

    def persistent_id(self, obj):
        return None

    def reducer_override(self, obj):
        if isinstance(obj, _ref_cls):
            self._contained_refs.append(obj)
            return (_ref_cls._deserialize, (obj.id.binary(), obj.owner))
        r = _device_reduce(obj)
        if r is not None:
            return r
        # delegate (NOT NotImplemented): cloudpickle's own
        # reducer_override is what pickles closures/lambdas by value
        return super().reducer_override(obj)


# Types that can never contain an ObjectRef or need out-of-band
# buffers: stock-pickled in one shot, skipping the BytesIO +
# CloudPickler machinery entirely (a no-op task's `return 0` is THE
# common small result at high task rates).
_SCALAR_TYPES = (type(None), bool, int, float)


def serialize(value: Any) -> SerializedValue:
    t = type(value)
    if t in _SCALAR_TYPES or (t is bytes or t is str) and len(value) < 8192:
        return SerializedValue([pickle.dumps(value, protocol=5)], [])
    global _ref_cls
    if _ref_cls is None:
        from .object_ref import ObjectRef as _ref_cls_  # noqa: N813

        _ref_cls = _ref_cls_
    buffers: List[pickle.PickleBuffer] = []
    contained_refs: List[Any] = []
    sio = io.BytesIO()
    p = _RefCollectingPickler(sio, buffers.append, contained_refs)
    p.dump(value)
    # getbuffer(), not getvalue(): the pickle stream stays a zero-copy
    # view of the BytesIO's internal buffer. For in-band-heavy values
    # (bytes/str payloads) getvalue() was a full second traversal of the
    # data before the store copy even started.
    frames = [sio.getbuffer()]
    for b in buffers:
        frames.append(b.raw())
    return SerializedValue(frames, contained_refs)


def deserialize(frames: List) -> Any:
    return pickle.loads(frames[0], buffers=frames[1:])


def dumps(value: Any) -> bytes:
    """One-shot in-band serialization (for control messages)."""
    return cloudpickle.dumps(value, protocol=5)


def loads(data: bytes) -> Any:
    return pickle.loads(data)
