"""User-visible exceptions (analog of python/ray/exceptions.py)."""

from __future__ import annotations


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task; re-raised on get()."""

    def __init__(self, cause_repr: str, traceback_str: str, cause=None):
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(f"task failed: {cause_repr}\n{traceback_str}")

    def __reduce__(self):
        import pickle

        cause = self.cause
        try:
            pickle.dumps(cause)
        except Exception:
            cause = None
        return (TaskError, (self.cause_repr, self.traceback_str, cause))

    def as_instance(self):
        if isinstance(self.cause, BaseException):
            return RayTaskError(self)
        return self


class RayTaskError(RayTpuError):
    def __init__(self, task_error: TaskError):
        self.task_error = task_error
        super().__init__(str(task_error))

    @property
    def cause(self):
        return self.task_error.cause

    def __reduce__(self):
        return (RayTaskError, (self.task_error,))


class WorkerCrashedError(RayTpuError):
    pass


class ActorDiedError(RayTpuError):
    pass


class ActorUnavailableError(RayTpuError):
    pass


class ObjectLostError(RayTpuError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class TaskCancelledError(RayTpuError):
    pass


class PlacementGroupError(RayTpuError):
    pass
