"""Function/class export and caching.

Analog of python/ray/_private/function_manager.py in the reference: remote
functions and actor classes are cloudpickled once, exported to the head KV
under a content-hash key, and lazily fetched + cached by executing workers.
"""

from __future__ import annotations

import hashlib
import threading
import weakref
from typing import Any, Callable, Dict


class FunctionManager:
    NS = "fn"

    def __init__(self, kv_put: Callable, kv_get: Callable):
        self._kv_put = kv_put
        self._kv_get = kv_get
        self._exported: set = set()
        self._cache: Dict[str, Any] = {}
        # fn object -> exported id; export() is on the per-task submit hot
        # path, so the cloudpickle+hash must run once per function object,
        # not once per task. Weak keys: dropping the last user reference to
        # a remote function must not pin it here.
        self._id_by_obj: "weakref.WeakKeyDictionary[Any, str]" = \
            weakref.WeakKeyDictionary()
        self._lock = threading.Lock()

    def export(self, obj: Any) -> str:
        """Serialize a function/class, export to KV, return its id.

        Memoized by object identity: a remote function's code and captured
        globals are snapshotted at FIRST submission, and later mutations of
        captured globals are not re-exported (matches the reference —
        python/ray/remote_function.py pickles once per function object, so
        mutating a module global between calls was never propagated there
        either). Redefine the function to pick up new state."""
        try:
            fn_id = self._id_by_obj.get(obj)
        except TypeError:  # unhashable/unweakrefable callable
            fn_id = None
        if fn_id is not None:
            return fn_id
        from .serialization import dumps

        data = dumps(obj)
        fn_id = hashlib.blake2b(data, digest_size=16).hexdigest()
        with self._lock:
            done = fn_id in self._exported
        if not done:
            self._kv_put(self.NS, fn_id, data, True)
            with self._lock:
                self._exported.add(fn_id)
                self._cache[fn_id] = obj
        try:
            self._id_by_obj[obj] = fn_id
        except TypeError:
            pass
        return fn_id

    def fetch(self, fn_id: str) -> Any:
        with self._lock:
            if fn_id in self._cache:
                return self._cache[fn_id]
        data = self._kv_get(self.NS, fn_id)
        if data is None:
            raise KeyError(f"function {fn_id} not found in KV")
        from .serialization import loads

        obj = loads(data)
        with self._lock:
            self._cache[fn_id] = obj
        return obj
