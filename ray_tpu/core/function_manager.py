"""Function/class export and caching.

Analog of python/ray/_private/function_manager.py in the reference: remote
functions and actor classes are cloudpickled once, exported to the head KV
under a content-hash key, and lazily fetched + cached by executing workers.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Callable, Dict


class FunctionManager:
    NS = "fn"

    def __init__(self, kv_put: Callable, kv_get: Callable):
        self._kv_put = kv_put
        self._kv_get = kv_get
        self._exported: set = set()
        self._cache: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def export(self, obj: Any) -> str:
        """Serialize a function/class, export to KV, return its id."""
        from .serialization import dumps

        data = dumps(obj)
        fn_id = hashlib.blake2b(data, digest_size=16).hexdigest()
        with self._lock:
            if fn_id in self._exported:
                return fn_id
        self._kv_put(self.NS, fn_id, data, True)
        with self._lock:
            self._exported.add(fn_id)
            self._cache[fn_id] = obj
        return fn_id

    def fetch(self, fn_id: str) -> Any:
        with self._lock:
            if fn_id in self._cache:
                return self._cache[fn_id]
        data = self._kv_get(self.NS, fn_id)
        if data is None:
            raise KeyError(f"function {fn_id} not found in KV")
        from .serialization import loads

        obj = loads(data)
        with self._lock:
            self._cache[fn_id] = obj
        return obj
