"""Head-side log monitor: tail worker log files -> driver mirroring.

The reference runs a log_monitor.py process per node that tails
`/tmp/ray/session_*/logs/worker-*` files and pushes appended lines to
drivers over GCS pubsub; the driver prints them prefixed with the worker
pid (python/ray/_private/log_monitor.py:103, worker.py print_logs). Here
the monitor is a thread inside the head process (the head already hosts
every local node's workers and receives remote agents' log lines over
their control connection), publishing on the "logs" pubsub channel that
drivers subscribe to when ``log_to_driver=True``.
"""

from __future__ import annotations

import os
import threading
from typing import Dict

POLL_PERIOD_S = 0.3


class LogMonitor:
    """Tails `{session_dir}/logs/worker-*.out` and publishes new lines."""

    def __init__(self, session_dir: str, publish, period_s: float = POLL_PERIOD_S):
        self.log_dir = os.path.join(session_dir, "logs")
        self._publish = publish          # callable(channel: str, payload)
        self._period = period_s
        self._offsets: Dict[str, int] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="log-monitor")

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(self._period):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — monitoring must not die
                pass

    def poll_once(self):
        if not os.path.isdir(self.log_dir):
            return
        for fname in sorted(os.listdir(self.log_dir)):
            if not fname.endswith(".out"):
                continue
            path = os.path.join(self.log_dir, fname)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            off = self._offsets.get(fname, 0)
            if size <= off:
                if size < off:  # truncated/rotated — restart from 0
                    self._offsets[fname] = 0
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(off)
                    chunk = f.read(min(size - off, 1 << 20))
            except OSError:
                continue
            # only ship complete lines; carry partials to the next poll —
            # unless the window is full (a single line larger than the cap
            # would otherwise stall this file's tailing forever): then
            # ship the whole window as one (split) line and move on
            nl = chunk.rfind(b"\n")
            if nl < 0:
                if len(chunk) < (1 << 20):
                    continue
                nl = len(chunk)
            self._offsets[fname] = off + min(nl + 1, len(chunk))
            lines = chunk[:nl].decode("utf-8", "replace").splitlines()
            if lines:
                self._publish("logs", {
                    "source": fname[:-len(".out")],
                    "lines": lines,
                })
