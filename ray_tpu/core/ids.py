"""Binary identifiers for jobs, tasks, actors, objects, nodes, placement groups.

Design follows the reference's ID scheme (src/ray/common/id.h): fixed-width
binary IDs where child IDs embed parentage (an ObjectID embeds the TaskID that
created it plus a return/put index; a TaskID embeds the ActorID/JobID context).
Unlike the reference we keep them as immutable Python values backed by
``bytes`` — the hot paths that need native speed deal in the object store's
integer handles, not these IDs.
"""

from __future__ import annotations

import os
import threading


class _RandPool:
    """Buffered kernel entropy: one urandom syscall per ~600 IDs.

    Per-call ``os.urandom`` measured ~0.4 ms on the deployment kernel —
    the single largest cost of ``f.remote()`` ID minting (one TaskID +
    one ObjectID per task). Fork safety is preserved by re-keying the
    pool in forked children (workers are fork+exec so they never share
    it, but the multiprocessing shim can fork)."""

    def __init__(self):
        self._buf = b""
        self._off = 0
        self._lock = threading.Lock()

    def take(self, n: int) -> bytes:
        if n > 4096:  # larger than the pool refill: draw directly
            return os.urandom(n)
        with self._lock:
            off = self._off
            if off + n > len(self._buf):
                self._buf = os.urandom(8192)
                off = 0
            self._off = off + n
            return self._buf[off:off + n]


_pool = _RandPool()
os.register_at_fork(after_in_child=_pool.__init__)


def _random_bytes(n: int) -> bytes:
    return _pool.take(n)


class BaseID:
    SIZE = 16
    __slots__ = ("_binary", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._binary = binary
        self._hash = hash(binary)

    @classmethod
    def from_random(cls):
        return cls(_random_bytes(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._binary == b"\x00" * self.SIZE

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._binary == self._binary

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, i: int) -> "JobID":
        return cls(i.to_bytes(4, "little"))

    def to_int(self) -> int:
        return int.from_bytes(self._binary, "little")


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    """12 bytes: 8 random + 4 job id."""

    SIZE = 12

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(_random_bytes(8) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._binary[8:])


class TaskID(BaseID):
    """16 bytes: 4 unique + 12 actor-or-job context."""

    SIZE = 16

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        return cls(_random_bytes(12) + job_id.binary())

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(_random_bytes(4) + actor_id.binary())

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls(b"\xff" * 12 + job_id.binary())


class ObjectID(BaseID):
    """20 bytes: 16-byte parent TaskID + 4-byte index.

    Index semantics match the reference: put objects and return objects draw
    from the same index space (puts are negative in the reference; we use the
    high bit instead).
    """

    SIZE = 20
    PUT_BIT = 0x8000_0000

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + (index | cls.PUT_BIT).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._binary[:16])

    def index(self) -> int:
        return int.from_bytes(self._binary[16:], "little") & ~self.PUT_BIT

    def is_put(self) -> bool:
        return bool(int.from_bytes(self._binary[16:], "little") & self.PUT_BIT)


class PlacementGroupID(BaseID):
    SIZE = 12

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(_random_bytes(8) + job_id.binary())
