"""ObjectRef — the distributed future handle.

Analog of the reference's ``ObjectRef`` (python/ray/_raylet.pyx ObjectRef +
C++ reference_count.h ownership). Each ref knows its ObjectID and its owner
(the worker that created it via ``put`` or task submission). Destruction
decrements the process-local reference count; when the owner observes zero
local refs, zero pending task args, and zero borrowers, the object is freed
from the store (distributed GC).
"""

from __future__ import annotations

from typing import Optional

from .ids import ObjectID


class ObjectRef:
    __slots__ = ("id", "owner", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: Optional[str] = None,
                 _register: bool = True):
        self.id = object_id
        self.owner = owner  # owker id hex string of owning worker, or None=local
        self._registered = False
        if _register:
            from .context import get_context_if_exists

            ctx = get_context_if_exists()
            if ctx is not None:
                ctx.ref_counter.add_local_ref(self)
                self._registered = True
                # Borrower registration with the owner (no-op if we own it).
                ctx.notify_deserialized_ref(self)

    @staticmethod
    def _deserialize(binary: bytes, owner: Optional[str]) -> "ObjectRef":
        return ObjectRef(ObjectID(binary), owner)

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id.binary()

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from .context import get_context

        return get_context().as_future(self)

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __del__(self):
        if not self._registered:
            return
        try:
            from .context import get_context_if_exists

            ctx = get_context_if_exists()
            if ctx is not None:
                ctx.ref_counter.remove_local_ref(self)
        except BaseException:
            # Interpreter teardown may have cleared module globals.
            pass

    def __reduce__(self):
        # Plain pickle of a ref (outside the serialization module's borrower
        # tracking) still round-trips, but borrower registration only happens
        # through serialization.serialize().
        return (ObjectRef._deserialize, (self.id.binary(), self.owner))
