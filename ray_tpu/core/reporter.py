"""Node telemetry reporter: /proc sampling -> per-node ``node_*`` gauges.

Ref parity: the reference's reporter agent
(dashboard/modules/reporter/reporter_agent.py — a per-node daemon sampling
psutil CPU/mem/disk/net every few seconds and exporting ``ray_node_*``
gauges through the metrics agent). Re-design: no psutil — the counters are
read straight from ``/proc`` (cpu percent from /proc/stat deltas, memory
from /proc/meminfo, network from /proc/net/dev, disk from /proc/diskstats)
plus the shm object-store fill, and published as plain gauge rows over the
existing METRICS_REPORT channel. The rows land in the head's metric table
(``/api/metrics``, ``/metrics`` Prometheus exposition, ``metrics_summary``)
and the head mirrors them into ``list_nodes()`` rows.

Runs as a daemon thread in every node_agent (one per remote host) and in
the head process (publishing one row-set per local logical node — same
host counters, per-node store fill).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple


def _read_proc_stat() -> Optional[Tuple[float, float]]:
    """(busy_jiffies, total_jiffies) from the aggregate cpu line."""
    try:
        with open("/proc/stat") as f:
            parts = f.readline().split()
    except OSError:
        return None
    if not parts or parts[0] != "cpu":
        return None
    vals = [float(x) for x in parts[1:]]
    total = sum(vals)
    idle = vals[3] + (vals[4] if len(vals) > 4 else 0.0)  # idle + iowait
    return total - idle, total




def _read_net_dev() -> Tuple[float, float]:
    """(rx_bytes, tx_bytes) summed over non-loopback interfaces."""
    rx = tx = 0.0
    try:
        with open("/proc/net/dev") as f:
            for line in f.readlines()[2:]:
                name, _, rest = line.partition(":")
                if name.strip() == "lo":
                    continue
                cols = rest.split()
                if len(cols) >= 9:
                    rx += float(cols[0])
                    tx += float(cols[8])
    except OSError:
        pass
    return rx, tx


def _read_diskstats() -> Tuple[float, float]:
    """(read_bytes, written_bytes) summed over whole devices (heuristic:
    names without a trailing partition digit, plus nvme/mmcblk whole
    disks), sectors * 512."""
    rd = wr = 0.0
    try:
        with open("/proc/diskstats") as f:
            for line in f:
                cols = line.split()
                if len(cols) < 10:
                    continue
                name = cols[2]
                if name.startswith(("loop", "ram", "dm-")):
                    continue
                # skip partitions so bytes aren't double-counted:
                # sda1 (trailing digit) and nvme0n1p2 / mmcblk0p1 (pN tail)
                if name.startswith(("nvme", "mmcblk")):
                    stem, _, tail = name.rpartition("p")
                    if stem and tail.isdigit():
                        continue
                elif name[-1].isdigit():
                    continue
                rd += float(cols[5]) * 512.0
                wr += float(cols[9]) * 512.0
    except OSError:
        pass
    return rd, wr


class NodeTelemetryReporter:
    """Daemon thread sampling host physical stats on a period and
    publishing ``node.*`` gauges tagged by node index.

    ``nodes_fn`` returns the current ``[(node_idx, store_or_None)]`` to
    publish for (an agent has one; the head has all its local nodes).
    ``publish_fn`` receives a METRICS_REPORT-shaped batch of gauge rows:
    ``(kind, name, desc, tag_keys, tags_key, value)``.
    """

    GAUGES = {
        "node.cpu_percent": "Host CPU utilization percent (/proc/stat)",
        "node.mem_used_bytes": "Host memory in use (MemTotal-MemAvailable)",
        "node.mem_total_bytes": "Host memory total (/proc/meminfo)",
        "node.net_rx_bytes": "Cumulative network bytes received",
        "node.net_tx_bytes": "Cumulative network bytes transmitted",
        "node.disk_read_bytes": "Cumulative disk bytes read",
        "node.disk_write_bytes": "Cumulative disk bytes written",
        "node.object_store_used_bytes": "Shm object store bytes in use",
        "node.object_store_capacity_bytes": "Shm object store capacity",
        # arena memory-observatory gauges (store.memory_stats()): one
        # native lock + table scan per sample, piggybacked on this same
        # heartbeat — no extra channel. Flow into the head's metric
        # table (Prometheus + flight-recorder timeseries) AND node rows.
        "object_plane.arena_capacity_bytes": "Arena capacity (bytes)",
        "object_plane.arena_used_bytes":
            "Arena bytes in use (blocks incl. headers)",
        "object_plane.arena_highwater_bytes":
            "Max arena bytes in use ever observed",
        "object_plane.arena_entries": "Live arena entries",
        "object_plane.arena_sealed_bytes":
            "Payload bytes of sealed objects",
        "object_plane.arena_sealed_data_bytes":
            "Sealed object data bytes only (the wire/directory size "
            "convention — per-node directory sums match this exactly)",
        "object_plane.arena_unsealed_bytes":
            "Payload bytes of created-but-unsealed objects",
        "object_plane.arena_pinned_bytes":
            "Payload bytes pinned by native readers",
        "object_plane.arena_borrow_pinned_bytes":
            "Payload bytes pinned by live zero-copy borrow views",
        "object_plane.arena_deferred_deletes":
            "Deletes deferred behind live borrow views",
        "object_plane.arena_deferred_delete_oldest_s":
            "Age of the oldest pending deferred delete (seconds)",
    }

    # memory_stats() key -> gauge name (sample_and_publish)
    _ARENA_GAUGES = {
        "capacity": "object_plane.arena_capacity_bytes",
        "used_bytes": "object_plane.arena_used_bytes",
        "highwater_bytes": "object_plane.arena_highwater_bytes",
        "entries": "object_plane.arena_entries",
        "sealed_bytes": "object_plane.arena_sealed_bytes",
        "sealed_data_bytes": "object_plane.arena_sealed_data_bytes",
        "unsealed_bytes": "object_plane.arena_unsealed_bytes",
        "pinned_bytes": "object_plane.arena_pinned_bytes",
        "borrow_pinned_bytes": "object_plane.arena_borrow_pinned_bytes",
        "deferred_deletes": "object_plane.arena_deferred_deletes",
        "deferred_delete_oldest_s":
            "object_plane.arena_deferred_delete_oldest_s",
    }

    def __init__(self, publish_fn: Callable[[list], None],
                 nodes_fn: Callable[[], List[tuple]],
                 period_s: Optional[float] = None):
        from .config import get_config

        self._publish = publish_fn
        self._nodes = nodes_fn
        self._period = (get_config().node_telemetry_period_s
                        if period_s is None else period_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="node-telemetry")
        self._prev_cpu: Optional[Tuple[float, float]] = None
        self.samples = 0  # observability + tests

    def start(self):
        if self._period > 0:
            self._thread.start()

    def stop(self):
        self._stop.set()

    def sample_host(self) -> Dict[str, float]:
        """One host-wide sample; cpu percent is over the interval since
        the previous call (0.0 on the first)."""
        out: Dict[str, float] = {}
        cur = _read_proc_stat()
        cpu = 0.0
        if cur is not None and self._prev_cpu is not None:
            dbusy = cur[0] - self._prev_cpu[0]
            dtotal = cur[1] - self._prev_cpu[1]
            if dtotal > 0:
                cpu = max(0.0, min(100.0, 100.0 * dbusy / dtotal))
        if cur is not None:
            self._prev_cpu = cur
        out["node.cpu_percent"] = cpu
        from .memory_monitor import read_meminfo_bytes

        total, avail = read_meminfo_bytes()
        out["node.mem_total_bytes"] = float(total)
        out["node.mem_used_bytes"] = float(max(total - avail, 0))
        rx, tx = _read_net_dev()
        out["node.net_rx_bytes"] = rx
        out["node.net_tx_bytes"] = tx
        rd, wr = _read_diskstats()
        out["node.disk_read_bytes"] = rd
        out["node.disk_write_bytes"] = wr
        return out

    def sample_and_publish(self):
        """One sampling round (callable from tests without the thread)."""
        host = self.sample_host()
        batch: list = []
        for node_idx, store in self._nodes():
            vals = dict(host)
            if store is not None:
                try:
                    vals["node.object_store_used_bytes"] = \
                        float(store.bytes_in_use())
                    vals["node.object_store_capacity_bytes"] = \
                        float(store.capacity())
                    mem = store.memory_stats()
                    for key, gname in self._ARENA_GAUGES.items():
                        if key in mem:
                            vals[gname] = float(mem[key])
                except Exception:  # noqa: BLE001 — store closing
                    pass
            tags_key = (str(node_idx),)
            for name, value in vals.items():
                batch.append(("gauge", name, self.GAUGES.get(name, ""),
                              ("node",), tags_key, value))
        if batch:
            self._publish(batch)
            self.samples += 1

    def _loop(self):
        # prime the cpu-delta baseline so the first published percent is
        # over a real interval
        self.sample_host()
        while not self._stop.wait(self._period):
            try:
                self.sample_and_publish()
            except Exception:  # noqa: BLE001 — telemetry must not die
                pass
