"""OOM control: kill the newest busy worker when host memory runs out.

Ref parity: the reference's MemoryMonitor + WorkerKillingPolicy
(src/ray/common/memory_monitor.h:52 polls /proc meminfo on a period;
retriable_lifo_order worker_killing_policy.cc kills the most recently
started retriable task first, so long-running work survives and the
killed task retries with backoff). The kill surfaces to the owner as a
WorkerCrashedError, which the normal retry machinery handles.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional


def read_meminfo_bytes() -> tuple:
    """(total_bytes, available_bytes) from /proc/meminfo — the ONE
    parser shared by the OOM monitor and the telemetry reporter
    (MemAvailable-based; free+cache alone undercounts reclaimable).
    (0, 0) when /proc is unreadable."""
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1])
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1])
                if total is not None and avail is not None:
                    break
    except OSError:
        return 0, 0
    return (total or 0) * 1024, (avail or 0) * 1024


def system_memory_usage_fraction() -> float:
    """Host memory pressure from /proc/meminfo."""
    total, avail = read_meminfo_bytes()
    if not total:
        return 0.0
    return 1.0 - avail / total


class MemoryMonitor:
    """Head-embedded monitor over the local nodes' worker pools."""

    def __init__(self, head, usage_fn: Optional[Callable[[], float]] = None,
                 period_s: Optional[float] = None,
                 threshold: Optional[float] = None):
        from .config import get_config

        cfg = get_config()
        self._head = head
        self._usage_fn = usage_fn or system_memory_usage_fraction
        self._period = period_s if period_s is not None else \
            cfg.memory_monitor_refresh_s
        self._threshold = threshold if threshold is not None else \
            cfg.memory_usage_threshold
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="memory-monitor")
        self.kills = 0  # observability + tests
        # one kill per cooldown: give the freed memory time to show up in
        # the next usage reading before escalating to another victim
        # (the reference re-reads memory after the worker exits)
        self.kill_cooldown_s = 2.0
        self._last_kill = 0.0

    def start(self):
        if self._period > 0:
            self._thread.start()

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(self._period):
            try:
                self.check_once()
            except Exception:  # noqa: BLE001 — monitoring must not die
                pass

    def check_once(self):
        import time

        usage = self._usage_fn()
        if usage < self._threshold:
            return
        if time.monotonic() - self._last_kill < self.kill_cooldown_s:
            return
        victim = self._pick_victim()
        if victim is None:
            return
        self._last_kill = time.monotonic()
        self.kills += 1
        w, node = victim
        import sys

        print(f"ray_tpu memory monitor: host memory at {usage:.0%} >= "
              f"{self._threshold:.0%}; killing worker {w.worker_id[:8]} "
              f"(newest busy, retriable) to relieve pressure",
              file=sys.stderr)
        self._head.emit_event(
            "ERROR", "memory_monitor", "worker_oom_kill",
            f"worker {w.worker_id[:8]} killed: host memory at "
            f"{usage:.0%} >= {self._threshold:.0%}",
            node_idx=w.node_idx, entity_id=w.worker_id,
            extra={"usage": round(usage, 4),
                   "threshold": self._threshold})
        self._head._kill_worker_process(w)
        self._head._handle_worker_death(w)
        with self._head._lock:
            node.workers.pop(w.worker_id, None)

    def _pick_victim(self):
        """Newest BUSY worker (leased or actor), LIFO by spawn time — the
        reference's retriable-LIFO policy: the youngest work loses, so
        long-running tasks keep their progress."""
        with self._head._lock:
            candidates = [
                (w, node)
                for node in self._head.nodes.values()
                if not node.is_remote
                for w in node.workers.values()
                if w.state in ("leased", "actor")
            ]
        if not candidates:
            return None
        return max(candidates, key=lambda wn: wn[0].spawned_at)
