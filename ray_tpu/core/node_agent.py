"""Node agent: joins a remote host to a head over TCP.

Ref analog: the raylet (src/ray/raylet/main.cc:113 — per-node daemon that
registers with the GCS, owns the local object store, and forks workers).
Re-designed small: the head keeps all scheduling state; the agent only
(1) creates the host-local shm object store, (2) forks/kills workers on
demand, (3) serves object reads/writes so the head can move objects
between hosts over the TCP control links.

Run:  python -m ray_tpu.core.node_agent --address tcp:HEAD_IP:PORT \
          [--num-cpus N] [--num-tpus N]
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import uuid
from typing import Dict, Optional

from . import protocol as P
from .config import get_config
from .ids import ObjectID
from .object_store import ShmObjectStore
from .resources import detect_node_resources


def _my_ip(head_host: str, head_port: int) -> str:
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((head_host, head_port))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


class NodeAgent:
    def __init__(self, head_addr: str, *, num_cpus=None, num_tpus=None,
                 object_store_memory=None, resources=None, labels=None):
        assert head_addr.startswith("tcp:"), "agents join over tcp:"
        _, host, port = head_addr.split(":")
        self.head_addr = head_addr
        self.node_ip = _my_ip(host, int(port))
        cfg = get_config()
        cap = object_store_memory or cfg.object_store_memory
        self.store_name = f"rtpu_agent_{uuid.uuid4().hex[:10]}"
        self.store = ShmObjectStore(self.store_name, cap, create=True)
        # agent-side arena evictions (pull/relay writes squeezing out LRU
        # objects) drop copies the head's object directory still lists —
        # report them so pulls stop targeting this host for those ids.
        # Async: evict() fires inside store.create on the allocating
        # thread (the puller IO thread included) and must not block there.
        self.store.on_evict = self._report_evictions_async
        self.session_dir = f"/tmp/ray_tpu/agent_{uuid.uuid4().hex[:8]}"
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self.workers: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()
        self._shutdown = threading.Event()
        # None until REGISTER_NODE's reply lands. The head may race a
        # SPAWN_WORKER onto the socket ahead of that reply (its keeper
        # thread fulfills queued leases the moment the node appears in
        # its tables); those spawns buffer here instead of being dropped.
        self.node_idx: Optional[int] = None
        self._pre_registration_spawns: list = []

        nr = detect_node_resources(num_cpus=num_cpus, num_tpus=num_tpus,
                                   object_store_memory=cap,
                                   resources=resources, labels=labels)
        self._node_resources = nr  # re-sent on re-registration
        self.io = P.IOLoop("agent-io")
        # Direct peer-to-peer object plane (object_transfer.py): this host
        # serves its arena to peers and pulls from theirs — payloads never
        # transit the head.
        from .object_transfer import ObjectPuller, TransferServer

        self.transfer_server = TransferServer(
            self.io, self._read_object, advertise_ip=self.node_ip,
            partial_fn=self.store.partial)
        self.puller = ObjectPuller(self.io, self.store)
        # Reconnecting head channel (GCS-FT analog: the raylet's GCS RPC
        # client retrying across a gcs_server restart): on socket loss
        # the agent re-dials up to head_reconnect_timeout_s, then
        # re-registers with its prior node id, live worker set, and a
        # full holder report so a restarted head rebuilds its node table
        # and object directory from this host's truth. on_close fires
        # only when the window expires — the pre-r12 fail-fast shutdown.
        self.head = P.ReconnectingConnection(
            head_addr, client_id=f"agent:{self.store_name}", peer="head",
            on_reattach=self._on_head_reattach)
        self.head.on_close = lambda c: self._shutdown.set()
        self.io.add_connection(self.head, self._on_head_message)
        self.io.start()
        reply = self.head.call(P.REGISTER_NODE, nr, self.store_name,
                               self.node_ip, self.session_dir,
                               self.transfer_server.addr, timeout=30)
        self.session_name = reply[1]
        with self._lock:
            self.node_idx = reply[0]
            buffered, self._pre_registration_spawns = \
                self._pre_registration_spawns, []
        for worker_id in buffered:
            self._spawn_worker(worker_id)
        # Tail THIS host's worker logs and publish them through the head's
        # "logs" channel so remote tasks' prints reach the driver too
        # (reference: one log_monitor per node, log_monitor.py:103).
        from .log_monitor import LogMonitor
        from .serialization import dumps as _dumps

        def _forward(ch, data):
            data = dict(data)
            data["source"] = f"node{self.node_idx}-" + data.get("source", "")
            try:
                self.head.send(P.PUBLISH, ch, _dumps(data))
            except P.ConnectionLost:
                pass

        self.log_monitor = LogMonitor(self.session_dir, _forward)
        self.log_monitor.start()
        # Physical telemetry for this host -> node.* gauges through the
        # head's metrics channel (reference: reporter_agent.py).
        from .reporter import NodeTelemetryReporter

        def _publish_metrics(batch):
            try:
                self.head.send(P.METRICS_REPORT, batch)
            except P.ConnectionLost:
                pass

        self.telemetry = NodeTelemetryReporter(
            _publish_metrics,
            lambda: [(self.node_idx, self.store)])
        self.telemetry.start()
        # Worker-crash watcher: the head only learns of a remote worker's
        # death via its socket close — the structured WHY (exit signal,
        # OOM kill) is only visible here, next to the process (reference:
        # the raylet's worker-death reporting + the reporter agent's OOM
        # detection feeding the event log).
        self._reaper = threading.Thread(target=self._reap_workers,
                                        daemon=True, name="agent-reaper")
        self._reaper.start()

    def _read_object(self, oid: ObjectID):
        got = self.store.get(oid)
        if got is None:
            return None
        data_v, meta_v = got
        return data_v, bytes(meta_v), lambda: self.store.release(oid)

    # -------------------------------------------------------- head messages

    def _on_head_message(self, conn: P.Connection, msg):
        mt, rid = msg[0], msg[1]
        try:
            if mt == P.SPAWN_WORKER:
                with self._lock:
                    if self.node_idx is None:
                        self._pre_registration_spawns.append(msg[2])
                        return
                self._spawn_worker(msg[2])
            elif mt == P.KILL_WORKER:
                self._kill_worker(msg[2])
            elif mt == P.AGENT_OBJ_GET:
                oid = ObjectID(msg[2])
                got = self.store.get(oid)
                if got is None:
                    conn.reply(rid, None, b"")
                else:
                    data_v, meta_v = got
                    try:
                        conn.reply(rid, bytes(data_v), bytes(meta_v))
                    finally:
                        del data_v, meta_v, got
                        self.store.release(oid)
            elif mt == P.AGENT_OBJ_PUT:
                oid = ObjectID(msg[2])
                payload, meta = msg[3], msg[4]
                if not self.store.contains(oid):
                    buf = self.store.create(oid, len(payload), len(meta))
                    buf[:len(payload)] = payload
                    buf[len(payload):] = meta
                    self.store.seal(oid)
                conn.reply(rid, True)
            elif mt == P.PULL_OBJECT:
                # head says: fetch this object straight from peer hosts —
                # msg carries the directory's holder-address list (or one
                # addr string), the object size for stripe planning, the
                # broadcast planner's stripe cap + relay markers, and the
                # r13 prefetch flag (speculative pull fired at lease
                # grant/dispatch: one-way, acked via PREFETCH_RESULT)
                oid, peers = ObjectID(msg[2]), msg[3]
                size = msg[4] if len(msg) > 4 else -1
                max_sources = msg[5] if len(msg) > 5 else 0
                relays = msg[6] if len(msg) > 6 else ()
                prefetch = bool(msg[7]) if len(msg) > 7 else False
                threading.Thread(
                    target=self._do_pull,
                    args=(conn, rid, oid, peers, size, max_sources,
                          relays, prefetch),
                    daemon=True).start()
            elif mt == P.PULL_ABORT:
                # stale speculation: the prefetched task was cancelled /
                # retried elsewhere — the puller honors this only for
                # prefetch-flagged pulls no demand get() has joined
                self.puller.abort(ObjectID(msg[2]))
            elif mt == P.AGENT_OBJ_FREE:
                for ob in msg[2]:
                    self.store.delete(ObjectID(ob))
            elif mt == P.SHUTDOWN_NODE:
                # deliberate eviction/cluster shutdown: die now — do
                # NOT ride the reconnect window (that is for head
                # CRASHES, where re-registration brings us back)
                self._shutdown.set()
            elif mt == P.PING:
                # health probe doubles as the clock-offset sampler: the
                # head takes the RTT midpoint of this call against our
                # monotonic clock to fold this host's task-event stamps
                # into its own timebase (wall clock rides along for
                # display-only diagnostics)
                conn.reply(rid, True, time.monotonic(), time.time())
        except Exception as e:  # noqa: BLE001
            if rid > 0:
                conn.reply_error(rid, e)

    def _do_pull(self, conn: P.Connection, rid: int, oid: ObjectID,
                 peers, size: int = -1, max_sources: int = 0,
                 relays=(), prefetch: bool = False):
        try:
            ok = self.puller.pull(oid, peers, size_hint=size,
                                  max_sources=max_sources,
                                  relay_addrs=relays, prefetch=prefetch)
            if ok and self.node_idx is not None:
                # report the gained copy so the directory lists this node
                # as a holder independent of the broker path's bookkeeping
                # (idempotent with the head's own _directory_add)
                try:
                    self.head.send(P.OBJ_LOCATION_ADD, oid.binary(),
                                   self.node_idx, max(size, 0))
                except P.ConnectionLost:
                    pass
            if prefetch:
                # one-way speculative pull: no blocked caller to reply
                # to — the result frame lets the head release the source
                # charges it registered at issue time
                try:
                    conn.send(P.PREFETCH_RESULT, oid.binary(),
                              self.node_idx if self.node_idx is not None
                              else -1, ok)
                except P.ConnectionLost:
                    pass
                return
            conn.reply(rid, ok)
        except Exception as e:  # noqa: BLE001
            if prefetch:
                try:
                    conn.send(P.PREFETCH_RESULT, oid.binary(),
                              self.node_idx if self.node_idx is not None
                              else -1, False)
                except P.ConnectionLost:
                    pass
            elif rid > 0:
                try:
                    conn.reply_error(rid, e)
                except P.ConnectionLost:
                    pass

    def _report_evictions_async(self, oids):
        """store.on_evict hook: report off-thread so the allocating thread
        never blocks on a head socket write."""
        from .object_transfer import send_eviction_report_async

        if self.node_idx is None or self._shutdown.is_set():
            return
        send_eviction_report_async(self.head, self.node_idx, oids)

    def _on_head_reattach(self, conn):
        """Reconnector-thread hook: the head channel came back (possibly
        to a RESTARTED head with empty tables) — re-register carrying
        our prior node id, the live worker set, and a holder report of
        every sealed object in this host's arena, so the head rebuilds
        its node table and object directory from holder truth
        (reference: raylet re-registration within
        gcs_rpc_server_reconnect_timeout_s)."""
        if self._shutdown.is_set():
            return
        with self._lock:
            prior = self.node_idx if self.node_idx is not None else -1
            wids = [wid for wid, p in self.workers.items()
                    if p.poll() is None]
        # full report: the native table holds at most 65536 entries, so
        # this cap is exhaustive; a report that FILLS it still warns —
        # a silent truncation would read as "directory rebuilt" while
        # pre-crash objects quietly went missing
        listed = self.store.list_objects(max_objects=65536)
        if len(listed) >= 65536:
            print("[ray_tpu] holder report hit the 65536-entry cap; "
                  "directory rebuild may be incomplete", flush=True)
        holders = [(oid.binary(), size) for oid, size in listed]
        reply = conn.call(P.REGISTER_NODE, self._node_resources,
                          self.store_name, self.node_ip, self.session_dir,
                          self.transfer_server.addr, prior, wids, holders,
                          timeout=30)
        with self._lock:
            self.node_idx = reply[0]
        self.session_name = reply[1]

    # ------------------------------------------------------------- workers

    def _spawn_worker(self, worker_id: str):
        env = dict(os.environ)
        import ray_tpu

        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        entries = [p for p in sys.path if p] + [pkg_parent]
        pp = env.get("PYTHONPATH", "")
        have = set(pp.split(os.pathsep)) if pp else set()
        add = [p for p in entries if p not in have]
        if add:
            env["PYTHONPATH"] = os.pathsep.join(add + ([pp] if pp else []))
        env.update({
            "RAY_TPU_WORKER_ID": worker_id,
            "RAY_TPU_HEAD_ADDR": self.head_addr,
            "RAY_TPU_NODE_IDX": str(self.node_idx),
            "RAY_TPU_SESSION_DIR": self.session_dir,
            "RAY_TPU_NODE_IP": self.node_ip,
            "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
        })
        if env["JAX_PLATFORMS"] == "cpu":
            # see head._spawn_worker: the axon sitecustomize must not load
            # in CPU-only workers
            env.pop("PALLAS_AXON_POOL_IPS", None)
        log_dir = os.path.join(self.session_dir, "logs")
        out = open(os.path.join(log_dir, f"worker-{worker_id[:8]}.out"),
                   "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker_main"],
            env=env, stdout=out, stderr=subprocess.STDOUT,
            start_new_session=True)
        with self._lock:
            self.workers[worker_id] = proc

    def _kill_worker(self, worker_id: str):
        with self._lock:
            proc = self.workers.pop(worker_id, None)
        if proc is not None and proc.poll() is None:
            try:
                proc.kill()
            except OSError:
                pass

    def _reap_workers(self):
        """Emit a cluster event for every worker that dies WITHOUT the
        head asking (head-requested kills leave self.workers first, in
        _kill_worker). Exit by SIGKILL under host memory pressure is
        classified as an OOM kill — the kernel's oom-killer leaves no
        other trace than the signal. Pressure is judged by the RECENT
        PEAK of usage, not the instant of reaping: the kill itself frees
        the victim's memory, so by the time the poll sees the corpse the
        live reading is back below threshold."""
        import signal as _sig
        from collections import deque as _deque

        from .events import make_cluster_event
        from .memory_monitor import system_memory_usage_fraction

        oom_threshold = get_config().memory_usage_threshold
        recent_usage: "_deque" = _deque(maxlen=20)  # ~10s window
        while not self._shutdown.wait(0.5):
            recent_usage.append(system_memory_usage_fraction())
            with self._lock:
                dead = [(wid, p.returncode) for wid, p in
                        self.workers.items() if p.poll() is not None]
                for wid, _ in dead:
                    self.workers.pop(wid, None)
            for wid, rc in dead:
                if rc == 0:
                    continue  # clean exit (idle reap / graceful terminate)
                if rc == -_sig.SIGKILL and \
                        max(recent_usage, default=0.0) >= oom_threshold:
                    etype, msg = "worker_oom_kill", (
                        f"worker {wid[:8]} SIGKILLed under host memory "
                        "pressure (likely kernel oom-killer)")
                else:
                    etype, msg = "worker_crash", (
                        f"worker {wid[:8]} exited unexpectedly "
                        f"(code {rc})")
                ev = make_cluster_event(
                    "ERROR", "node_agent", etype, msg,
                    node_idx=self.node_idx if self.node_idx is not None
                    else -1,
                    entity_id=wid, extra={"exit_code": rc})
                try:
                    self.head.send(P.CLUSTER_EVENT, [ev], 0)
                except P.ConnectionLost:
                    pass

    # ------------------------------------------------------------ lifecycle

    def run_forever(self):
        try:
            while not self._shutdown.wait(0.5):
                pass
        finally:
            self.shutdown()

    def shutdown(self):
        self._shutdown.set()
        if getattr(self, "log_monitor", None) is not None:
            self.log_monitor.stop()
        if getattr(self, "telemetry", None) is not None:
            self.telemetry.stop()
        with self._lock:
            procs = list(self.workers.values())
            self.workers.clear()
        for p in procs:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
        try:
            self.head.close()
        except Exception:
            pass
        try:
            self.transfer_server.close()
            self.puller.close()
        except Exception:
            pass
        self.io.stop()
        try:
            self.store.close()
        except Exception:
            pass
        # belt-and-braces arena unlink (r19, ROADMAP 5c): close()
        # destroys the arena for creators, but if it raised (live
        # zero-copy borrows, a wedged native lock) the /dev/shm file
        # would outlive this process and pin its full capacity —
        # unlinking an already-destroyed name is a harmless ENOENT
        try:
            os.unlink(f"/dev/shm/{self.store_name}")
        except OSError:
            pass


def main(argv=None):
    ap = argparse.ArgumentParser(description="ray_tpu node agent")
    ap.add_argument("--address", required=True,
                    help="head address, tcp:HOST:PORT")
    ap.add_argument("--num-cpus", type=int, default=None)
    ap.add_argument("--num-tpus", type=int, default=None)
    ap.add_argument("--object-store-memory", type=int, default=None)
    ap.add_argument("--label", action="append", default=[],
                    metavar="KEY=VALUE",
                    help="node label (repeatable; e.g. the autoscaler "
                         "tags its launches to reclaim them later)")
    args = ap.parse_args(argv)
    labels = dict(kv.split("=", 1) for kv in args.label)
    agent = NodeAgent(args.address, num_cpus=args.num_cpus,
                      num_tpus=args.num_tpus,
                      object_store_memory=args.object_store_memory,
                      labels=labels or None)
    print(f"node agent joined as node {agent.node_idx} "
          f"(store {agent.store_name})", flush=True)
    # Arena hygiene (r19, ROADMAP 5c): every exit path must unlink the
    # /dev/shm arena. SIGTERM/SIGINT flow through run_forever's finally
    # -> shutdown() -> store destroy; atexit catches a run_forever that
    # unwound via an exception without reaching shutdown(). Only
    # SIGKILL leaks, and Cluster's handle.terminate sweep +
    # doctor_warnings' orphan scan cover that.
    import atexit

    def _unlink_arena():
        try:
            os.unlink(f"/dev/shm/{agent.store_name}")
        except OSError:
            pass

    atexit.register(_unlink_arena)
    signal.signal(signal.SIGTERM, lambda *a: agent._shutdown.set())
    signal.signal(signal.SIGINT, lambda *a: agent._shutdown.set())
    agent.run_forever()


if __name__ == "__main__":
    main()
