"""Core runtime: ids, resources, scheduling, object store, tasks, actors."""
