"""Generic ref-gated task-graph executor (r17).

Extracted from ``train/pipeline.py``'s r15 ``run_batch`` walk so the
same execution discipline serves BOTH pipeline schedules and the data
layer's shuffle DAGs (ROADMAP item 5: "the refactor that earns a
generic task-graph-with-by-ref-edges executor"). The model:

- **nodes submit, the object plane executes.** A node's ``fn`` fires a
  remote call and returns its ``ObjectRef`` (or list of refs for
  ``num_returns > 1``); a node is *submittable* the moment every
  dependency has been SUBMITTED — not completed — because the ref IS
  the edge: the consuming task's arg fetch waits on the object plane,
  not on the driver. The driver only orders submissions.
- **lanes = intra-actor program order.** Nodes sharing a ``lane``
  submit in add order (per-actor task seqno order is the stage's local
  program in the pipeline; a shuffle keeps its splits in upstream
  order the same way). The walk round-robins lanes, draining each as
  far as dep gating allows — exactly r15's ``_run_wave`` loop.
- **eager handle drop.** Every produced ref is dropped the moment its
  LAST registered consumer has been submitted (the consumer's task-arg
  refcount keeps the object alive until that task completes, then the
  owner free reclaims the store copy promptly). Multi-return nodes
  free per PORT: ``deps=[(key, j)]`` consumes only return ``j``, so a
  shuffle merge releases its column of split parts without waiting for
  the other columns' consumers. ``keep=True`` exempts terminal outputs.

Static graphs call ``run()`` (wedge-checked, returns kept values);
dynamic graphs — a shuffle discovering upstream blocks as they arrive —
interleave ``add()`` with ``pump()`` and finish with ``run()``.
"""

from __future__ import annotations

import itertools
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, Hashable, Optional, \
    Sequence, Tuple, Union

class Port:
    """Dep spec consuming a single return of a multi-return node:
    ``Port(key, j)`` resolves to ``value_of(key)[j]`` and is
    ref-counted (and eagerly freed) per PORT, not per node — explicit
    so tuple-shaped node keys stay unambiguous."""

    __slots__ = ("key", "index")

    def __init__(self, key: Hashable, index: int):
        self.key = key
        self.index = index


DepSpec = Union[Hashable, Port]


class TaskNode:
    """One submission: ``fn(*dep_values)`` fires the remote call and
    returns the node's value (an ``ObjectRef``, a list of refs for
    multi-return tasks, or any placeholder). ``deps`` name upstream
    node keys — or ``Port(key, j)`` to consume a single return of a
    multi-return node. ``keep=True`` marks a terminal output whose
    handle survives the walk (everything else is dropped eagerly)."""

    __slots__ = ("key", "fn", "deps", "lane", "keep")

    def __init__(self, key: Hashable, fn: Callable[..., Any],
                 deps: Sequence[DepSpec] = (),
                 lane: Optional[Hashable] = None, keep: bool = False):
        self.key = key
        self.fn = fn
        self.deps = tuple(deps)
        self.lane = lane
        self.keep = keep


def _ports(dep: DepSpec) -> Tuple[Hashable, Optional[int]]:
    if isinstance(dep, Port):
        return dep.key, dep.index
    return dep, None


class TaskGraphExecutor:
    """Submission-order walk with by-ref edges and eager handle drop.

    Not thread-safe: one driver thread builds and pumps the graph (the
    pipeline's wave loop; the shuffle's streaming admission loop)."""

    def __init__(self):
        self._lanes: "OrderedDict[Hashable, deque]" = OrderedDict()
        self._vals: Dict[Hashable, Any] = {}
        self._keep: Dict[Hashable, Any] = {}
        self._keys: set = set()  # every key ever added (dup guard)
        self._submitted: set = set()
        self._pending = 0
        # (key, port|None) -> remaining registered consumers; freeing
        # fires on the decrement to zero, so a port registered before
        # its consumer exists (incremental graphs) never frees early
        self._consumers: Dict[Tuple[Hashable, Optional[int]], int] = {}
        # key -> count of PORT slots already freed (None'd): the whole
        # entry drops only once every slot is — a port whose consumer
        # is added LATER (incremental graphs fold lazily) must find its
        # ref still held, however many sibling ports released first
        self._freed_ports: Dict[Hashable, int] = {}
        self._anon = itertools.count()

    # ------------------------------------------------------- building

    def add(self, node: TaskNode) -> None:
        if node.key in self._keys:
            raise ValueError(f"duplicate task-graph key {node.key!r}")
        self._keys.add(node.key)
        for dep in node.deps:
            slot = _ports(dep)
            self._consumers[slot] = self._consumers.get(slot, 0) + 1
        lane = node.lane if node.lane is not None \
            else ("_anon", next(self._anon))
        self._lanes.setdefault(lane, deque()).append(node)
        self._pending += 1

    def add_value(self, key: Hashable, value: Any,
                  keep: bool = False) -> None:
        """Register an externally produced value (e.g. an upstream
        block ref) as an already-submitted node, subject to the same
        eager drop when its consumers submit."""
        if key in self._keys:
            raise ValueError(f"duplicate task-graph key {key!r}")
        self._keys.add(key)
        self._submitted.add(key)
        self._vals[key] = value
        if keep:
            self._keep[key] = value

    # ------------------------------------------------------- querying

    def pending(self) -> int:
        return self._pending

    def kept(self) -> Dict[Hashable, Any]:
        return dict(self._keep)

    def value(self, key: Hashable) -> Any:
        """Current stored value of a submitted node (ports already
        released by consumers read as None slots); None if unknown or
        fully dropped. For completion probes — holding a peeked ref
        delays its eager free for as long as the caller keeps it."""
        return self._vals.get(key)

    # ------------------------------------------------------- the walk

    def _submittable(self, node: TaskNode) -> bool:
        return all(_ports(d)[0] in self._submitted for d in node.deps)

    def _resolve(self, dep: DepSpec) -> Any:
        key, port = _ports(dep)
        val = self._vals.get(key)
        if port is None:
            return val
        return None if val is None else val[port]

    def _release(self, dep: DepSpec) -> None:
        key, port = _ports(dep)
        slot = (key, port)
        n = self._consumers.get(slot, 0) - 1
        if n > 0:
            self._consumers[slot] = n
            return
        self._consumers.pop(slot, None)
        if key in self._keep:
            return
        if port is None:
            self._vals.pop(key, None)
            return
        val = self._vals.get(key)
        if not (isinstance(val, list) and 0 <= port < len(val)):
            return
        val[port] = None  # this column's handle drops now
        freed = self._freed_ports.get(key, 0) + 1
        if freed >= len(val) and (key, None) not in self._consumers:
            self._freed_ports.pop(key, None)
            self._vals.pop(key, None)
        else:
            self._freed_ports[key] = freed

    def _submit(self, node: TaskNode) -> None:
        args = [self._resolve(d) for d in node.deps]
        value = node.fn(*args)
        del args
        self._submitted.add(node.key)
        self._pending -= 1
        res = list(value) if isinstance(value, (list, tuple)) else value
        self._vals[node.key] = res
        if node.keep:
            self._keep[node.key] = res
        # eager drop of the deps' handles — AFTER fn ran, so the
        # consumer task's arg refcount already pins the objects
        for dep in node.deps:
            self._release(dep)

    def pump(self) -> int:
        """Submit everything currently submittable (lane-ordered).
        Returns the number of submissions; 0 means the walk is blocked
        on nodes not yet added (dynamic graphs) or done."""
        total = 0
        while True:
            progressed = False
            for lane in list(self._lanes):
                q = self._lanes[lane]
                while q and self._submittable(q[0]):
                    self._submit(q.popleft())
                    progressed = True
                    total += 1
                if not q:
                    del self._lanes[lane]
            if not progressed:
                return total

    def run(self) -> Dict[Hashable, Any]:
        """Pump to completion; raises if the remaining graph cannot
        make progress (a dependency cycle or a dep never added — the
        r15 'pipeline submission wedged' guard, generalized). Returns
        the kept values and drops every internal handle."""
        self.pump()
        if self._pending:
            stuck = [n.key for q in self._lanes.values() for n in q]
            raise RuntimeError(
                f"task graph submission wedged; {self._pending} nodes "
                f"blocked (first few: {stuck[:5]})")
        self._vals.clear()
        self._consumers.clear()
        self._freed_ports.clear()
        kept, self._keep = self._keep, {}
        return kept
