"""Flight recorder: bounded in-memory time series over the head's
merged metric table (r19).

The head calls :meth:`FlightRecorder.sample` from its housekeeping
loop every ``timeseries_sample_s`` seconds, passing the same merged
metric rows that back ``metrics_summary()``. Each metric folds into
one or more scalar series:

- **counters** -> a per-second *rate* series (delta between
  consecutive cumulative samples / elapsed; negative deltas — a
  process restart resetting its counter — clamp to zero rather than
  emitting a large negative spike),
- **gauges** -> the sampled value as-is,
- **histograms** -> three point-estimate series (``<name>.p50`` /
  ``.p95`` / ``.p99``) via the standard linear-interpolation bucket
  estimator.

Memory is bounded per series by construction, not by policy: a *fine*
ring holds the most recent ``window_s / sample_s`` points at full
resolution, and points that age out are folded 8:1 (mean of ts, mean
of value) into a *coarse* ring of the same capacity — so the recorder
covers ~9x the configured window end-to-end, the most recent window at
sample resolution and the older tail at 1/8 resolution, in O(2 *
window_s / sample_s) floats per series. The reference system ships
this job out-of-process (dashboard metrics agent -> Prometheus ->
Grafana); a single-binary cluster wants the recent history answerable
by the head itself (`state.metrics_history()` / ``/api/timeseries``)
with no external TSDB.
"""
from __future__ import annotations

import fnmatch
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence

# Fine->coarse fold factor: 8 fine points average into one coarse
# point, so the coarse ring (same capacity as fine) spans 8 windows.
DOWNSAMPLE = 8
# Safety valve on series cardinality, far above anything a sane
# cluster produces; beyond it new series are counted, not stored.
MAX_SERIES = 4096


def hist_quantile(bounds, value, q: float) -> float:
    """Estimate the q-quantile of a [bucket counts..., +inf, sum, n]
    histogram row by linear interpolation inside the holding bucket
    (the Prometheus histogram_quantile estimator); the +Inf bucket
    clamps to the last finite bound."""
    n = value[-1]
    if n <= 0:
        return 0.0
    target = q * n
    acc, lo = 0.0, 0.0
    for i, b in enumerate(bounds):
        c = value[i]
        if c > 0 and acc + c >= target:
            return lo + (b - lo) * max(0.0, min(1.0, (target - acc) / c))
        acc += c
        lo = b
    return float(bounds[-1])


def series_key(name: str, tags: Optional[dict]) -> str:
    """Stable series identity: ``name`` or ``name{k=v,...}`` with
    sorted tag keys (mirrors the Prometheus exposition identity)."""
    if not tags:
        return name
    inner = ",".join(f"{k}={tags[k]}" for k in sorted(tags))
    return f"{name}{{{inner}}}"


class _Series:
    __slots__ = ("kind", "fine", "coarse", "_pending", "last_raw",
                 "last_ts")

    def __init__(self, kind: str, fine_cap: int):
        self.kind = kind                    # "rate" | "gauge" | "quantile"
        self.fine: deque = deque()          # (ts, value), manual eviction
        self.coarse: deque = deque(maxlen=fine_cap)
        self._pending: List[tuple] = []     # fine evictions awaiting fold
        self.last_raw: Optional[float] = None   # counters: last cumulative
        self.last_ts: Optional[float] = None

    def push(self, ts: float, value: float, fine_cap: int):
        self.fine.append((ts, value))
        while len(self.fine) > fine_cap:
            self._pending.append(self.fine.popleft())
            if len(self._pending) >= DOWNSAMPLE:
                n = len(self._pending)
                self.coarse.append((
                    sum(p[0] for p in self._pending) / n,
                    sum(p[1] for p in self._pending) / n,
                ))
                self._pending.clear()


class FlightRecorder:
    """Bounded ring-buffer recorder over metric-table snapshots.

    Thread-safe: ``sample()`` runs on the head housekeeping thread
    while ``history()`` is served from IO threads.
    """

    def __init__(self, sample_s: float = 1.0, window_s: float = 300.0):
        self.sample_s = float(sample_s)
        self.window_s = float(window_s)
        self.fine_cap = max(2, int(round(window_s / max(sample_s, 1e-6))))
        self._series: Dict[str, _Series] = {}
        self._lock = threading.Lock()
        self.samples_taken = 0
        self.series_dropped = 0  # new series refused past MAX_SERIES

    # -- ingestion ----------------------------------------------------

    def _get(self, key: str, kind: str) -> Optional[_Series]:
        s = self._series.get(key)
        if s is None:
            if len(self._series) >= MAX_SERIES:
                self.series_dropped += 1
                return None
            s = self._series[key] = _Series(kind, self.fine_cap)
        return s

    def sample(self, rows: Sequence[dict], now: float):
        """Fold one merged-metric-table snapshot into the rings.

        ``rows`` use the head's merged schema: ``{name, kind, tags,
        boundaries, value}`` where histogram values are the
        ``[buckets..., +inf, sum, n]`` list.
        """
        with self._lock:
            self.samples_taken += 1
            for row in rows:
                kind = row.get("kind")
                name = row.get("name")
                tags = row.get("tags") or {}
                value = row.get("value")
                if kind == "counter":
                    s = self._get(series_key(name, tags), "rate")
                    if s is None:
                        continue
                    v = float(value)
                    if s.last_raw is not None and s.last_ts is not None:
                        dt = now - s.last_ts
                        if dt > 0:
                            rate = max(0.0, (v - s.last_raw) / dt)
                            s.push(now, rate, self.fine_cap)
                    s.last_raw, s.last_ts = v, now
                elif kind == "gauge":
                    s = self._get(series_key(name, tags), "gauge")
                    if s is not None:
                        s.push(now, float(value), self.fine_cap)
                elif kind == "histogram":
                    bounds = row.get("boundaries")
                    if not bounds or not value:
                        continue
                    for q, suffix in ((0.50, "p50"), (0.95, "p95"),
                                      (0.99, "p99")):
                        key = series_key(f"{name}.{suffix}", tags)
                        s = self._get(key, "quantile")
                        if s is not None:
                            s.push(now, hist_quantile(bounds, value, q),
                                   self.fine_cap)

    # -- queries ------------------------------------------------------

    @staticmethod
    def _match(patterns: Optional[Sequence[str]], key: str) -> bool:
        if not patterns:
            return True
        base = key.split("{", 1)[0]
        for p in patterns:
            if "*" in p or "?" in p or "[" in p:
                if fnmatch.fnmatchcase(base, p) or \
                        fnmatch.fnmatchcase(key, p):
                    return True
            elif base == p or key == p or base.startswith(p + ".") \
                    or key.startswith(p):
                return True
        return False

    def history(self, names: Optional[Sequence[str]] = None,
                window_s: Optional[float] = None) -> dict:
        """Return matching series, fine points restricted to the most
        recent ``window_s`` seconds (default: the full fine window).
        ``names`` entries may be exact series keys, metric-name
        prefixes, or fnmatch globs (``collective.*``)."""
        with self._lock:
            out: Dict[str, dict] = {}
            horizon = None
            if window_s is not None:
                newest = max((s.fine[-1][0] for s in
                              self._series.values() if s.fine),
                             default=None)
                if newest is not None:
                    horizon = newest - float(window_s)
            for key, s in self._series.items():
                if not self._match(names, key):
                    continue
                pts = list(s.fine)
                if horizon is not None:
                    pts = [p for p in pts if p[0] >= horizon]
                out[key] = {
                    "kind": s.kind,
                    "points": [[t, v] for t, v in pts],
                    "coarse": [[t, v] for t, v in s.coarse],
                }
            return {
                "sample_s": self.sample_s,
                "window_s": self.window_s,
                "samples_taken": self.samples_taken,
                "series_dropped": self.series_dropped,
                "series": out,
            }

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)
