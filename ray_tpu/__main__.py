"""Entry point: ``python -m ray_tpu <command>`` (the reference's `ray` CLI)."""

import sys

from ray_tpu.scripts import main

sys.exit(main())
