// Native cluster-scheduling core: the head's per-lease placement decision.
//
// Ref analog: src/ray/raylet/scheduling/cluster_resource_scheduler.h:44
// (GetBestSchedulableNode) + policy/hybrid_scheduling_policy.h:50 and the
// fixed-point resource vectors of cluster_resource_data.h / fixed_point.h.
// The Python ClusterResourceScheduler keeps policy-rich bundle placement;
// this core answers the hot single-task question — feasibility scan +
// utilization ranking over the whole node table — in C so a 10k-node
// table costs tens of microseconds, not milliseconds, per lease.
//
// Resource kinds are int64 ids interned by the Python side. Ids 0..4
// (CPU, GPU, TPU, memory, object_store_memory) are "predefined" and live
// in flat per-node arrays (the scan is cache-linear); of those, ids 0..3
// drive the hybrid policy's max-utilization, mirroring
// NodeResources.utilization(). Custom kinds ride a small sorted vector.
// Quantities are 1/10000 fixed-point int64, mirroring resources.py.
//
// Build: ray_tpu/native/build.py -> libsched_core.so

#include <algorithm>
#include <cstdint>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

constexpr int kPredef = 5;          // flat-array kinds (ids 0..4)
constexpr int kCriticalKinds = 4;   // CPU, GPU, TPU, memory drive util

struct Node {
  int64_t idx = -1;  // -1 marks a free slot
  int64_t avail[kPredef] = {0};
  int64_t total[kPredef] = {0};
  std::vector<std::pair<int64_t, int64_t>> custom_avail;  // sorted by kind
  std::vector<std::pair<int64_t, int64_t>> custom_total;
  bool draining = false;
};

struct Sched {
  std::vector<Node> slots;                     // contiguous scan target
  std::unordered_map<int64_t, size_t> by_idx;  // idx -> slot
  std::vector<size_t> free_slots;
};

struct Demand {
  int64_t predef[kPredef];
  const int64_t* kinds;
  const int64_t* amounts;
  int n;
  bool has_custom;
};

Demand parse_demand(int n, const int64_t* kinds, const int64_t* amounts) {
  Demand d{{0, 0, 0, 0, 0}, kinds, amounts, n, false};
  for (int i = 0; i < n; ++i) {
    if (kinds[i] >= 0 && kinds[i] < kPredef)
      d.predef[kinds[i]] = amounts[i];
    else if (amounts[i] > 0)
      d.has_custom = true;
  }
  return d;
}

int64_t custom_get(const std::vector<std::pair<int64_t, int64_t>>& v,
                   int64_t kind) {
  auto it = std::lower_bound(
      v.begin(), v.end(), kind,
      [](const std::pair<int64_t, int64_t>& p, int64_t k) {
        return p.first < k;
      });
  return (it != v.end() && it->first == kind) ? it->second : 0;
}

bool covers(const Node& node, const Demand& d, bool use_total) {
  const int64_t* have = use_total ? node.total : node.avail;
  for (int k = 0; k < kPredef; ++k)
    if (d.predef[k] > have[k]) return false;
  if (d.has_custom) {
    const auto& customs = use_total ? node.custom_total : node.custom_avail;
    for (int i = 0; i < d.n; ++i) {
      if (d.kinds[i] < kPredef || d.amounts[i] == 0) continue;
      if (custom_get(customs, d.kinds[i]) < d.amounts[i]) return false;
    }
  }
  return true;
}

double utilization(const Node& n) {
  double util = 0.0;
  for (int k = 0; k < kCriticalKinds; ++k) {
    if (n.total[k] == 0) continue;
    double u = 1.0 - static_cast<double>(n.avail[k]) /
                         static_cast<double>(n.total[k]);
    if (u > util) util = u;
  }
  return util;
}

uint64_t xorshift(uint64_t* s) {
  uint64_t x = *s ? *s : 0x9e3779b97f4a7c15ULL;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  *s = x;
  return x;
}

}  // namespace

extern "C" {

void* sched_create() { return new Sched(); }

void sched_destroy(void* h) { delete static_cast<Sched*>(h); }

// Replace (or insert) a node's full resource state.
void sched_set_node(void* h, int64_t idx, int n, const int64_t* kinds,
                    const int64_t* avail, const int64_t* total) {
  Sched* s = static_cast<Sched*>(h);
  size_t slot;
  auto it = s->by_idx.find(idx);
  if (it != s->by_idx.end()) {
    slot = it->second;
  } else if (!s->free_slots.empty()) {
    slot = s->free_slots.back();
    s->free_slots.pop_back();
    s->by_idx[idx] = slot;
  } else {
    slot = s->slots.size();
    s->slots.emplace_back();
    s->by_idx[idx] = slot;
  }
  Node& node = s->slots[slot];
  node = Node{};
  node.idx = idx;
  for (int i = 0; i < n; ++i) {
    if (kinds[i] >= 0 && kinds[i] < kPredef) {
      node.avail[kinds[i]] = avail[i];
      node.total[kinds[i]] = total[i];
    } else {
      node.custom_avail.emplace_back(kinds[i], avail[i]);
      node.custom_total.emplace_back(kinds[i], total[i]);
    }
  }
  std::sort(node.custom_avail.begin(), node.custom_avail.end());
  std::sort(node.custom_total.begin(), node.custom_total.end());
}

void sched_remove_node(void* h, int64_t idx) {
  Sched* s = static_cast<Sched*>(h);
  auto it = s->by_idx.find(idx);
  if (it == s->by_idx.end()) return;
  s->slots[it->second] = Node{};  // idx = -1: skipped by scans
  s->free_slots.push_back(it->second);
  s->by_idx.erase(it);
}

void sched_set_draining(void* h, int64_t idx, int draining) {
  Sched* s = static_cast<Sched*>(h);
  auto it = s->by_idx.find(idx);
  if (it != s->by_idx.end())
    s->slots[it->second].draining = draining != 0;
}

int64_t sched_node_count(void* h) {
  return static_cast<int64_t>(static_cast<Sched*>(h)->by_idx.size());
}

// strategy: 0 = hybrid (local preference below threshold, then top-k
// least-utilized at random), 1 = spread (least utilized, ties by idx).
// threshold/topk_frac are 1/10000 fixed point. rng_state is in/out so
// the caller owns determinism. Returns node idx or -1.
int64_t sched_best_node(void* h, int n, const int64_t* kinds,
                        const int64_t* demand, int strategy,
                        int64_t local_idx, int64_t threshold_fp,
                        int64_t topk_frac_fp, uint64_t* rng_state) {
  Sched* s = static_cast<Sched*>(h);
  Demand d = parse_demand(n, kinds, demand);
  struct Cand {
    double util;
    int64_t idx;
  };
  std::vector<Cand> feasible;
  feasible.reserve(s->by_idx.size());
  for (const Node& node : s->slots) {
    if (node.idx < 0 || node.draining) continue;
    if (!covers(node, d, /*use_total=*/false)) continue;
    feasible.push_back({utilization(node), node.idx});
  }
  if (feasible.empty()) return -1;

  if (strategy == 1) {  // spread: min (util, idx)
    const Cand* best = &feasible[0];
    for (const Cand& c : feasible)
      if (c.util < best->util || (c.util == best->util && c.idx < best->idx))
        best = &c;
    return best->idx;
  }

  // hybrid: local node wins while its utilization is below threshold
  double threshold = static_cast<double>(threshold_fp) / 10000.0;
  for (const Cand& c : feasible)
    if (c.idx == local_idx && c.util < threshold) return local_idx;

  std::sort(feasible.begin(), feasible.end(),
            [](const Cand& a, const Cand& b) {
              return a.util != b.util ? a.util < b.util : a.idx < b.idx;
            });
  size_t k = static_cast<size_t>(
      feasible.size() * (static_cast<double>(topk_frac_fp) / 10000.0));
  if (k < 1) k = 1;
  if (k > feasible.size()) k = feasible.size();
  return feasible[xorshift(rng_state) % k].idx;
}

// 1 if any non-draining node's TOTAL covers the demand (feasibility, not
// current availability) — mirrors is_feasible_anywhere.
int sched_feasible_anywhere(void* h, int n, const int64_t* kinds,
                            const int64_t* demand) {
  Sched* s = static_cast<Sched*>(h);
  Demand d = parse_demand(n, kinds, demand);
  for (const Node& node : s->slots) {
    if (node.idx < 0 || node.draining) continue;
    if (covers(node, d, /*use_total=*/true)) return 1;
  }
  return 0;
}

}  // extern "C"
