// Shared-memory object store ("plasma-lite") for the TPU-native runtime.
//
// Role analog: the reference's per-node plasma store
// (src/ray/object_manager/plasma/store.cc — PlasmaStore, ObjectLifecycleManager,
// EvictionPolicy) which serves clients over a unix socket with flatbuffers.
// TPU-first redesign: instead of a store *server* process brokering every
// create/get over a socket, the arena and its metadata live directly in one
// shared-memory segment that every worker process on the host maps. All
// operations are lock-protected in-place updates — create/get/seal cost a
// futex acquisition plus table lookup, no IPC round trip. Data transfer is
// zero-copy: Python maps the same segment and reads object payloads as
// buffers. This matches TPU hosts' usage (few large tensor/checkpoint blobs,
// many small control objects) better than a socket protocol.
//
// Layout of the segment:
//   [Header | ObjectEntry table | data arena]
// Allocation: boundary-tag first-fit free list with coalescing, protected by a
// process-shared robust mutex in the header.
//
// Exposed as a plain C ABI consumed by ctypes (ray_tpu/core/object_store.py).

#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52544F5253484D31ULL;  // "RTORSHM1"
constexpr uint32_t kIdSize = 20;                    // ObjectID is 20 bytes
constexpr uint32_t kTableSize = 1 << 16;            // open-addressed entries
constexpr uint64_t kAlign = 64;

enum ObjState : uint32_t {
  kEmpty = 0,
  kCreated = 1,
  kSealed = 2,
  kTombstone = 3,
};

struct ObjectEntry {
  uint8_t id[kIdSize];
  uint32_t state;
  uint64_t offset;    // data offset from segment base
  uint64_t data_size;
  uint64_t meta_size; // metadata bytes appended after data
  int64_t ref_count;  // pinned readers (eviction guard)
  uint64_t create_ns; // creation stamp for LRU-ish eviction
};

// Free block header embedded in the arena. Allocated blocks carry the same
// header so free() can find the size; boundary tag at the end enables
// backward coalescing.
struct BlockHeader {
  uint64_t size;      // total block size incl. headers
  uint64_t prev_size; // size of physically-previous block (0 if first)
  uint32_t free_flag; // 1 if free
  uint32_t pad;
  uint64_t next_free; // offset of next free block (0 = none); valid if free
  uint64_t prev_free;
};

struct Header {
  uint64_t magic;
  uint64_t segment_size;
  uint64_t arena_offset;
  uint64_t arena_size;
  uint64_t bytes_in_use;
  uint64_t num_objects;
  uint64_t free_head;  // offset of first free block (0 = none)
  uint64_t clock;      // monotone counter for create stamps
  uint64_t highwater;  // max bytes_in_use ever observed (arena pressure)
  pthread_mutex_t mutex;
  ObjectEntry table[kTableSize];
};

struct Store {
  int fd;
  uint8_t* base;
  Header* hdr;
};

inline uint64_t align_up(uint64_t v) { return (v + kAlign - 1) & ~(kAlign - 1); }

inline uint64_t hash_id(const uint8_t* id) {
  // FNV-1a over the 20-byte id.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdSize; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

class Guard {
 public:
  explicit Guard(Header* h) : h_(h) {
    int rc = pthread_mutex_lock(&h_->mutex);
    if (rc == EOWNERDEAD) {
      // A worker died holding the lock; state is still consistent enough for
      // our in-place updates (each op is short); mark recovered.
      pthread_mutex_consistent(&h_->mutex);
    }
  }
  ~Guard() { pthread_mutex_unlock(&h_->mutex); }

 private:
  Header* h_;
};

ObjectEntry* find_entry(Header* h, const uint8_t* id) {
  uint64_t idx = hash_id(id) & (kTableSize - 1);
  for (uint32_t probe = 0; probe < kTableSize; probe++) {
    ObjectEntry* e = &h->table[(idx + probe) & (kTableSize - 1)];
    if (e->state == kEmpty) return nullptr;
    if (e->state != kTombstone && memcmp(e->id, id, kIdSize) == 0) return e;
  }
  return nullptr;
}

ObjectEntry* find_slot(Header* h, const uint8_t* id) {
  uint64_t idx = hash_id(id) & (kTableSize - 1);
  ObjectEntry* first_tomb = nullptr;
  for (uint32_t probe = 0; probe < kTableSize; probe++) {
    ObjectEntry* e = &h->table[(idx + probe) & (kTableSize - 1)];
    if (e->state == kEmpty) return first_tomb ? first_tomb : e;
    if (e->state == kTombstone) {
      if (!first_tomb) first_tomb = e;
    } else if (memcmp(e->id, id, kIdSize) == 0) {
      return e;  // existing
    }
  }
  return first_tomb;
}

BlockHeader* block_at(Store* s, uint64_t off) {
  return reinterpret_cast<BlockHeader*>(s->base + off);
}

void freelist_remove(Header* h, Store* s, BlockHeader* b, uint64_t off) {
  if (b->prev_free)
    block_at(s, b->prev_free)->next_free = b->next_free;
  else
    h->free_head = b->next_free;
  if (b->next_free) block_at(s, b->next_free)->prev_free = b->prev_free;
}

void freelist_push(Header* h, Store* s, BlockHeader* b, uint64_t off) {
  b->free_flag = 1;
  b->next_free = h->free_head;
  b->prev_free = 0;
  if (h->free_head) block_at(s, h->free_head)->prev_free = off;
  h->free_head = off;
}

// Allocate `need` bytes of payload; returns data offset or 0 on OOM.
uint64_t arena_alloc(Store* s, uint64_t need) {
  Header* h = s->hdr;
  uint64_t total = align_up(need + sizeof(BlockHeader));
  uint64_t off = h->free_head;
  while (off) {
    BlockHeader* b = block_at(s, off);
    if (b->size >= total) {
      freelist_remove(h, s, b, off);
      uint64_t remainder = b->size - total;
      if (remainder >= sizeof(BlockHeader) + kAlign) {
        // Split: tail becomes a new free block.
        b->size = total;
        uint64_t tail_off = off + total;
        BlockHeader* tail = block_at(s, tail_off);
        tail->size = remainder;
        tail->prev_size = total;
        freelist_push(h, s, tail, tail_off);
        // Fix prev_size of the block after the tail.
        uint64_t after = tail_off + remainder;
        if (after < h->arena_offset + h->arena_size)
          block_at(s, after)->prev_size = remainder;
      }
      b->free_flag = 0;
      h->bytes_in_use += b->size;
      if (h->bytes_in_use > h->highwater) h->highwater = h->bytes_in_use;
      return off + sizeof(BlockHeader);
    }
    off = b->next_free;
  }
  return 0;
}

void arena_free(Store* s, uint64_t data_off) {
  Header* h = s->hdr;
  uint64_t off = data_off - sizeof(BlockHeader);
  BlockHeader* b = block_at(s, off);
  h->bytes_in_use -= b->size;
  // Coalesce forward.
  uint64_t next_off = off + b->size;
  uint64_t arena_end = h->arena_offset + h->arena_size;
  if (next_off < arena_end) {
    BlockHeader* nb = block_at(s, next_off);
    if (nb->free_flag) {
      freelist_remove(h, s, nb, next_off);
      b->size += nb->size;
    }
  }
  // Coalesce backward.
  if (b->prev_size) {
    uint64_t prev_off = off - b->prev_size;
    BlockHeader* pb = block_at(s, prev_off);
    if (pb->free_flag) {
      freelist_remove(h, s, pb, prev_off);
      pb->size += b->size;
      b = pb;
      off = prev_off;
    }
  }
  // Fix prev_size of following block.
  uint64_t after = off + b->size;
  if (after < arena_end) block_at(s, after)->prev_size = b->size;
  freelist_push(h, s, b, off);
}

}  // namespace

extern "C" {

// Create a new store segment (unlinks any existing one of the same name).
// Returns opaque handle or null.
void* shm_store_create(const char* name, uint64_t segment_size) {
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (segment_size < sizeof(Header) + (1 << 20)) segment_size = sizeof(Header) + (1 << 20);
  if (ftruncate(fd, static_cast<off_t>(segment_size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, segment_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  // NOTE on pre-population: tmpfs allocates a page on its first WRITE
  // fault, so an un-touched arena costs ~25k faults + kernel zeroing per
  // 100 MiB on the very first object writes. An eager synchronous
  // MADV_POPULATE_WRITE here was measured to degrade pathologically as
  // populated segments accumulate (0.2s -> 10s per 512 MiB arena on the
  // deployment kernel), serializing node registration; the Python side
  // now populates in bounded chunks from a background thread instead.
  Store* s = new Store{fd, static_cast<uint8_t*>(base), static_cast<Header*>(base)};
  Header* h = s->hdr;
  memset(h, 0, sizeof(Header));
  h->segment_size = segment_size;
  h->arena_offset = align_up(sizeof(Header));
  h->arena_size = segment_size - h->arena_offset;
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&h->mutex, &attr);
  // One giant free block spanning the arena.
  BlockHeader* b = block_at(s, h->arena_offset);
  b->size = h->arena_size;
  b->prev_size = 0;
  freelist_push(h, s, b, h->arena_offset);
  h->magic = kMagic;
  return s;
}

// Attach to an existing segment created by shm_store_create.
void* shm_store_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Store* s = new Store{fd, static_cast<uint8_t*>(base), static_cast<Header*>(base)};
  if (s->hdr->magic != kMagic) {
    munmap(base, static_cast<size_t>(st.st_size));
    close(fd);
    delete s;
    return nullptr;
  }
  return s;
}

void shm_store_detach(void* handle) {
  Store* s = static_cast<Store*>(handle);
  munmap(s->base, s->hdr->segment_size);
  close(s->fd);
  delete s;
}

void shm_store_destroy(void* handle, const char* name) {
  shm_store_detach(handle);
  shm_unlink(name);
}

// Create an object. Returns data offset (>0), 0 on OOM, -1 if already exists.
int64_t shm_store_create_object(void* handle, const uint8_t* id, uint64_t data_size,
                                uint64_t meta_size) {
  Store* s = static_cast<Store*>(handle);
  Header* h = s->hdr;
  Guard g(h);
  ObjectEntry* existing = find_entry(h, id);
  if (existing) return -1;
  ObjectEntry* e = find_slot(h, id);
  if (!e) return 0;
  uint64_t off = arena_alloc(s, data_size + meta_size);
  if (!off) return 0;
  memcpy(e->id, id, kIdSize);
  e->state = kCreated;
  e->offset = off;
  e->data_size = data_size;
  e->meta_size = meta_size;
  e->ref_count = 0;
  e->create_ns = ++h->clock;
  h->num_objects++;
  return static_cast<int64_t>(off);
}

int shm_store_seal(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  ObjectEntry* e = find_entry(s->hdr, id);
  if (!e || e->state != kCreated) return -1;
  e->state = kSealed;
  return 0;
}

// Get a sealed object, pinning it. out = [offset, data_size, meta_size].
// Returns 0 on success, -1 not found, -2 not sealed yet.
int shm_store_get(void* handle, const uint8_t* id, uint64_t* out) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  ObjectEntry* e = find_entry(s->hdr, id);
  if (!e) return -1;
  if (e->state != kSealed) return -2;
  e->ref_count++;
  out[0] = e->offset;
  out[1] = e->data_size;
  out[2] = e->meta_size;
  return 0;
}

// Check existence/sealed without pinning.
int shm_store_contains(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  ObjectEntry* e = find_entry(s->hdr, id);
  if (!e) return 0;
  return e->state == kSealed ? 1 : 2;
}

int shm_store_release(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  ObjectEntry* e = find_entry(s->hdr, id);
  if (!e) return -1;
  if (e->ref_count > 0) e->ref_count--;
  return 0;
}

// Delete object (frees arena space). Fails with -2 if pinned.
int shm_store_delete(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  Header* h = s->hdr;
  Guard g(h);
  ObjectEntry* e = find_entry(h, id);
  if (!e) return -1;
  if (e->ref_count > 0) return -2;
  arena_free(s, e->offset);
  e->state = kTombstone;
  h->num_objects--;
  return 0;
}

// Evict up to `need` bytes of sealed, unpinned objects (oldest first).
// Writes evicted ids packed into out_ids (capacity max_ids), returns count.
int shm_store_evict(void* handle, uint64_t need, uint8_t* out_ids, int max_ids) {
  Store* s = static_cast<Store*>(handle);
  Header* h = s->hdr;
  Guard g(h);
  int count = 0;
  uint64_t freed = 0;
  while (freed < need && count < max_ids) {
    ObjectEntry* victim = nullptr;
    for (uint32_t i = 0; i < kTableSize; i++) {
      ObjectEntry* e = &h->table[i];
      if (e->state == kSealed && e->ref_count == 0 &&
          (!victim || e->create_ns < victim->create_ns))
        victim = e;
    }
    if (!victim) break;
    freed += victim->data_size + victim->meta_size;
    memcpy(out_ids + count * kIdSize, victim->id, kIdSize);
    count++;
    arena_free(s, victim->offset);
    victim->state = kTombstone;
    h->num_objects--;
  }
  return count;
}

// List sealed objects: packs up to max_ids ids (20 bytes each) into
// out_ids and their total sizes (data + metadata) into out_sizes;
// returns the count. The holder-report path: a node agent re-registering
// with a restarted head enumerates its arena so the head can rebuild the
// object directory from holder truth (the directory is deliberately not
// written to the head WAL).
int shm_store_list(void* handle, uint8_t* out_ids, uint64_t* out_sizes,
                   int max_ids) {
  Store* s = static_cast<Store*>(handle);
  Header* h = s->hdr;
  Guard g(h);
  int count = 0;
  for (uint32_t i = 0; i < kTableSize && count < max_ids; i++) {
    ObjectEntry* e = &h->table[i];
    if (e->state != kSealed) continue;
    memcpy(out_ids + count * kIdSize, e->id, kIdSize);
    out_sizes[count] = e->data_size + e->meta_size;
    count++;
  }
  return count;
}

// One-pass arena accounting snapshot under a single lock acquisition
// (the memory-observatory sampling path; cheap enough for a heartbeat
// cadence — one 64k-entry table scan, no allocation). Writes 10 values:
//   [capacity, bytes_in_use, highwater, num_objects,
//    sealed_count, sealed_bytes, unsealed_count, unsealed_bytes,
//    pinned_count, pinned_bytes]
// sealed/unsealed bytes are PAYLOAD bytes (data + metadata) so they
// compare exactly against the directory's per-object sizes; bytes_in_use
// additionally carries block headers + alignment slack.
void shm_store_memory_stats(void* handle, uint64_t* out) {
  Store* s = static_cast<Store*>(handle);
  Header* h = s->hdr;
  Guard g(h);
  uint64_t sealed_count = 0, sealed_bytes = 0, sealed_data_bytes = 0;
  uint64_t unsealed_count = 0, unsealed_bytes = 0;
  uint64_t pinned_count = 0, pinned_bytes = 0;
  for (uint32_t i = 0; i < kTableSize; i++) {
    ObjectEntry* e = &h->table[i];
    if (e->state != kSealed && e->state != kCreated) continue;
    uint64_t payload = e->data_size + e->meta_size;
    if (e->state == kSealed) {
      sealed_count++;
      sealed_bytes += payload;
      // data-only view: the wire size convention (directory entries,
      // stripe ranges, pull buffers) excludes the frame-size metadata
      sealed_data_bytes += e->data_size;
    } else {
      unsealed_count++;
      unsealed_bytes += payload;
    }
    if (e->ref_count > 0) {
      pinned_count++;
      pinned_bytes += payload;
    }
  }
  out[0] = h->arena_size;
  out[1] = h->bytes_in_use;
  out[2] = h->highwater;
  out[3] = h->num_objects;
  out[4] = sealed_count;
  out[5] = sealed_bytes;
  out[6] = unsealed_count;
  out[7] = unsealed_bytes;
  out[8] = pinned_count;
  out[9] = pinned_bytes;
  out[10] = sealed_data_bytes;
}

uint64_t shm_store_bytes_in_use(void* handle) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  return s->hdr->bytes_in_use;
}

uint64_t shm_store_capacity(void* handle) {
  Store* s = static_cast<Store*>(handle);
  return s->hdr->arena_size;
}

uint64_t shm_store_num_objects(void* handle) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  return s->hdr->num_objects;
}

// Segment base address in THIS process — offsets from shm_store_get /
// shm_store_create_object resolve against it (the C++ client's zero-copy
// views; Python uses its own mmap of the same segment instead).
void* shm_store_base_ptr(void* handle) {
  return static_cast<Store*>(handle)->base;
}

}  // extern "C"
