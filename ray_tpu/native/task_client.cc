// C++ task-submission frontend for the ray_tpu head.
//
// Ref analogs: cpp/include/ray/api.h + cpp/src/ray/runtime/task/
// task_submitter.h:26 (the reference's C++ public API submits tasks by
// function descriptor through the shared CoreWorker). Re-design for the
// framed-socket control plane: this client speaks the head's wire
// protocol directly — it EMITS the one fixed pickle shape the protocol
// needs (a (msg_type, request_id, bytes) tuple; protocol.py:XLANG_CALL)
// and receives replies as RAW frames of JSON, so no Python runtime and
// no pickle PARSER exist on the C++ side. Submission is by function
// descriptor ("module:qualname"), the cross-language pattern of
// python/ray/cross_language.py:15.
//
// Usage:
//   task_client <addr> <module:qualname> [json-args] [json-options]
//   task_client <addr> actor-create <module:Class> [json-args] [json-opts]
//   task_client <addr> actor-call <actor-name> <method> [json-args]
//   task_client <addr> actor-kill <actor-name>
// The actor subcommands are the C++ actor API (ref analog:
// cpp/src/ray/runtime/task/task_submitter.h:26 actor creation/submission
// paths): create prints the registered actor name, call prints the
// method result, kill tears the actor down. Exit 0 iff status == "ok".
//
// Build: g++ -O2 -o task_client task_client.cc   (native/build.py)

#include <arpa/inet.h>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <netdb.h>
#include <string>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint64_t kRawBit = 1ULL << 63;
constexpr int kXlangCall = 67;  // protocol.py XLANG_CALL

// ---- minimal pickle WRITER for the one frame shape we send -----------------
// (int, int, bytes) tuple, pickle protocol 3:
//   \x80\x03  PROTO 3
//   J <i32le> BININT            (msg_type)
//   J <i32le> BININT            (request_id)
//   C <u8> .. / B <u32le> ..    SHORT_BINBYTES / BINBYTES (payload)
//   \x87      TUPLE3
//   .         STOP
std::string PickleCall(int msg_type, int request_id,
                       const std::string& payload) {
  std::string out;
  out += "\x80\x03";
  auto put_int = [&out](int32_t v) {
    out += 'J';
    char b[4];
    memcpy(b, &v, 4);  // little-endian hosts (x86/arm)
    out.append(b, 4);
  };
  put_int(msg_type);
  put_int(request_id);
  if (payload.size() < 256) {
    out += 'C';
    out += static_cast<char>(payload.size());
  } else {
    out += 'B';
    uint32_t n = payload.size();
    char b[4];
    memcpy(b, &n, 4);
    out.append(b, 4);
  }
  out += payload;
  out += '\x87';
  out += '.';
  return out;
}

// ---- socket helpers --------------------------------------------------------

int DialTcp(const std::string& host, const std::string& port) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  if (getaddrinfo(host.c_str(), port.c_str(), &hints, &res) != 0) return -1;
  int fd = -1;
  for (addrinfo* p = res; p; p = p->ai_next) {
    fd = socket(p->ai_family, p->ai_socktype, p->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, p->ai_addr, p->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  return fd;
}

int DialUnix(const std::string& path) {
  int fd = socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  snprintf(addr.sun_path, sizeof(addr.sun_path), "%s", path.c_str());
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

bool WriteAll(int fd, const char* data, size_t n) {
  while (n) {
    ssize_t w = write(fd, data, n);
    if (w <= 0) return false;
    data += w;
    n -= w;
  }
  return true;
}

bool ReadAll(int fd, char* data, size_t n) {
  while (n) {
    ssize_t r = read(fd, data, n);
    if (r <= 0) return false;
    data += r;
    n -= r;
  }
  return true;
}

bool SendFrame(int fd, const std::string& payload) {
  uint64_t len = payload.size();
  char hdr[8];
  memcpy(hdr, &len, 8);
  return WriteAll(fd, hdr, 8) && WriteAll(fd, payload.data(),
                                          payload.size());
}

// Reads frames until a RAW frame arrives (pickled frames are
// length-skipped — this client never parses pickle); returns its bytes.
bool ReadRawFrame(int fd, std::string* out) {
  for (;;) {
    char hdr[8];
    if (!ReadAll(fd, hdr, 8)) return false;
    uint64_t len;
    memcpy(&len, hdr, 8);
    const bool raw = len & kRawBit;
    len &= ~kRawBit;
    std::vector<char> buf(len);
    if (!ReadAll(fd, buf.data(), len)) return false;
    if (raw) {
      out->assign(buf.data(), len);
      return true;
    }
    // else: a pickled frame for some other party (pubsub etc.) — skip.
  }
}

// ---- tiny JSON field extraction (flat string fields of our reply) ----------

std::string JsonStringField(const std::string& js, const std::string& key) {
  const std::string pat = "\"" + key + "\":";
  size_t i = js.find(pat);
  if (i == std::string::npos) return "";
  i += pat.size();
  while (i < js.size() && (js[i] == ' ')) i++;
  if (i >= js.size()) return "";
  if (js[i] == '"') {
    std::string out;
    for (size_t j = i + 1; j < js.size(); j++) {
      if (js[j] == '\\' && j + 1 < js.size()) {
        out += js[++j];
      } else if (js[j] == '"') {
        return out;
      } else {
        out += js[j];
      }
    }
    return out;
  }
  // non-string value: scan to the matching end at depth 0
  int depth = 0;
  size_t j = i;
  for (; j < js.size(); j++) {
    char c = js[j];
    if (c == '[' || c == '{') depth++;
    if (c == ']' || c == '}') {
      if (depth == 0) break;
      depth--;
    }
    if ((c == ',') && depth == 0) break;
  }
  return js.substr(i, j - i);
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr,
            "usage: %s <host:port|unix:/path> <module:qualname> "
            "[json-args] [json-options]\n",
            argv[0]);
    return 2;
  }
  std::string addr = argv[1];
  if (addr.rfind("tcp:", 0) == 0) addr = addr.substr(4);
  int fd;
  if (addr.rfind("unix:", 0) == 0) {
    fd = DialUnix(addr.substr(5));
  } else {
    size_t colon = addr.rfind(':');
    if (colon == std::string::npos) {
      fprintf(stderr, "bad address %s\n", addr.c_str());
      return 2;
    }
    fd = DialTcp(addr.substr(0, colon), addr.substr(colon + 1));
  }
  if (fd < 0) {
    fprintf(stderr, "connect failed: %s\n", argv[1]);
    return 2;
  }

  const std::string cmd = argv[2];
  std::string req;
  if (cmd == "actor-create") {
    if (argc < 4) {
      fprintf(stderr, "actor-create needs <module:Class>\n");
      return 2;
    }
    req = std::string("{\"op\":\"actor_create\",\"class\":\"") + argv[3] +
          "\",\"args\":" + (argc > 4 ? argv[4] : "[]") +
          ",\"options\":" + (argc > 5 ? argv[5] : "{}") + "}";
  } else if (cmd == "actor-call") {
    if (argc < 5) {
      fprintf(stderr, "actor-call needs <actor-name> <method>\n");
      return 2;
    }
    req = std::string("{\"op\":\"actor_call\",\"actor\":\"") + argv[3] +
          "\",\"method\":\"" + argv[4] +
          "\",\"args\":" + (argc > 5 ? argv[5] : "[]") + "}";
  } else if (cmd == "actor-kill") {
    if (argc < 4) {
      fprintf(stderr, "actor-kill needs <actor-name>\n");
      return 2;
    }
    req = std::string("{\"op\":\"actor_kill\",\"actor\":\"") + argv[3] +
          "\"}";
  } else {
    // default: normal-task submission by function descriptor
    req = std::string("{\"op\":\"submit\",\"function\":\"") + cmd +
          "\",\"args\":" + (argc > 3 ? argv[3] : "[]") +
          ",\"options\":" + (argc > 4 ? argv[4] : "{}") + "}";
  }
  const int rid = 1;
  if (!SendFrame(fd, PickleCall(kXlangCall, rid, req))) {
    fprintf(stderr, "send failed\n");
    return 2;
  }
  std::string reply;
  if (!ReadRawFrame(fd, &reply)) {
    fprintf(stderr, "connection closed before reply\n");
    return 2;
  }
  close(fd);
  const std::string status = JsonStringField(reply, "status");
  if (status != "ok") {
    fprintf(stderr, "error: %s\n",
            JsonStringField(reply, "error").c_str());
    return 1;
  }
  printf("%s\n", JsonStringField(reply, "result").c_str());
  return 0;
}
