// ray_tpu C++ client: native access to a node's shared-memory object store.
//
// Ref analog: the reference's C++ worker API (cpp/include/ray/api.h) lets
// native code produce/consume objects in the plasma store. The ray_tpu
// equivalent is data-plane interop: a C++ process on a node attaches to
// that node's arena (created by the Python runtime) and reads/writes
// objects zero-copy — e.g. a native data loader feeding a Python/JAX
// training job, or a C++ consumer of task results. Task/actor submission
// stays in Python (tasks are Python functions); this header is the
// native data plane, not a native task runtime.
//
// Link against ray_tpu/native/libshm_store.so (built by
// `python -m ray_tpu.native.build`). Object IDs are 20 raw bytes —
// obtain them from Python (`ref.id.binary()`) or mint client-local ones
// with raytpu::ObjectId::Random() for native<->native use.
//
// Payload convention for cross-language objects: RAW BYTES with empty
// metadata (meta_size == 0). Python reads them with
// `ShmObjectStore.get_raw(oid)` and writes them with
// `ShmObjectStore.put_raw(oid, data)`; pickled Python objects carry a
// non-empty metadata suffix and are NOT generally decodable from C++.

#ifndef RAY_TPU_CLIENT_H_
#define RAY_TPU_CLIENT_H_

#include <cstdint>
#include <cstring>
#include <random>
#include <stdexcept>
#include <string>

extern "C" {
void* shm_store_attach(const char* name);
void shm_store_detach(void* handle);
// Returns arena offset (>0), 0 = full, -1 = already exists.
int64_t shm_store_create_object(void* handle, const uint8_t* id,
                                uint64_t data_size, uint64_t meta_size);
int shm_store_seal(void* handle, const uint8_t* id);
// out = {offset, data_size, meta_size}; pins the object. 0 on success.
int shm_store_get(void* handle, const uint8_t* id, uint64_t* out);
int shm_store_contains(void* handle, const uint8_t* id);
int shm_store_release(void* handle, const uint8_t* id);
int shm_store_delete(void* handle, const uint8_t* id);
uint64_t shm_store_bytes_in_use(void* handle);
uint64_t shm_store_capacity(void* handle);
void* shm_store_base_ptr(void* handle);
}

namespace raytpu {

constexpr int kIdSize = 20;

struct ObjectId {
  uint8_t bytes[kIdSize];

  static ObjectId Random() {
    ObjectId id;
    std::random_device rd;
    for (int i = 0; i < kIdSize; i++) id.bytes[i] = rd() & 0xff;
    return id;
  }

  static ObjectId FromBinary(const std::string& bin) {
    if (bin.size() != kIdSize)
      throw std::invalid_argument("ObjectId needs exactly 20 bytes");
    ObjectId id;
    std::memcpy(id.bytes, bin.data(), kIdSize);
    return id;
  }

  static ObjectId FromHex(const std::string& hex) {
    if (hex.size() != 2 * kIdSize)
      throw std::invalid_argument("ObjectId hex needs 40 chars");
    ObjectId id;
    for (int i = 0; i < kIdSize; i++)
      id.bytes[i] = static_cast<uint8_t>(
          std::stoi(hex.substr(2 * i, 2), nullptr, 16));
    return id;
  }

  std::string Hex() const {
    static const char* d = "0123456789abcdef";
    std::string out(2 * kIdSize, '0');
    for (int i = 0; i < kIdSize; i++) {
      out[2 * i] = d[bytes[i] >> 4];
      out[2 * i + 1] = d[bytes[i] & 0xf];
    }
    return out;
  }

  const uint8_t* data() const { return bytes; }
};

// A pinned, zero-copy view of an object's payload; releases the pin on
// destruction.
class ObjectBuffer {
 public:
  ObjectBuffer(void* store, ObjectId id, const uint8_t* data, uint64_t size)
      : store_(store), id_(id), data_(data), size_(size) {}
  ObjectBuffer(const ObjectBuffer&) = delete;
  ObjectBuffer& operator=(const ObjectBuffer&) = delete;
  ObjectBuffer(ObjectBuffer&& o) noexcept
      : store_(o.store_), id_(o.id_), data_(o.data_), size_(o.size_) {
    o.store_ = nullptr;
  }
  ~ObjectBuffer() {
    if (store_) shm_store_release(store_, id_.data());
  }
  const uint8_t* data() const { return data_; }
  uint64_t size() const { return size_; }
  std::string ToString() const {
    return std::string(reinterpret_cast<const char*>(data_), size_);
  }

 private:
  void* store_;
  ObjectId id_;
  const uint8_t* data_;
  uint64_t size_;
};

class ObjectStoreClient {
 public:
  // `store_name` is the node's arena name — Python exposes it as
  // `ray_tpu.nodes()[i]["store_name"]` (also in `RAY_TPU_STORE_NAME`
  // inside workers).
  explicit ObjectStoreClient(const std::string& store_name) {
    handle_ = shm_store_attach(store_name.c_str());
    if (!handle_)
      throw std::runtime_error("cannot attach to store '" + store_name +
                               "' (is the runtime up on this node?)");
    base_ = static_cast<uint8_t*>(shm_store_base_ptr(handle_));
  }
  ~ObjectStoreClient() {
    if (handle_) shm_store_detach(handle_);
  }
  ObjectStoreClient(const ObjectStoreClient&) = delete;
  ObjectStoreClient& operator=(const ObjectStoreClient&) = delete;

  // Store raw bytes under `id` (cross-language convention: no metadata).
  void Put(const ObjectId& id, const void* data, uint64_t size) {
    int64_t off = shm_store_create_object(handle_, id.data(), size, 0);
    if (off == -1) throw std::runtime_error("object already exists");
    if (off == 0) throw std::runtime_error("object store is full");
    std::memcpy(base_ + off, data, size);
    if (shm_store_seal(handle_, id.data()) != 0)
      throw std::runtime_error("seal failed");
  }
  void Put(const ObjectId& id, const std::string& s) {
    Put(id, s.data(), s.size());
  }

  bool Contains(const ObjectId& id) const {
    return shm_store_contains(handle_, id.data()) == 1;
  }

  // Zero-copy pinned view (data + metadata contiguous; size excludes
  // metadata for raw-convention objects, which have none).
  ObjectBuffer Get(const ObjectId& id) const {
    uint64_t out[3];
    if (shm_store_get(handle_, id.data(), out) != 0)
      throw std::runtime_error("object not found: " + id.Hex());
    return ObjectBuffer(handle_, id, base_ + out[0], out[1]);
  }

  bool Delete(const ObjectId& id) {
    return shm_store_delete(handle_, id.data()) == 0;
  }

  uint64_t BytesInUse() const { return shm_store_bytes_in_use(handle_); }
  uint64_t Capacity() const { return shm_store_capacity(handle_); }

 private:
  void* handle_ = nullptr;
  uint8_t* base_ = nullptr;
};

}  // namespace raytpu

#endif  // RAY_TPU_CLIENT_H_
