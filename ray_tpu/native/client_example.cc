// C++ client round-trip example/driver (exercised by
// tests/test_cpp_client.py; also a template for native data loaders).
//
//   client_example <store_name> put <object_id_hex> <payload>
//   client_example <store_name> get <object_id_hex>
//
// Build:
//   g++ -O2 -std=c++17 client_example.cc -o client_example \
//       -L. -lshm_store -Wl,-rpath,'$ORIGIN'

#include <cstdio>
#include <string>

#include "ray_tpu_client.h"

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: %s <store> put <id_hex> <payload> | get <id_hex>\n",
                 argv[0]);
    return 2;
  }
  try {
    raytpu::ObjectStoreClient client(argv[1]);
    raytpu::ObjectId id = raytpu::ObjectId::FromHex(argv[3]);
    std::string cmd = argv[2];
    if (cmd == "put") {
      client.Put(id, std::string(argv[4]));
      std::printf("put %s (%zu bytes)\n", id.Hex().c_str(),
                  std::string(argv[4]).size());
    } else if (cmd == "get") {
      raytpu::ObjectBuffer buf = client.Get(id);
      std::printf("get %s -> %llu bytes: %s\n", id.Hex().c_str(),
                  static_cast<unsigned long long>(buf.size()),
                  buf.ToString().c_str());
    } else {
      std::fprintf(stderr, "unknown command %s\n", cmd.c_str());
      return 2;
    }
  } catch (const std::exception& e) {
    std::fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
  return 0;
}
