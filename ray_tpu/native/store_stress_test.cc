// Concurrency stress test for the shm object store, built under
// TSAN/ASAN by the test suite.
//
// Ref analog: the reference's sanitizer strategy (SURVEY.md §4.7 —
// .bazelrc asan/tsan configs run the C++ unit tests instrumented).
// Here: N threads hammer one store with create/seal/get/release/delete
// cycles over overlapping object-id spaces, plus an eviction thread,
// so the arena allocator, the object table, and the process-shared
// robust mutex see real contention. Exit 0 = no sanitizer report (the
// sanitizers abort non-zero on a finding).
//
// Build+run: tests/test_native_sanitizers.py (gated on toolchain).

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
void* shm_store_create(const char* name, uint64_t segment_size);
void shm_store_destroy(void* h, const char* name);
int64_t shm_store_create_object(void* h, const uint8_t* oid,
                                uint64_t data_size, uint64_t meta_size);
int shm_store_seal(void* h, const uint8_t* oid);
int shm_store_get(void* h, const uint8_t* oid, uint64_t* out);
int shm_store_release(void* h, const uint8_t* oid);
int shm_store_delete(void* h, const uint8_t* oid);
int shm_store_evict(void* h, uint64_t need, uint8_t* out_ids, int max_ids);
uint64_t shm_store_bytes_in_use(void* h);
}

namespace {

constexpr int kThreads = 8;
constexpr int kOpsPerThread = 4000;
constexpr int kIdSpace = 64;  // overlapping ids force table contention

void make_oid(uint8_t* buf, int thread_mod, int i) {
  // 20-byte binary ids like the Python side's ObjectID
  std::memset(buf, 0, 20);
  std::snprintf(reinterpret_cast<char*>(buf), 20, "t%02d-obj-%06d",
                thread_mod, i % kIdSpace);
}

void worker(void* h, int tid, std::atomic<int>* errors) {
  uint8_t oid[20];
  uint64_t out[3];
  for (int i = 0; i < kOpsPerThread; ++i) {
    // threads share an id space pairwise so create/get/delete race
    make_oid(oid, tid / 2, i);
    int64_t off = shm_store_create_object(h, oid, 256 + (i % 1024), 16);
    if (off > 0) {
      if (shm_store_seal(h, oid) != 0) {
        // a racing thread deleted it between create and seal: legal
      }
    }
    if (shm_store_get(h, oid, out) == 0) {
      shm_store_release(h, oid);
    }
    if (i % 7 == 0) shm_store_delete(h, oid);
  }
  (void)errors;
}

}  // namespace

int main() {
  const char* name = "rtpu_stress_test_arena";
  void* h = shm_store_create(name, 16ull << 20);
  if (!h) {
    std::fprintf(stderr, "store create failed\n");
    return 2;
  }
  std::atomic<int> errors{0};
  std::vector<std::thread> threads;
  threads.reserve(kThreads + 1);
  for (int t = 0; t < kThreads; ++t)
    threads.emplace_back(worker, h, t, &errors);
  // eviction pressure concurrent with the object churn
  std::thread evictor([h] {
    uint8_t evicted[20 * 64];
    for (int i = 0; i < 200; ++i) {
      shm_store_evict(h, 1 << 20, evicted, 64);
      std::this_thread::yield();
    }
  });
  for (auto& t : threads) t.join();
  evictor.join();
  uint64_t used = shm_store_bytes_in_use(h);
  shm_store_destroy(h, name);
  std::printf("ok used=%llu errors=%d\n",
              static_cast<unsigned long long>(used), errors.load());
  return errors.load() == 0 ? 0 : 1;
}
