"""Build the native components into ray_tpu/native/lib*.so.

Invoked lazily at import time (ray_tpu.core.object_store) if the shared
library is missing or older than its sources, and by `python -m
ray_tpu.native.build` explicitly. Uses g++ directly — the only dependencies
are pthreads and librt.
"""

from __future__ import annotations

import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))

TARGETS = {
    "libshm_store.so": ["shm_store.cc"],
    "libsched_core.so": ["sched_core.cc"],
}

# standalone executables (the C++ task-submission frontend)
BINARIES = {
    "task_client": ["task_client.cc"],
}

CXXFLAGS = ["-O2", "-fPIC", "-shared", "-std=c++17", "-Wall"]
BINFLAGS = ["-O2", "-std=c++17", "-Wall"]
LDFLAGS = ["-lpthread", "-lrt"]


def _stale(out: str, srcs) -> bool:
    return not os.path.exists(out) or any(
        os.path.getmtime(out) < os.path.getmtime(s) for s in srcs)


def build(force: bool = False) -> None:
    for lib, sources in TARGETS.items():
        out = os.path.join(_DIR, lib)
        srcs = [os.path.join(_DIR, s) for s in sources]
        if not force and not _stale(out, srcs):
            continue
        cmd = ["g++", *CXXFLAGS, "-o", out, *srcs, *LDFLAGS]
        subprocess.run(cmd, check=True, cwd=_DIR)


def build_binary(name: str, force: bool = False) -> str:
    """Compile one executable from BINARIES; returns its path."""
    sources = BINARIES[name]
    out = os.path.join(_DIR, name)
    srcs = [os.path.join(_DIR, s) for s in sources]
    if force or _stale(out, srcs):
        subprocess.run(["g++", *BINFLAGS, "-o", out, *srcs, *LDFLAGS],
                       check=True, cwd=_DIR)
    return out


def build_sanitized(sources, out_name: str, sanitizer: str) -> str:
    """Compile an instrumented test binary (ref: .bazelrc asan/tsan
    configs); ``sanitizer`` is "thread" or "address". Returns its path.
    Sanitized binaries link the C++ sources directly (no .so) so the
    instrumentation covers everything."""
    out = os.path.join(_DIR, out_name)
    srcs = [os.path.join(_DIR, s) for s in sources]
    if _stale(out, srcs):
        subprocess.run(
            ["g++", "-O1", "-g", "-std=c++17", f"-fsanitize={sanitizer}",
             "-fno-omit-frame-pointer", "-o", out, *srcs, *LDFLAGS],
            check=True, cwd=_DIR)
    return out


def lib_path(name: str) -> str:
    build()
    return os.path.join(_DIR, name)


if __name__ == "__main__":
    build(force="--force" in sys.argv)
    print("native libs built")
