"""Build the native components into ray_tpu/native/lib*.so.

Invoked lazily at import time (ray_tpu.core.object_store) if the shared
library is missing or older than its sources, and by `python -m
ray_tpu.native.build` explicitly. Uses g++ directly — the only dependencies
are pthreads and librt.
"""

from __future__ import annotations

import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))

TARGETS = {
    "libshm_store.so": ["shm_store.cc"],
}

CXXFLAGS = ["-O2", "-fPIC", "-shared", "-std=c++17", "-Wall"]
LDFLAGS = ["-lpthread", "-lrt"]


def build(force: bool = False) -> None:
    for lib, sources in TARGETS.items():
        out = os.path.join(_DIR, lib)
        srcs = [os.path.join(_DIR, s) for s in sources]
        if (
            not force
            and os.path.exists(out)
            and all(os.path.getmtime(out) >= os.path.getmtime(s) for s in srcs)
        ):
            continue
        cmd = ["g++", *CXXFLAGS, "-o", out, *srcs, *LDFLAGS]
        subprocess.run(cmd, check=True, cwd=_DIR)


def lib_path(name: str) -> str:
    build()
    return os.path.join(_DIR, name)


if __name__ == "__main__":
    build(force="--force" in sys.argv)
    print("native libs built")
