"""Virtual multi-node cluster for tests.

Analog of the reference's ``ray.cluster_utils.Cluster``
(python/ray/cluster_utils.py:99, add_node :165): N logical nodes in one
process, each with its own resource view, worker pool, and shm object store,
all hosted by the embedded head. The workhorse for scheduling / placement /
failover tests without real hosts (SURVEY.md §4.2).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.core import api
from ray_tpu.core.resources import TpuTopology


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self._info = None
        if initialize_head:
            args = dict(head_node_args or {})
            self._info = api.init(**args)

    @property
    def head(self):
        from ray_tpu.core.api import _head

        return _head

    def add_node(self, *, num_cpus: int = 1, num_tpus: int = 0,
                 memory: Optional[int] = None,
                 object_store_memory: Optional[int] = None,
                 resources: Optional[dict] = None,
                 labels: Optional[dict] = None,
                 tpu_topology: Optional[TpuTopology] = None) -> int:
        """Add a logical node; returns its node index."""
        return self.head.add_node(
            num_cpus=num_cpus, num_tpus=num_tpus, memory=memory,
            object_store_memory=object_store_memory, resources=resources,
            labels=labels, tpu_topology=tpu_topology)

    def remove_node(self, node_idx: int):
        """Kill a logical node (workers die, objects on it are lost)."""
        self.head.remove_node(node_idx)

    # ------------------------------------------------ real remote processes

    def enable_tcp(self, host: str = "127.0.0.1") -> str:
        """Open the head's TCP port; returns the tcp: address to join."""
        return self.head.enable_tcp(host=host, advertise_ip=host)

    def add_remote_node(self, *, num_cpus: int = 1, num_tpus: int = 0,
                        object_store_memory: Optional[int] = None,
                        timeout: float = 60.0):
        """Start a real node-agent PROCESS that joins over TCP — exercises
        the full multi-host path (TCP registration, delegated worker fork,
        cross-host object transfer) on one machine. Returns a
        RemoteNodeHandle with .node_idx / .terminate().
        """
        import os
        import subprocess
        import sys
        import time

        addr = self.enable_tcp()
        known = set(self.head.nodes)
        import ray_tpu as _pkg

        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(_pkg.__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_parent + os.pathsep + \
            env.get("PYTHONPATH", "")
        cmd = [sys.executable, "-m", "ray_tpu.core.node_agent",
               "--address", addr, "--num-cpus", str(num_cpus),
               "--num-tpus", str(num_tpus)]
        if object_store_memory:
            cmd += ["--object-store-memory", str(object_store_memory)]
        proc = subprocess.Popen(cmd, env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            new = set(self.head.nodes) - known
            if new:
                idx = new.pop()
                node = self.head.nodes.get(idx)
                return RemoteNodeHandle(
                    proc, idx, getattr(node, "store_name", ""))
            if proc.poll() is not None:
                out = proc.stdout.read().decode(errors="replace")
                raise RuntimeError(f"node agent died: {out[-2000:]}")
            time.sleep(0.05)
        proc.kill()
        raise TimeoutError("node agent did not register in time")

    def shutdown(self):
        api.shutdown()


class NodeKiller:
    """Randomized fault-injection harness.

    Analog of the reference's chaos ``NodeKillerActor``
    (python/ray/_private/test_utils.py:1386): a background thread that,
    at random intervals, kills a random *non-head* node — logical nodes
    via ``Cluster.remove_node`` and real agent processes via
    ``RemoteNodeHandle.terminate`` — while a workload runs. With
    ``respawn=True`` (the default) each killed logical node is replaced
    by a fresh node with the same CPU/TPU totals, so the cluster keeps
    capacity and a retried/lineage-reconstructed workload should
    converge despite the carnage.

    Usage::

        killer = NodeKiller(cluster, max_kills=3, seed=7)
        killer.start()
        ...run workload with max_retries=-1...
        killer.stop()
        assert killer.kills  # at least one node actually died
    """

    def __init__(self, cluster: Cluster, *,
                 interval_s=(0.2, 0.8), max_kills: int = 3,
                 respawn: bool = True, seed: Optional[int] = None,
                 protect=(0,), remote_handles=()):
        import random

        self._cluster = cluster
        self._interval = interval_s
        self._max_kills = max_kills
        self._respawn = respawn
        self._protect = set(protect)
        self._remote = list(remote_handles)
        self._rng = random.Random(seed)
        self._stop = None
        self._thread = None
        #: [(monotonic_time, node_idx, kind)] for each node actually killed
        self.kills = []
        #: exception that ended the killer thread early, if any
        self.error = None

    def _eligible(self):
        head = self._cluster.head
        logical = [(idx, n) for idx, n in list(head.nodes.items())
                   if idx not in self._protect and not n.is_remote]
        remote = [h for h in self._remote
                  if h.proc.poll() is None and
                  h.node_idx not in self._protect]
        return logical, remote

    def _kill_one(self):
        import time

        logical, remote = self._eligible()
        choices = [("logical", v) for v in logical] + \
                  [("remote", h) for h in remote]
        if not choices:
            return False
        kind, victim = self._rng.choice(choices)
        if kind == "logical":
            idx, node = victim
            total = node.resources.total.to_dict()
            labels = dict(node.resources.labels)
            topology = node.resources.tpu
            self._cluster.remove_node(idx)
            self.kills.append((time.monotonic(), idx, "logical"))
            if self._respawn:
                # replacement preserves the victim's FULL resource set —
                # CPU/TPU/memory plus custom resources AND tpu topology,
                # so topology-aware (STRICT_PACK) workloads can still
                # reschedule and cluster capacity holds steady. CPU/TPU
                # pass through unrounded: the resource model is
                # fixed-point, so fractional grants survive respawn.
                custom = {k: v for k, v in total.items()
                          if k not in ("CPU", "TPU", "memory",
                                       "object_store_memory")}
                self._cluster.add_node(
                    num_cpus=total.get("CPU", 0),
                    num_tpus=total.get("TPU", 0),
                    memory=total.get("memory"),
                    object_store_memory=(
                        int(total["object_store_memory"])
                        if "object_store_memory" in total else None),
                    resources=custom or None,
                    labels=labels or None,
                    tpu_topology=topology)
        else:
            victim.terminate()
            self.kills.append((time.monotonic(), victim.node_idx, "remote"))
        return True

    def _run(self):
        lo, hi = self._interval
        while not self._stop.is_set() and len(self.kills) < self._max_kills:
            if self._stop.wait(self._rng.uniform(lo, hi)):
                break
            try:
                self._kill_one()
            except Exception as e:
                # a racing cluster shutdown mustn't crash the thread, but
                # record why injection stopped so tests can surface it
                self.error = e
                break

    def start(self):
        import threading

        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="node-killer", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)


class RemoteNodeHandle:
    def __init__(self, proc, node_idx: int, store_name: str = ""):
        self.proc = proc
        self.node_idx = node_idx
        #: the agent's /dev/shm arena file name, so terminate() can
        #: sweep it — SIGKILL gives the agent no chance to unlink its
        #: own arena, and each orphan pins object_store_memory bytes of
        #: shared memory until someone removes it (ROADMAP 5c)
        self.store_name = store_name

    def terminate(self):
        """Kill the agent process (simulates host loss) and sweep its
        leaked /dev/shm arena."""
        if self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                pass
        self.proc.wait(timeout=10)
        if self.store_name:
            import os

            try:
                os.unlink(f"/dev/shm/{self.store_name}")
            except OSError:
                pass
