"""Virtual multi-node cluster for tests.

Analog of the reference's ``ray.cluster_utils.Cluster``
(python/ray/cluster_utils.py:99, add_node :165): N logical nodes in one
process, each with its own resource view, worker pool, and shm object store,
all hosted by the embedded head. The workhorse for scheduling / placement /
failover tests without real hosts (SURVEY.md §4.2).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.core import api
from ray_tpu.core.resources import TpuTopology


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self._info = None
        if initialize_head:
            args = dict(head_node_args or {})
            self._info = api.init(**args)

    @property
    def head(self):
        from ray_tpu.core.api import _head

        return _head

    def add_node(self, *, num_cpus: int = 1, num_tpus: int = 0,
                 memory: Optional[int] = None,
                 object_store_memory: Optional[int] = None,
                 resources: Optional[dict] = None,
                 labels: Optional[dict] = None,
                 tpu_topology: Optional[TpuTopology] = None) -> int:
        """Add a logical node; returns its node index."""
        return self.head.add_node(
            num_cpus=num_cpus, num_tpus=num_tpus, memory=memory,
            object_store_memory=object_store_memory, resources=resources,
            labels=labels, tpu_topology=tpu_topology)

    def remove_node(self, node_idx: int):
        """Kill a logical node (workers die, objects on it are lost)."""
        self.head.remove_node(node_idx)

    # ------------------------------------------------ real remote processes

    def enable_tcp(self, host: str = "127.0.0.1") -> str:
        """Open the head's TCP port; returns the tcp: address to join."""
        return self.head.enable_tcp(host=host, advertise_ip=host)

    def add_remote_node(self, *, num_cpus: int = 1, num_tpus: int = 0,
                        object_store_memory: Optional[int] = None,
                        timeout: float = 60.0):
        """Start a real node-agent PROCESS that joins over TCP — exercises
        the full multi-host path (TCP registration, delegated worker fork,
        cross-host object transfer) on one machine. Returns a
        RemoteNodeHandle with .node_idx / .terminate().
        """
        import os
        import subprocess
        import sys
        import time

        addr = self.enable_tcp()
        known = set(self.head.nodes)
        import ray_tpu as _pkg

        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.abspath(_pkg.__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_parent + os.pathsep + \
            env.get("PYTHONPATH", "")
        cmd = [sys.executable, "-m", "ray_tpu.core.node_agent",
               "--address", addr, "--num-cpus", str(num_cpus),
               "--num-tpus", str(num_tpus)]
        if object_store_memory:
            cmd += ["--object-store-memory", str(object_store_memory)]
        proc = subprocess.Popen(cmd, env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT,
                                start_new_session=True)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            new = set(self.head.nodes) - known
            if new:
                return RemoteNodeHandle(proc, new.pop())
            if proc.poll() is not None:
                out = proc.stdout.read().decode(errors="replace")
                raise RuntimeError(f"node agent died: {out[-2000:]}")
            time.sleep(0.05)
        proc.kill()
        raise TimeoutError("node agent did not register in time")

    def shutdown(self):
        api.shutdown()


class RemoteNodeHandle:
    def __init__(self, proc, node_idx: int):
        self.proc = proc
        self.node_idx = node_idx

    def terminate(self):
        """Kill the agent process (simulates host loss)."""
        if self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                pass
        self.proc.wait(timeout=10)
