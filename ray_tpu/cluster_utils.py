"""Virtual multi-node cluster for tests.

Analog of the reference's ``ray.cluster_utils.Cluster``
(python/ray/cluster_utils.py:99, add_node :165): N logical nodes in one
process, each with its own resource view, worker pool, and shm object store,
all hosted by the embedded head. The workhorse for scheduling / placement /
failover tests without real hosts (SURVEY.md §4.2).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.core import api
from ray_tpu.core.resources import TpuTopology


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[dict] = None):
        self._info = None
        if initialize_head:
            args = dict(head_node_args or {})
            self._info = api.init(**args)

    @property
    def head(self):
        from ray_tpu.core.api import _head

        return _head

    def add_node(self, *, num_cpus: int = 1, num_tpus: int = 0,
                 memory: Optional[int] = None,
                 object_store_memory: Optional[int] = None,
                 resources: Optional[dict] = None,
                 labels: Optional[dict] = None,
                 tpu_topology: Optional[TpuTopology] = None) -> int:
        """Add a logical node; returns its node index."""
        return self.head.add_node(
            num_cpus=num_cpus, num_tpus=num_tpus, memory=memory,
            object_store_memory=object_store_memory, resources=resources,
            labels=labels, tpu_topology=tpu_topology)

    def remove_node(self, node_idx: int):
        """Kill a logical node (workers die, objects on it are lost)."""
        self.head.remove_node(node_idx)

    def shutdown(self):
        api.shutdown()
