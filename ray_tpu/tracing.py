"""Tracing: chrome-trace timeline export + user span annotations.

Ref parity: ray.timeline() (python/ray/_private/state.py chrome_tracing_dump
— every task becomes a chrome trace event laid out by worker lane) and the
span annotations of ray.util.tracing (tracing_helper.py; the reference
wraps task entry/exit in OpenTelemetry spans). Spans here ride the same
task-event channel the state API uses — no OpenTelemetry dependency; the
produced JSON loads in chrome://tracing / Perfetto.
"""

from __future__ import annotations

import json
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from .core import protocol as P
from .core.context import get_context

SPAN_START = "SPAN_START"
SPAN_END = "SPAN_END"


@contextmanager
def span(name: str):
    """Annotate a code region; it appears as a lane event in timeline().

    Usable in the driver or inside tasks/actors::

        with ray_tpu.tracing.span("preprocess"):
            ...
    """
    ctx = get_context()
    span_id = uuid.uuid4().hex[:16]
    ctx.events.record(span_id, name, SPAN_START)
    try:
        yield
    finally:
        ctx.events.record(span_id, name, SPAN_END)


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Cluster timeline as chrome-trace events (ref: ray.timeline()).

    Task RUNNING->FINISHED/FAILED pairs and span START->END pairs become
    complete ("X") events; pid = node, tid = worker. Returns the event
    list; also writes JSON when ``filename`` is given."""
    ctx = get_context()
    ctx.events.flush()
    time.sleep(0.05)  # let the head ingest the tail of the batch
    (rows,) = ctx.head.call(P.STATE_QUERY, "task_events", 1_000_000,
                            timeout=30)
    open_at: Dict[str, dict] = {}
    events: List[Dict[str, Any]] = []
    for r in sorted(rows, key=lambda r: r["ts"]):
        state = r["state"]
        if state in ("RUNNING", SPAN_START):
            open_at[r["task_id"]] = r
        elif state in ("FINISHED", "FAILED", SPAN_END):
            start = open_at.pop(r["task_id"], None)
            if start is None:
                continue
            events.append({
                "name": r["name"],
                "cat": "span" if state == SPAN_END else "task",
                "ph": "X",
                "ts": start["ts"] * 1e6,           # chrome wants usec
                "dur": max(r["ts"] - start["ts"], 0) * 1e6,
                "pid": f"node{start['node_idx']}",
                "tid": f"worker:{start['worker_id'][:8]}",
                "args": ({"error": r["error"]} if state == "FAILED"
                         else {}),
            })
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
