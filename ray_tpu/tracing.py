"""Tracing: chrome-trace timeline export + user span annotations.

Ref parity: ray.timeline() (python/ray/_private/state.py chrome_tracing_dump
— every task becomes a chrome trace event laid out by worker lane) and the
span annotations of ray.util.tracing (tracing_helper.py; the reference
wraps task entry/exit in OpenTelemetry spans AND propagates the caller's
span context inside the task spec, so spans nest across processes). Spans
here ride the same task-event channel the state API uses — no OpenTelemetry
dependency; the produced JSON loads in chrome://tracing / Perfetto.

Cross-task propagation: every span carries ``trace_id`` / ``span_id`` /
``parent_span_id``. Task submission stamps the caller's active span
context into the spec (core/events.py submit_trace_ctx); task execution
wraps user code in a span parented to the submit site; a ``span()``
opened inside a remote task therefore shares the submitter's trace_id
and nests under it — ``timeline()`` exposes the ids via each event's
``args`` so Perfetto (and tests) can reassemble the cross-process tree.
"""

from __future__ import annotations

import json
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from .core import events as _ev
from .core.ids import _random_bytes
from .core import protocol as P
from .core.context import get_context

SPAN_START = "SPAN_START"
SPAN_END = "SPAN_END"

# Spans whose name starts with this land in timeline() under cat
# "comm" — the communication lanes (collective hops, object-plane
# transfers, pipeline grad all-reduce) the trace analyzer separates
# from compute when computing exposed-comm time.
COMM_PREFIX = "comm."

# Page size for the chunked task-event pull (r19): bounds the head's
# per-reply frame, replacing the old single 1M-row STATE_QUERY.
_PAGE_LIMIT = 50_000


def current_span_context() -> Optional[tuple]:
    """The active (trace_id, span_id) of this thread, if any — the task
    span inside a remote task, or the innermost open span()."""
    return _ev.current_trace()


@contextmanager
def span(name: str):
    """Annotate a code region; it appears as a lane event in timeline().

    Usable in the driver or inside tasks/actors::

        with ray_tpu.tracing.span("preprocess"):
            ...

    Inside a remote task the span nests under the task's auto-span (and
    thus under the submitting span), sharing its trace_id.
    """
    ctx = get_context()
    parent = _ev.current_trace()
    trace_id = parent[0] if parent else _random_bytes(16).hex()
    parent_id = parent[1] if parent else ""
    span_id = _ev.new_span_id()
    ctx.events.record(span_id, name, SPAN_START, trace_id=trace_id,
                      span_id=span_id, parent_span_id=parent_id)
    prev = _ev.set_trace((trace_id, span_id))
    try:
        yield
    finally:
        _ev.set_trace(prev)
        ctx.events.record(span_id, name, SPAN_END, trace_id=trace_id,
                          span_id=span_id, parent_span_id=parent_id)


@contextmanager
def comm_span(name: str):
    """``span()`` for communication intervals: prefixes the name with
    ``comm.`` (so timeline() categorizes it as a comm lane event) and
    NO-OPS outside a CoreContext — collective/transfer internals call
    this from processes (node agents, teardown paths) that may not be
    attached to a cluster, and instrumentation must never be the thing
    that throws."""
    from .core.context import get_context_if_exists

    if get_context_if_exists() is None:
        yield
        return
    with span(COMM_PREFIX + name if not name.startswith(COMM_PREFIX)
              else name):
        yield


def record_comm_span(name: str, start_ts: float, end_ts: float,
                     start_mono: Optional[float] = None,
                     end_mono: Optional[float] = None):
    """Retroactively emit one comm.* SPAN_START/SPAN_END pair for an
    interval measured elsewhere (object-plane pulls stamp spans at
    completion so the fetch path carries zero tracing overhead when the
    transfer is small). No-op outside a CoreContext."""
    from .core.context import get_context_if_exists

    ctx = get_context_if_exists()
    if ctx is None:
        return
    if not name.startswith(COMM_PREFIX):
        name = COMM_PREFIX + name
    parent = _ev.current_trace()
    trace_id = parent[0] if parent else _random_bytes(16).hex()
    parent_id = parent[1] if parent else ""
    span_id = _ev.new_span_id()
    ctx.events.record(span_id, name, SPAN_START, trace_id=trace_id,
                      span_id=span_id, parent_span_id=parent_id,
                      ts=start_ts, mono=start_mono)
    ctx.events.record(span_id, name, SPAN_END, trace_id=trace_id,
                      span_id=span_id, parent_span_id=parent_id,
                      ts=end_ts, mono=end_mono)


def _pull_task_events(ctx) -> List[dict]:
    """Chunked raw-event readback (r19): page through the head's ring
    via task_events_page so no single reply frame carries the whole
    log. Falls back to the unpaged query against a pre-r19 head."""
    rows: List[dict] = []
    cursor = 0
    while True:
        try:
            (reply,) = ctx.head.call(
                P.STATE_QUERY, f"task_events_page:{cursor}",
                _PAGE_LIMIT, timeout=30)
            page = reply[0]
        except Exception:  # noqa: BLE001 — pre-r19 head: unpaged pull
            (rows,) = ctx.head.call(P.STATE_QUERY, "task_events",
                                    1_000_000, timeout=30)
            return rows
        rows.extend(page["rows"])
        cursor = page["next"]
        if page["done"] or not page["rows"]:
            return rows


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Cluster timeline as chrome-trace events (ref: ray.timeline()).

    Task RUNNING->FINISHED/FAILED pairs and span START->END pairs become
    complete ("X") events; pid = node, tid = worker; args carry the
    trace/span ids for traced events. Spans named ``comm.*`` (collective
    hops, object-plane transfers, pipeline grad all-reduce — r19) get
    cat "comm" so communication intervals lay in the same lanes as the
    compute that should be hiding them (``analyze()`` computes the
    exposed remainder). Every task with lifecycle stamps
    additionally gets per-phase sub-slices (cat "phase": sched_wait /
    dispatch / arg_fetch / exec / result_return) laid in the lane of the
    process that ended the phase — the "where does task time go" view,
    zoomable in Perfetto. Returns the event list; also writes JSON when
    ``filename`` is given."""
    ctx = get_context()
    # flush-ack: the head replies only after ingesting the batch, so the
    # STATE_QUERY below is ordered after ingestion (no sleep, no race —
    # except for OTHER workers' buffers, which flush on their own 1s
    # period as in the reference).
    ctx.events.flush(sync=True)
    rows = _pull_task_events(ctx)
    open_at: Dict[str, dict] = {}
    events: List[Dict[str, Any]] = []
    # per-task first-occurrence of each lifecycle state, for sub-slices
    lifecycle: Dict[str, Dict[str, dict]] = {}
    for r in sorted(rows, key=lambda r: r["ts"]):
        state = r["state"]
        if state in _ev.STATE_RANK:
            lifecycle.setdefault(r["task_id"], {}).setdefault(state, r)
        if state in ("RUNNING", SPAN_START):
            open_at[r["task_id"]] = r
        elif state in ("FINISHED", "FAILED", SPAN_END):
            start = open_at.pop(r["task_id"], None)
            if start is None:
                continue
            args: Dict[str, Any] = {}
            if state == "FAILED":
                args["error"] = r["error"]
            if start.get("trace_id"):
                args["trace_id"] = start["trace_id"]
                args["span_id"] = start["span_id"]
                args["parent_span_id"] = start["parent_span_id"]
            if state == SPAN_END:
                cat = "comm" if r["name"].startswith(COMM_PREFIX) \
                    else "span"
            else:
                cat = "task"
            events.append({
                "name": r["name"],
                "cat": cat,
                "ph": "X",
                "ts": start["ts"] * 1e6,           # chrome wants usec
                "dur": max(r["ts"] - start["ts"], 0) * 1e6,
                "pid": f"node{start['node_idx']}",
                "tid": f"worker:{start['worker_id'][:8]}",
                "args": args,
            })
    # per-phase sub-slices from the shared events.PHASE_BOUNDS table
    # (wall-clock laid out for display; the exact monotonic-clock
    # durations live in list_tasks()'s phase_ms). e2e is skipped — it
    # would just shadow the whole row.
    for tid, states in lifecycle.items():
        for phase, a_states, b_states in _ev.PHASE_BOUNDS:
            if phase == "e2e":
                continue
            a = next((states[s] for s in a_states if s in states), None)
            b = next((states[s] for s in b_states if s in states), None)
            if a is None or b is None:
                continue
            events.append({
                "name": f"{b['name']}:{phase}",
                "cat": "phase",
                "ph": "X",
                "ts": a["ts"] * 1e6,
                "dur": max(b["ts"] - a["ts"], 0) * 1e6,
                # the lane of the process that ENDED the phase (the
                # worker for dispatch/arg_fetch/exec, the driver for
                # sched_wait/result_return)
                "pid": f"node{b['node_idx']}",
                "tid": f"worker:{b['worker_id'][:8]}",
                "args": {"task_id": tid, "phase": phase},
            })
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def analyze(events: Optional[List[Dict[str, Any]]] = None,
            filename: Optional[str] = None) -> Dict[str, Any]:
    """Comm-aware trace analysis (r19): per-lane utilization,
    exposed-comm time (communication not hidden under compute), per-
    (stage, replica) bubble breakdown and the critical path — computed
    from ``timeline()`` events (pulled fresh when ``events`` is None).
    See :mod:`ray_tpu.trace_analysis` for the full result shape; the
    ``ray_tpu analyze`` CLI renders it."""
    from . import trace_analysis

    if events is None:
        events = timeline()
    report = trace_analysis.analyze(events)
    if filename:
        with open(filename, "w") as f:
            json.dump(report, f, indent=2)
    return report


def dump_flight_record(filename: Optional[str] = None,
                       names: Optional[List[str]] = None,
                       window_s: Optional[float] = None) -> Dict[str, Any]:
    """Flight-recorder snapshot (r19): the head's bounded metric time
    series (``state.metrics_history``), optionally written to JSON so a
    bench can correlate wall-clock trace events with counter movement
    post-hoc (series points are wall-clock stamped, same timebase as
    ``timeline()``'s ``ts``)."""
    from . import state

    record = state.metrics_history(names, window_s)
    if filename:
        with open(filename, "w") as f:
            json.dump(record, f)
    return record
