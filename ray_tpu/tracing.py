"""Tracing: chrome-trace timeline export + user span annotations.

Ref parity: ray.timeline() (python/ray/_private/state.py chrome_tracing_dump
— every task becomes a chrome trace event laid out by worker lane) and the
span annotations of ray.util.tracing (tracing_helper.py; the reference
wraps task entry/exit in OpenTelemetry spans AND propagates the caller's
span context inside the task spec, so spans nest across processes). Spans
here ride the same task-event channel the state API uses — no OpenTelemetry
dependency; the produced JSON loads in chrome://tracing / Perfetto.

Cross-task propagation: every span carries ``trace_id`` / ``span_id`` /
``parent_span_id``. Task submission stamps the caller's active span
context into the spec (core/events.py submit_trace_ctx); task execution
wraps user code in a span parented to the submit site; a ``span()``
opened inside a remote task therefore shares the submitter's trace_id
and nests under it — ``timeline()`` exposes the ids via each event's
``args`` so Perfetto (and tests) can reassemble the cross-process tree.
"""

from __future__ import annotations

import json
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

from .core import events as _ev
from .core.ids import _random_bytes
from .core import protocol as P
from .core.context import get_context

SPAN_START = "SPAN_START"
SPAN_END = "SPAN_END"


def current_span_context() -> Optional[tuple]:
    """The active (trace_id, span_id) of this thread, if any — the task
    span inside a remote task, or the innermost open span()."""
    return _ev.current_trace()


@contextmanager
def span(name: str):
    """Annotate a code region; it appears as a lane event in timeline().

    Usable in the driver or inside tasks/actors::

        with ray_tpu.tracing.span("preprocess"):
            ...

    Inside a remote task the span nests under the task's auto-span (and
    thus under the submitting span), sharing its trace_id.
    """
    ctx = get_context()
    parent = _ev.current_trace()
    trace_id = parent[0] if parent else _random_bytes(16).hex()
    parent_id = parent[1] if parent else ""
    span_id = _ev.new_span_id()
    ctx.events.record(span_id, name, SPAN_START, trace_id=trace_id,
                      span_id=span_id, parent_span_id=parent_id)
    prev = _ev.set_trace((trace_id, span_id))
    try:
        yield
    finally:
        _ev.set_trace(prev)
        ctx.events.record(span_id, name, SPAN_END, trace_id=trace_id,
                          span_id=span_id, parent_span_id=parent_id)


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Cluster timeline as chrome-trace events (ref: ray.timeline()).

    Task RUNNING->FINISHED/FAILED pairs and span START->END pairs become
    complete ("X") events; pid = node, tid = worker; args carry the
    trace/span ids for traced events. Every task with lifecycle stamps
    additionally gets per-phase sub-slices (cat "phase": sched_wait /
    dispatch / arg_fetch / exec / result_return) laid in the lane of the
    process that ended the phase — the "where does task time go" view,
    zoomable in Perfetto. Returns the event list; also writes JSON when
    ``filename`` is given."""
    ctx = get_context()
    # flush-ack: the head replies only after ingesting the batch, so the
    # STATE_QUERY below is ordered after ingestion (no sleep, no race —
    # except for OTHER workers' buffers, which flush on their own 1s
    # period as in the reference).
    ctx.events.flush(sync=True)
    (rows,) = ctx.head.call(P.STATE_QUERY, "task_events", 1_000_000,
                            timeout=30)
    open_at: Dict[str, dict] = {}
    events: List[Dict[str, Any]] = []
    # per-task first-occurrence of each lifecycle state, for sub-slices
    lifecycle: Dict[str, Dict[str, dict]] = {}
    for r in sorted(rows, key=lambda r: r["ts"]):
        state = r["state"]
        if state in _ev.STATE_RANK:
            lifecycle.setdefault(r["task_id"], {}).setdefault(state, r)
        if state in ("RUNNING", SPAN_START):
            open_at[r["task_id"]] = r
        elif state in ("FINISHED", "FAILED", SPAN_END):
            start = open_at.pop(r["task_id"], None)
            if start is None:
                continue
            args: Dict[str, Any] = {}
            if state == "FAILED":
                args["error"] = r["error"]
            if start.get("trace_id"):
                args["trace_id"] = start["trace_id"]
                args["span_id"] = start["span_id"]
                args["parent_span_id"] = start["parent_span_id"]
            events.append({
                "name": r["name"],
                "cat": "span" if state == SPAN_END else "task",
                "ph": "X",
                "ts": start["ts"] * 1e6,           # chrome wants usec
                "dur": max(r["ts"] - start["ts"], 0) * 1e6,
                "pid": f"node{start['node_idx']}",
                "tid": f"worker:{start['worker_id'][:8]}",
                "args": args,
            })
    # per-phase sub-slices from the shared events.PHASE_BOUNDS table
    # (wall-clock laid out for display; the exact monotonic-clock
    # durations live in list_tasks()'s phase_ms). e2e is skipped — it
    # would just shadow the whole row.
    for tid, states in lifecycle.items():
        for phase, a_states, b_states in _ev.PHASE_BOUNDS:
            if phase == "e2e":
                continue
            a = next((states[s] for s in a_states if s in states), None)
            b = next((states[s] for s in b_states if s in states), None)
            if a is None or b is None:
                continue
            events.append({
                "name": f"{b['name']}:{phase}",
                "cat": "phase",
                "ph": "X",
                "ts": a["ts"] * 1e6,
                "dur": max(b["ts"] - a["ts"], 0) * 1e6,
                # the lane of the process that ENDED the phase (the
                # worker for dispatch/arg_fetch/exec, the driver for
                # sched_wait/result_return)
                "pid": f"node{b['node_idx']}",
                "tid": f"worker:{b['worker_id'][:8]}",
                "args": {"task_id": tid, "phase": phase},
            })
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
