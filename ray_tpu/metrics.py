"""Application + system metrics: Counter / Gauge / Histogram.

Ref parity: ray.util.metrics (python/ray/util/metrics.py Counter/Gauge/
Histogram over src/ray/stats/metric.h:103). Re-designed transport: each
process aggregates locally (tag-tuple -> float or bucket counts) and a
pusher thread flushes deltas to the head over the existing control
connection; the head merges per (name, tags) so `metrics_summary()` /
`python -m ray_tpu list metrics`-style queries see cluster totals. No
Prometheus/OpenCensus dependency — the head table IS the scrape target
(`export_prometheus()` renders the text exposition format).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_FLUSH_PERIOD_S = 2.0
_registry_lock = threading.Lock()
_registry: List["Metric"] = []
_pusher_started = False


def _tags_key(tags: Optional[Dict[str, str]], tag_keys: Sequence[str]
              ) -> Tuple[str, ...]:
    tags = tags or {}
    return tuple(str(tags.get(k, "")) for k in tag_keys)


class Metric:
    """Base: local aggregation + registration with the pusher."""

    kind = "counter"

    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        if not name:
            raise ValueError("metric name is required")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], float] = {}
        self._default_tags: Dict[str, str] = {}
        # registration LAST: the pusher snapshots registered metrics from
        # its own thread, so the instance must be fully initialized first
        # (subclasses with extra state register themselves instead)
        if type(self)._registers_in_base:
            self._register()

    _registers_in_base = True

    def _register(self):
        with _registry_lock:
            _registry.append(self)
        _ensure_pusher()

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _merged(self, tags):
        if self._default_tags:
            merged = dict(self._default_tags)
            merged.update(tags or {})
            return merged
        return tags

    # pusher protocol: drain (and reset deltas for counters)
    def _snapshot(self) -> List[tuple]:
        with self._lock:
            out = [(self.kind, self.name, self.description, self.tag_keys,
                    k, v) for k, v in self._values.items()]
            if self.kind == "counter":
                self._values.clear()  # counters push deltas
        return out


class Counter(Metric):
    """Monotonic count (ref: util/metrics.py Counter)."""

    kind = "counter"

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value < 0:
            raise ValueError("Counter.inc() value must be >= 0")
        key = _tags_key(self._merged(tags), self.tag_keys)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(Metric):
    """Last-written value (ref: util/metrics.py Gauge)."""

    kind = "gauge"

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = _tags_key(self._merged(tags), self.tag_keys)
        with self._lock:
            self._values[key] = float(value)


DEFAULT_BOUNDARIES = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                      2.5, 5.0, 10.0)


class Histogram(Metric):
    """Bucketed observations (ref: util/metrics.py Histogram)."""

    kind = "histogram"
    _registers_in_base = False  # registers below, after _hist exists

    def __init__(self, name: str, description: str = "",
                 boundaries: Sequence[float] = DEFAULT_BOUNDARIES,
                 tag_keys: Sequence[str] = ()):
        if not boundaries or list(boundaries) != sorted(boundaries):
            raise ValueError("boundaries must be sorted and non-empty")
        super().__init__(name, description, tag_keys)
        self.boundaries = tuple(boundaries)
        # per tag-key: [bucket counts..., +inf count, sum, n]
        self._hist: Dict[Tuple[str, ...], List[float]] = {}
        self._register()

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None):
        key = _tags_key(self._merged(tags), self.tag_keys)
        with self._lock:
            h = self._hist.get(key)
            if h is None:
                h = self._hist[key] = [0.0] * (len(self.boundaries) + 3)
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    h[i] += 1
                    break
            else:
                h[len(self.boundaries)] += 1
            h[-2] += value
            h[-1] += 1

    def _snapshot(self) -> List[tuple]:
        with self._lock:
            out = [("histogram", self.name, self.description,
                    (self.tag_keys, self.boundaries), k, list(v))
                   for k, v in self._hist.items()]
            self._hist.clear()  # histograms push deltas
        return out


# ---------------------------------------------------- object-plane metrics

_object_plane: Optional[Dict[str, Metric]] = None
_object_plane_lock = threading.Lock()

# pull latency spans shm memcpy (sub-ms) to multi-GiB cross-host (minutes)
PULL_LATENCY_BOUNDARIES = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                           10.0, 30.0, 60.0)


def object_plane_metrics() -> Dict[str, Metric]:
    """Lazily-created counters for the object data plane (reference:
    object_manager_stats / pull_manager metrics). ``source_count`` tags
    split single-source pulls from striped multi-source ones; the head's
    locality hit/miss counters live head-side and are surfaced through
    the ``object_plane`` state query instead (the head is the metrics
    aggregator, not a client)."""
    global _object_plane
    if _object_plane is None:
        with _object_plane_lock:
            if _object_plane is None:
                _object_plane = {
                    "pulls": Counter(
                        "object_plane.pulls",
                        "Completed object pulls, by concurrent source "
                        "count",
                        tag_keys=("source_count",)),
                    "pull_bytes": Counter(
                        "object_plane.pull_bytes",
                        "Bytes pulled from peer transfer servers",
                        tag_keys=("source_count",)),
                    "pull_latency": Histogram(
                        "object_plane.pull_latency_s",
                        "End-to-end object pull latency (seconds)",
                        boundaries=PULL_LATENCY_BOUNDARIES),
                    # serving side (TransferServer): role=root streams a
                    # sealed local copy, role=relay re-serves an
                    # in-progress pull chunk-by-chunk (cooperative
                    # broadcast tree)
                    "serves": Counter(
                        "object_plane.serves",
                        "OBJ_PULL ranges served to downstream pullers, "
                        "by source role",
                        tag_keys=("role",)),
                    "serve_bytes": Counter(
                        "object_plane.serve_bytes",
                        "Bytes streamed out of local arenas to "
                        "downstream pullers, by source role",
                        tag_keys=("role",)),
                }
    return _object_plane


# ------------------------------------------------------------ wire plane

_WIRE_DESCS = {
    "frames_sent": "Framed messages written to sockets",
    "sendmsg_calls": "Vectored write syscalls issued",
    "frames_coalesced": "Frames that shared a sendmsg with others",
    "coalesced_flushes": "Vectored writes carrying more than one frame",
    "zero_copy_bytes": "Raw-frame bytes sent with no intermediate copy",
    "bytes_sent": "Total bytes written to sockets",
    "task_done_batches": "TASK_DONE_BATCH completion frames sent",
    "task_done_batched": "Task completions that rode batched frames",
    "backpressure_hits": "Times a connection write queue hit its bound",
}
_wire_last: Dict[str, int] = {}
_wire_lock = threading.Lock()


def wire_metrics_snapshot() -> List[tuple]:
    """Delta rows for the process's wire fast-path counters
    (protocol.WIRE), in the pusher's batch schema — so `frames coalesced /
    batched completions / zero-copy bytes` aggregate cluster-wide next to
    the application metrics."""
    from .core.protocol import WIRE

    snap = WIRE.snapshot()
    out: List[tuple] = []
    with _wire_lock:
        for key, val in snap.items():
            delta = val - _wire_last.get(key, 0)
            if delta <= 0:
                continue
            _wire_last[key] = val
            out.append(("counter", f"wire.{key}", _WIRE_DESCS.get(key, ""),
                        (), (), float(delta)))
    return out


# ------------------------------------------------------------- transport


def _ensure_pusher():
    global _pusher_started
    with _registry_lock:
        if _pusher_started:
            return
        _pusher_started = True
    t = threading.Thread(target=_push_loop, daemon=True,
                         name="metrics-pusher")
    t.start()


def _push_loop():
    from .core import protocol as P
    from .core.context import get_context_if_exists

    while True:
        time.sleep(_FLUSH_PERIOD_S)
        ctx = get_context_if_exists()
        if ctx is None:
            continue
        with _registry_lock:
            metrics = list(_registry)
        batch: List[tuple] = []
        for m in metrics:
            batch.extend(m._snapshot())
        batch.extend(wire_metrics_snapshot())
        if not batch:
            continue
        try:
            ctx.head.send(P.METRICS_REPORT, batch)
        except Exception:  # noqa: BLE001 — shutdown race
            pass


def flush_now():
    """Push pending metric deltas immediately (tests / shutdown)."""
    from .core import protocol as P
    from .core.context import get_context_if_exists

    ctx = get_context_if_exists()
    if ctx is None:
        return
    with _registry_lock:
        metrics = list(_registry)
    batch: List[tuple] = []
    for m in metrics:
        batch.extend(m._snapshot())
    batch.extend(wire_metrics_snapshot())
    if batch:
        ctx.head.send(P.METRICS_REPORT, batch)


# ------------------------------------------------------------ query side


def metrics_summary() -> List[dict]:
    """Cluster-merged metric rows from the head."""
    from .core import protocol as P
    from .core.context import get_context

    (rows,) = get_context().head.call(P.STATE_QUERY, "metrics", 100000,
                                      timeout=30)
    return rows


def _escape_label_value(v) -> str:
    """Escape a label value per the Prometheus text exposition format
    spec: backslash, double-quote, and line-feed must be escaped (in
    that order — escaping the backslash first keeps it idempotent)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(tags: Dict[str, str]) -> str:
    return ",".join(f'{k}="{_escape_label_value(v)}"'
                    for k, v in tags.items())


def _escape_help(text: str) -> str:
    """HELP-line escaping per the exposition spec: backslash and
    line-feed only (double quotes are legal in HELP text)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


_NAME_SANITIZE = None  # compiled lazily (module import stays cheap)


def _metric_name(raw: str) -> str:
    """Sanitize to the spec's metric-name charset
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (dotted internal names like
    ``task.phase_ms`` become ``task_phase_ms``)."""
    global _NAME_SANITIZE
    if _NAME_SANITIZE is None:
        import re

        _NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")
    name = _NAME_SANITIZE.sub("_", raw)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def export_prometheus() -> str:
    """Render the head's metric table in Prometheus text exposition
    format (the reference exports via opencensus -> prometheus).

    Spec conformance (audited against the text-format spec, and parsed
    by a unit test): one ``# HELP``/``# TYPE`` header per metric family
    before any of its samples; histogram bucket counts are CUMULATIVE,
    always include the mandatory ``le="+Inf"`` bucket (whose value
    equals ``_count``), and every family ships its ``_sum``/``_count``
    series; label values escape backslash/quote/newline; metric names
    sanitize to the legal charset."""
    families: Dict[str, List[dict]] = {}
    order: List[str] = []
    for row in metrics_summary():
        name = _metric_name(row["name"])
        if name not in families:
            families[name] = []
            order.append(name)
        families[name].append(row)
    lines: List[str] = []
    for name in order:
        rows = families[name]
        kind = rows[0]["kind"]
        ptype = {"counter": "counter", "gauge": "gauge",
                 "histogram": "histogram"}.get(kind, "untyped")
        desc = next((r["description"] for r in rows
                     if r.get("description")), "")
        if desc:
            lines.append(f"# HELP {name} {_escape_help(desc)}")
        lines.append(f"# TYPE {name} {ptype}")
        for row in rows:
            tags = row["tags"]
            label = _label_str(tags)
            label = "{" + label + "}" if label else ""
            if row["kind"] == "histogram":
                h = row["value"]
                bounds = row["boundaries"]
                acc = 0.0
                for b, c in zip(list(bounds) + ["+Inf"], h[:-2]):
                    acc += c
                    ls = _label_str(dict(tags, le=str(b)))
                    lines.append(f"{name}_bucket{{{ls}}} {acc:g}")
                lines.append(f"{name}_sum{label} {h[-2]:g}")
                lines.append(f"{name}_count{label} {h[-1]:g}")
            else:
                lines.append(f"{name}{label} {row['value']:g}")
    return "\n".join(lines) + "\n"
