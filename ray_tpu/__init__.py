"""ray_tpu — a TPU-native distributed compute framework.

A ground-up redesign of the reference runtime (Ray ≈2.6, see SURVEY.md) for
TPU clusters: tasks/actors/objects with ownership-based futures and gang
placement groups on the control plane; JAX/XLA/pjit/Pallas as the tensor
plane, with ICI collectives compiled into SPMD programs instead of an
NCCL-style library.

Public core API mirrors the reference's (``ray.*``):
    init, shutdown, remote, get, put, wait, kill, cancel, get_actor,
    placement_group, nodes, cluster_resources, ...
Library layers live in submodules: ``ray_tpu.train``, ``ray_tpu.tune``,
``ray_tpu.data``, ``ray_tpu.serve``, ``ray_tpu.rllib``, ``ray_tpu.collective``,
``ray_tpu.parallel``, ``ray_tpu.models``, ``ray_tpu.ops``.
"""

from ray_tpu._version import __version__
from ray_tpu.core.api import (
    ActorClass,
    ActorHandle,
    NodeAffinitySchedulingStrategy,
    PlacementGroup,
    PlacementGroupSchedulingStrategy,
    available_resources,
    cancel,
    cluster_resources,
    drain_node,
    get,
    get_actor,
    get_tpu_ids,
    init,
    is_initialized,
    kill,
    nodes,
    object_locations,
    placement_group,
    placement_group_table,
    put,
    remote,
    remove_placement_group,
    shutdown,
    wait,
    warm_object,
)
from ray_tpu.core.exceptions import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    RayTaskError,
    TaskCancelledError,
    WorkerCrashedError,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.tracing import timeline

__all__ = [
    "__version__", "init", "shutdown", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "get_tpu_ids", "is_initialized",
    "ObjectRef",
    "ActorClass", "ActorHandle", "PlacementGroup", "placement_group",
    "remove_placement_group", "placement_group_table",
    "PlacementGroupSchedulingStrategy", "NodeAffinitySchedulingStrategy",
    "nodes", "cluster_resources", "available_resources", "timeline",
    "object_locations", "warm_object", "drain_node",
    "RayTaskError", "ActorDiedError", "ActorUnavailableError",
    "GetTimeoutError", "ObjectLostError", "TaskCancelledError",
    "WorkerCrashedError",
]
