"""Logical-axis sharding rules (GSPMD partitioning).

The reference has no analog — its tensor plane is NCCL DDP with replicated
params (ref: python/ray/train/torch/train_loop_utils.py:245 wraps the model in
DistributedDataParallel). Here parallelism is expressed by annotating every
array with *logical* axis names and translating those to mesh axes through a
rule table, then letting XLA insert the collectives (the GSPMD recipe).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicate).
# Batch shards over every data-like axis (incl. the DCN "slice" axis of
# hybrid multi-slice meshes — pure data parallelism is the only traffic
# slow enough for DCN); embed shards over fsdp (ZeRO-3); heads/mlp/vocab
# shard over tensor (Megatron); seq over sequence (ring CP). Axes absent
# from a given mesh are dropped at spec-build time.
DEFAULT_RULES: Tuple[Tuple[str, Any], ...] = (
    ("batch", ("slice", "data", "fsdp")),
    ("seq", "sequence"),
    ("embed", "fsdp"),
    ("heads", "tensor"),
    ("kv_heads", "tensor"),
    ("qkv_dim", None),
    ("mlp", "tensor"),
    ("vocab", "tensor"),
    ("experts", "expert"),
    ("layers", None),
    ("stages", "pipeline"),
)

LogicalAxes = Tuple[Optional[str], ...]


def rules_to_dict(rules=None) -> dict:
    return dict(rules if rules is not None else DEFAULT_RULES)


def logical_to_spec(logical: Sequence[Optional[str]], rules=None,
                    mesh_axes: Optional[Sequence[str]] = None) -> P:
    """Translate logical axis names into a PartitionSpec via the rule
    table. ``mesh_axes`` (when given) drops rule axes the target mesh
    doesn't have — e.g. "slice" on a single-slice mesh."""
    table = rules_to_dict(rules)
    out, used = [], set()
    for name in logical:
        mesh_ax = table.get(name) if name is not None else None
        if mesh_ax is not None and mesh_axes is not None:
            if isinstance(mesh_ax, tuple):
                mesh_ax = tuple(a for a in mesh_ax if a in mesh_axes) \
                    or None
            elif mesh_ax not in mesh_axes:
                mesh_ax = None
        # A mesh axis may appear only once per spec; later duplicates replicate.
        if mesh_ax is None:
            out.append(None)
        elif isinstance(mesh_ax, tuple):
            fresh = tuple(a for a in mesh_ax if a not in used)
            used.update(fresh)
            out.append(fresh if fresh else None)
        elif mesh_ax in used:
            out.append(None)
        else:
            used.add(mesh_ax)
            out.append(mesh_ax)
    return P(*out)


def logical_sharding(mesh: Mesh, logical: Sequence[Optional[str]],
                     rules=None) -> NamedSharding:
    return NamedSharding(mesh,
                         logical_to_spec(logical, rules, mesh.axis_names))


def tree_shardings(mesh: Mesh, logical_tree: Any, rules=None) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda ax: logical_sharding(mesh, ax, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x),
    )


def with_logical_constraint(x, logical: Sequence[Optional[str]], rules=None,
                            mesh: Optional[Mesh] = None):
    """`lax.with_sharding_constraint` in logical-axis vocabulary.

    No-op outside a mesh context so model code runs un-meshed (single chip,
    unit tests) unchanged. Pass ``mesh=`` explicitly (as the model code
    does); only `jax.set_mesh` / `jax.sharding.use_mesh` contexts are
    auto-detected — the legacy ``with mesh:`` context manager is not.
    """
    mesh = mesh or _current_mesh()
    if mesh is None or mesh.empty:
        return x
    return jax.lax.with_sharding_constraint(
        x, logical_sharding(mesh, logical, rules))


def _current_mesh() -> Optional[Mesh]:
    try:
        m = jax.sharding.get_abstract_mesh()  # jax>=0.4.35, set via set_mesh
        if m is not None and not m.empty:
            return m
    except Exception:
        pass
    return None


def shard_array(mesh: Mesh, x, logical, rules=None):
    """Device-put `x` with the sharding derived from its logical axes."""
    return jax.device_put(x, logical_sharding(mesh, logical, rules))
