"""Device-mesh construction for SPMD programs.

The reference scales tensor computation by wiring NCCL process groups between
actors (ref: python/ray/util/collective/collective.py:120,
python/ray/train/torch/config.py:70). On TPU the intra-slice network (ICI) is
programmed by the XLA compiler, so the framework's job reduces to *naming* the
parallelism axes and building a `jax.sharding.Mesh` whose layout maps them
onto the hardware torus. Everything downstream (Train, models, ops) speaks in
these axis names.

Axes (superset of anything the reference supports; ref has DP only in-tree,
TP/PP delegated to Alpa — SURVEY.md §2.3):
    data      — pure data parallelism (params replicated)
    fsdp      — data parallelism with sharded params/opt state (ZeRO-3)
    tensor    — Megatron-style tensor parallelism (heads/mlp sharded)
    sequence  — context parallelism (ring attention over ICI)
    expert    — MoE expert parallelism
    pipeline  — pipeline stages (shard_map + ppermute microbatching)
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order: outermost (DCN-friendly, infrequent comm) first,
# innermost (ICI-hot, per-layer comm) last — matches how contiguous device
# order maps onto the torus so tensor/sequence collectives ride nearest
# neighbours. "slice" (multi-slice DCN data parallelism — gradient
# all-reduce across pod slices, scaling-book hybrid-mesh recipe) only
# appears when MeshSpec(slices=) > 1.
MESH_AXES: Tuple[str, ...] = (
    "data", "fsdp", "expert", "pipeline", "sequence", "tensor")
DCN_AXIS = "slice"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape; -1 in at most one axis means "fill the rest".

    Example::

        MeshSpec(fsdp=-1, tensor=4).build()   # on 32 chips -> (1,8,1,1,1,4)

    ``slices > 1`` builds a hybrid ICI x DCN mesh: the ICI axes above
    describe ONE pod slice, and a leading "slice" axis spans slices over
    DCN (greenfield per SURVEY §2.3 — the reference has no multi-slice
    story). Devices are grouped by their ``slice_index`` attribute when
    the backend reports one (real multi-slice TPU), else contiguously
    (virtual/CPU simulation)::

        MeshSpec(fsdp=-1, slices=2).build()  # 8 devs -> slice=2, fsdp=4
    """

    data: int = 1
    fsdp: int = -1
    expert: int = 1
    pipeline: int = 1
    sequence: int = 1
    tensor: int = 1
    slices: int = 1

    def sizes(self, n_devices: int) -> Tuple[int, ...]:
        """Per-slice ICI axis sizes over n_devices // slices."""
        if self.slices < 1:
            raise ValueError("slices must be >= 1")
        if n_devices % self.slices:
            raise ValueError(
                f"{n_devices} devices not divisible into {self.slices} "
                f"slices")
        per_slice = n_devices // self.slices
        raw = [self.data, self.fsdp, self.expert, self.pipeline,
               self.sequence, self.tensor]
        fills = [i for i, v in enumerate(raw) if v == -1]
        if len(fills) > 1:
            raise ValueError("at most one mesh axis may be -1 (fill)")
        fixed = math.prod(v for v in raw if v != -1)
        if fills:
            if per_slice % fixed:
                raise ValueError(
                    f"{per_slice} per-slice devices not divisible by "
                    f"fixed axes {fixed}")
            raw[fills[0]] = per_slice // fixed
        elif fixed != per_slice:
            raise ValueError(
                f"mesh {raw} needs {fixed} devices/slice, have {per_slice}")
        return tuple(raw)

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        shape = self.sizes(len(devices))
        if self.slices == 1:
            arr = np.asarray(devices).reshape(shape)
            return Mesh(arr, MESH_AXES)
        # hybrid ICI x DCN: group devices by hardware slice so the DCN
        # axis really crosses slices and every ICI axis stays intra-slice
        per = len(devices) // self.slices
        by_slice = {}
        if all(getattr(d, "slice_index", None) is not None
               for d in devices):
            for d in devices:
                by_slice.setdefault(d.slice_index, []).append(d)
            if len(by_slice) != self.slices or \
                    any(len(v) != per for v in by_slice.values()):
                raise ValueError(
                    f"hardware reports {len(by_slice)} slices with sizes "
                    f"{[len(v) for v in by_slice.values()]}; "
                    f"spec wants {self.slices} x {per}")
            groups = [by_slice[k] for k in sorted(by_slice)]
        else:  # simulation: contiguous split
            groups = [devices[i * per:(i + 1) * per]
                      for i in range(self.slices)]
        arr = np.asarray(groups).reshape((self.slices,) + shape)
        return Mesh(arr, (DCN_AXIS,) + MESH_AXES)


def make_mesh(n_devices: Optional[int] = None, **axis_sizes) -> Mesh:
    """Shorthand: ``make_mesh(fsdp=8)`` or ``make_mesh(8, tensor=2)``.

    With all axes fixed (no -1) and fewer requested than available, the
    leading devices are used — convenient for tests on a virtual mesh.
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    spec = MeshSpec(**axis_sizes) if axis_sizes else MeshSpec()
    sizes = [spec.data, spec.fsdp, spec.expert, spec.pipeline, spec.sequence,
             spec.tensor]
    if -1 not in sizes:
        want = math.prod(sizes)
        if want <= len(devices):
            devices = devices[:want]
    return spec.build(devices)


def single_device_mesh() -> Mesh:
    return MeshSpec(fsdp=1).build(jax.devices()[:1])
