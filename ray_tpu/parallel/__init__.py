"""SPMD parallelism layer: meshes, logical shardings, ring collectives.

TPU-native replacement for the reference's NCCL plumbing (SURVEY.md §2.3):
within a slice, collectives are compiled by XLA over ICI; this package only
names the axes, builds meshes, and provides the sharded-attention primitives.
"""

from ray_tpu.parallel.mesh import MESH_AXES, MeshSpec, make_mesh, single_device_mesh
from ray_tpu.parallel.ring import reference_attention, ring_attention
from ray_tpu.parallel.sharding import (
    DEFAULT_RULES,
    logical_sharding,
    logical_to_spec,
    shard_array,
    tree_shardings,
    with_logical_constraint,
)

__all__ = [
    "MESH_AXES", "MeshSpec", "make_mesh", "single_device_mesh",
    "DEFAULT_RULES", "logical_to_spec", "logical_sharding", "tree_shardings",
    "with_logical_constraint", "shard_array",
    "ring_attention", "reference_attention",
]
