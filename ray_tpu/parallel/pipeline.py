"""Pipeline parallelism: GPipe-style microbatched execution of a stacked
layer scan over the ``pipeline`` mesh axis.

The reference has no in-tree pipeline parallelism — it delegates to
Alpa-on-Ray (release/alpa_tests/train_opt_2_7b_minimum.py). This is the
TPU-native design (SURVEY.md §2.3): the transformer already stores its L
layers *stacked* on a leading axis and runs them with one `lax.scan`, so
pipelining is a re-partition of exactly that structure:

  - The stack [L, ...] becomes [S, L/S, ...] with the leading (stages) axis
    sharded over the ``pipeline`` mesh axis — each device group holds one
    stage's contiguous block of layers.
  - The batch is split into M microbatches. A `jax.shard_map` manual only
    over the ``pipeline`` axis (every other mesh axis stays auto/GSPMD, so
    tensor/fsdp/sequence sharding inside the block is untouched) runs the
    classic M+S-1-tick schedule: each tick every stage runs its layer block
    on its current activation and hands the result to the next stage with a
    single `ppermute` hop over ICI.
  - The whole schedule is a `lax.scan` over ticks, so `jax.grad` through it
    yields the reverse pipeline automatically — no hand-written backward
    schedule.

Bubble fraction is (S-1)/(M+S-1); pick num_microbatches >= 4*S to amortize.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel.ring import _CHECK_KW, _shard_map

PyTree = Any


def pipeline_axis_size(mesh: Optional[Mesh]) -> int:
    if mesh is None:
        return 1
    return int(mesh.shape.get("pipeline", 1))


def pipeline_scan(body: Callable[[jax.Array, PyTree], Any],
                  x: jax.Array,
                  stacked_params: PyTree,
                  mesh: Mesh,
                  num_microbatches: Optional[int] = None) -> jax.Array:
    """Run ``lax.scan(body, x, stacked_params)`` pipelined over stages.

    ``body(activation, layer_params) -> (activation, _)`` is the SAME block
    function the un-pipelined scan uses. ``stacked_params`` leaves carry a
    leading layer axis of size L; ``x`` is [B, ...] activations. Returns the
    final activations [B, ...], numerically identical to the plain scan
    (tests/test_parallel.py parity test).
    """
    S = pipeline_axis_size(mesh)
    if S <= 1:
        out, _ = jax.lax.scan(lambda c, lp: body(c, lp), x, stacked_params)
        return out

    L = jax.tree.leaves(stacked_params)[0].shape[0]
    if L % S:
        raise ValueError(f"n_layers {L} not divisible by pipeline size {S}")
    M = num_microbatches or 2 * S
    B = x.shape[0]
    if B % M:
        # fall back to the largest microbatch count that divides B
        M = next((m for m in range(min(M, B), 0, -1) if B % m == 0), 1)

    staged = jax.tree.map(
        lambda p: p.reshape((S, L // S) + p.shape[1:]), stacked_params)
    mb = x.reshape((M, B // M) + x.shape[1:])

    def inner(staged_local: PyTree, mb: jax.Array) -> jax.Array:
        # staged_local leaves: [1, L/S, ...] — this device group's stage.
        stage_params = jax.tree.map(lambda p: p[0], staged_local)
        p_idx = jax.lax.axis_index("pipeline")

        def run_stage(act):
            out, _ = jax.lax.scan(lambda c, lp: body(c, lp), act,
                                  stage_params)
            return out

        buf = jnp.zeros(mb.shape[1:], mb.dtype)
        outs = jnp.zeros(mb.shape, mb.dtype)

        def tick(carry, t):
            buf, outs = carry
            inp = jax.lax.dynamic_index_in_dim(
                mb, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            act = jnp.where((p_idx == 0) & (t < M), inp, buf)
            y = run_stage(act)
            emit = t - (S - 1)
            outs = jax.lax.cond(
                (p_idx == S - 1) & (emit >= 0),
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(emit, 0, M - 1), 0),
                lambda o: o, outs)
            nxt = jax.lax.ppermute(
                y, "pipeline", [(i, (i + 1) % S) for i in range(S)])
            return (nxt, outs), None

        (_, outs), _ = jax.lax.scan(tick, (buf, outs),
                                    jnp.arange(M + S - 1))
        # Only the last stage holds real outputs; psum broadcasts them to
        # every pipeline rank (one activation-sized all-reduce per step).
        outs = jax.lax.psum(
            jnp.where(p_idx == S - 1, outs, jnp.zeros_like(outs)),
            "pipeline")
        return outs

    out = _shard_map(
        inner, mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P("pipeline"), staged), P()),
        out_specs=P(),
        axis_names={"pipeline"}, **{_CHECK_KW: False})(staged, mb)
    return out.reshape((B,) + x.shape[1:])
