"""Ring attention: context parallelism over the ICI torus.

Absent from the reference (SURVEY.md §5 — no ring attention, Ulysses, or
sequence parallelism in-tree; its closest artifact is raw NCCL send/recv at
python/ray/util/collective/collective_group/nccl_collective_group.py:350).
Designed fresh for TPU: the sequence dimension is sharded over the `sequence`
mesh axis, K/V blocks rotate around the ring with `jax.lax.ppermute` (nearest
neighbour over ICI), and each step folds one block into a numerically-stable
online-softmax accumulator — so attention over a sequence of length S costs
each chip O(S/n * S) FLOPs and S/n-sized KV traffic, fully overlapped by XLA
with the matmuls.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
import inspect

try:
    from jax import shard_map as _shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

# The replication-check kwarg was renamed check_rep -> check_vma in jax 0.8.
_CHECK_KW = ("check_vma" if "check_vma" in
             inspect.signature(_shard_map).parameters else "check_rep")


def shard_map(f, *, mesh, in_specs, out_specs):
    try:
        # Nested use only (e.g. ring attention inside a pipeline stage
        # body): when the ambient mesh has MANUAL axes we are inside an
        # enclosing shard_map, and jax requires the inner shard_map to see
        # that context mesh, not the original concrete one. A plain
        # `jax.set_mesh` context (all-auto) must NOT override an explicit
        # mesh argument.
        am = jax.sharding.get_abstract_mesh()
        if am is not None and not am.empty and any(
                t == jax.sharding.AxisType.Manual for t in am.axis_types):
            mesh = am
    except Exception:
        pass
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_CHECK_KW: False})

_NEG_INF = -1e30


def _block_attend(q, k, v, m, l, o, mask, scale):
    """Fold one K/V block into the (m, l, o) online-softmax state.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]; m, l: [B, H, Tq]; o: [B, Tq, H, D].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask, s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    # Rows that have seen nothing yet (m == -inf) contribute zero, not NaN.
    p = jnp.where((s <= _NEG_INF / 2), 0.0, p)
    corr = jnp.exp(m - m_new)
    corr = jnp.where(m <= _NEG_INF / 2, 0.0, corr)
    l_new = l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          scale: float):
    """Body run per-device inside shard_map. Shapes are per-shard."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    qf = q.astype(jnp.float32)

    m0 = jnp.full((b, h, t_q), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_q), jnp.float32)
    o0 = jnp.zeros((b, t_q, h, d), jnp.float32)

    q_pos = idx * t_q + jnp.arange(t_q)

    def step(s, carry):
        k_blk, v_blk, m, l, o = carry
        src = (idx - s) % n  # which global chunk this block came from
        if causal:
            k_pos = src * t_k + jnp.arange(t_k)
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((t_q, t_k), bool)
        mask = mask[None, None, :, :]
        m, l, o = _block_attend(qf, k_blk, v_blk, m, l, o, mask, scale)
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m, l, o

    _, _, m, l, o = jax.lax.fori_loop(0, n, step, (k, v, m0, l0, o0))
    l = jnp.maximum(l, 1e-30)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, *, axis_name: str = "sequence",
                   causal: bool = True, scale: Optional[float] = None,
                   batch_axes=None, head_axis: str = "tensor"):
    """Causal self-attention with the sequence dim sharded over `axis_name`.

    q, k, v: [batch, seq, heads, head_dim] (seq globally sharded).
    Degenerates to plain (still flash-style) attention when the sequence
    axis has size 1, so callers can use it unconditionally.

    ``batch_axes`` defaults to every data-like axis PRESENT in the mesh
    (slice/data/fsdp) — a hybrid multi-slice mesh must keep the batch
    sharded over DCN here, or shard_map would silently all-gather q/k/v
    across slices.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    if batch_axes is None:
        batch_axes = tuple(a for a in ("slice", "data", "fsdp")
                           if a in mesh.axis_names)
    spec = P(batch_axes, axis_name, head_axis, None)
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def reference_attention(q, k, v, *, causal: bool = True,
                        scale: Optional[float] = None):
    """Unsharded flash-free reference for tests: [B, T, H, D] -> [B, T, H, D]."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = jnp.arange(t_q)[:, None] >= jnp.arange(t_k)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(
        q.dtype)
