"""Job submission: run driver scripts on the cluster with tracked status.

Ref parity: ray job submission (python/ray/dashboard/modules/job/
job_manager.py:517 JobManager.submit_job — entrypoint subprocess with
RAY_ADDRESS injected, status machine PENDING -> RUNNING -> SUCCEEDED/
FAILED/STOPPED, logs captured per job; python/ray/job_submission/
JobSubmissionClient). Re-design: the manager is a named detached actor on
the cluster (so remote clients reach it through the normal actor path and
job state survives the submitting client), spawning entrypoint
subprocesses next to the head with RAY_TPU_ADDRESS injected.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

JOB_MANAGER_NAME = "_ray_tpu_job_manager"

PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"


class _JobManager:
    """Named actor owning job subprocesses + their status table."""

    def __init__(self, head_addr: str, log_dir: str):
        self._head_addr = head_addr
        self._log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._jobs: Dict[str, dict] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def submit(self, entrypoint: str, submission_id: Optional[str],
               env_vars: Optional[Dict[str, str]],
               metadata: Optional[Dict[str, str]]) -> str:
        job_id = submission_id or f"raytpu-job-{uuid.uuid4().hex[:10]}"
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id!r} already exists")
            self._jobs[job_id] = {
                "job_id": job_id, "entrypoint": entrypoint,
                "status": PENDING, "submitted_at": time.time(),
                "started_at": None, "ended_at": None,
                "metadata": metadata or {}, "message": "",
            }
        env = dict(os.environ)
        env.update(env_vars or {})
        # the entrypoint attaches to THIS cluster (ref: RAY_ADDRESS)
        env["RAY_TPU_ADDRESS"] = self._head_addr
        log_path = os.path.join(self._log_dir, f"{job_id}.log")
        try:
            with open(log_path, "ab") as logf:
                proc = subprocess.Popen(
                    entrypoint, shell=True, env=env, stdout=logf,
                    stderr=subprocess.STDOUT, start_new_session=True)
        except OSError as e:
            with self._lock:
                self._jobs[job_id].update(status=FAILED, message=repr(e),
                                          ended_at=time.time())
            self._emit_event("ERROR", "job_finished",
                             f"job {job_id} failed to launch: {e!r}",
                             job_id)
            return job_id
        with self._lock:
            self._procs[job_id] = proc
            self._jobs[job_id].update(status=RUNNING,
                                      started_at=time.time())
        self._emit_event("INFO", "job_started",
                         f"job {job_id} started: {entrypoint}", job_id)
        threading.Thread(target=self._wait, args=(job_id, proc),
                         daemon=True).start()
        return job_id

    @staticmethod
    def _emit_event(severity: str, event_type: str, message: str,
                    job_id: str):
        """Job transitions land in the cluster event log (reference: the
        GCS job table feeding `ray list cluster-events`)."""
        from ray_tpu.core import events as _ev

        _ev.emit_cluster_event(severity, "jobs", event_type, message,
                               entity_id=job_id)

    def _wait(self, job_id: str, proc: subprocess.Popen):
        rc = proc.wait()
        with self._lock:
            info = self._jobs.get(job_id)
            if info is None or info["status"] == STOPPED:
                return
            info["status"] = SUCCEEDED if rc == 0 else FAILED
            info["message"] = f"exit code {rc}"
            info["ended_at"] = time.time()
        self._emit_event("INFO" if rc == 0 else "ERROR", "job_finished",
                         f"job {job_id} finished: exit code {rc}", job_id)

    def status(self, job_id: str) -> dict:
        with self._lock:
            info = self._jobs.get(job_id)
            if info is None:
                raise ValueError(f"no such job: {job_id}")
            return dict(info)

    def list(self) -> List[dict]:
        with self._lock:
            return [dict(v) for v in self._jobs.values()]

    def logs(self, job_id: str, offset: int = 0) -> str:
        """Log text from BYTE ``offset`` (tailing clients track bytes so
        a chatty multi-hour job is not re-read every poll)."""
        return self.logs_from(job_id, offset)[0]

    def logs_from(self, job_id: str, offset: int = 0):
        """-> (text, next_byte_offset) for exact tailing. Reads binary (a
        text-mode seek would land mid-character) and holds back an
        incomplete trailing UTF-8 sequence so a multi-byte character
        split across a poll boundary is never emitted as U+FFFD."""
        self.status(job_id)  # raises on unknown id
        path = os.path.join(self._log_dir, f"{job_id}.log")
        try:
            with open(path, "rb") as f:
                if offset:
                    f.seek(offset)
                blob = f.read()
        except OSError:
            return "", offset
        # trim an incomplete trailing multi-byte sequence (<= 3 bytes)
        keep = len(blob)
        for back in range(1, min(4, keep + 1)):
            b = blob[keep - back]
            if b < 0x80:          # ASCII: sequence complete
                break
            if b >= 0xC0:         # start byte: complete iff its length fits
                need = 2 + (b >= 0xE0) + (b >= 0xF0)
                if back < need:
                    keep -= back  # truncated sequence: hold it back
                break
        blob = blob[:keep]
        return blob.decode("utf-8", errors="replace"), offset + keep

    def stop(self, job_id: str) -> bool:
        with self._lock:
            info = self._jobs.get(job_id)
            proc = self._procs.get(job_id)
            if info is None:
                raise ValueError(f"no such job: {job_id}")
            if info["status"] != RUNNING or proc is None:
                return False
            info["status"] = STOPPED
            info["ended_at"] = time.time()
        try:
            os.killpg(os.getpgid(proc.pid), 15)  # the job's process group
        except (ProcessLookupError, PermissionError):
            pass
        return True

    def delete(self, job_id: str) -> bool:
        with self._lock:
            info = self._jobs.get(job_id)
            if info is None:
                return False
            if info["status"] == RUNNING:
                raise RuntimeError("stop the job before deleting it")
            self._jobs.pop(job_id, None)
            self._procs.pop(job_id, None)
        return True


class JobSubmissionClient:
    """Ref parity: ray.job_submission.JobSubmissionClient (HTTP in the
    reference; the named-actor path here serves the same surface)."""

    def __init__(self, address: Optional[str] = None):
        if address and not ray_tpu.is_initialized():
            ray_tpu.init(address=address, log_to_driver=False)
        elif not ray_tpu.is_initialized():
            ray_tpu.init()
        self._manager = _get_or_create_manager()

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[dict] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        env_vars = (runtime_env or {}).get("env_vars")
        return ray_tpu.get(self._manager.submit.remote(
            entrypoint, submission_id, env_vars, metadata), timeout=60)

    def get_job_status(self, job_id: str) -> str:
        return ray_tpu.get(self._manager.status.remote(job_id),
                           timeout=60)["status"]

    def get_job_info(self, job_id: str) -> dict:
        return ray_tpu.get(self._manager.status.remote(job_id), timeout=60)

    def get_job_logs(self, job_id: str, offset: int = 0) -> str:
        return ray_tpu.get(self._manager.logs.remote(job_id, offset),
                           timeout=60)

    def list_jobs(self) -> List[dict]:
        return ray_tpu.get(self._manager.list.remote(), timeout=60)

    def stop_job(self, job_id: str) -> bool:
        return ray_tpu.get(self._manager.stop.remote(job_id), timeout=60)

    def delete_job(self, job_id: str) -> bool:
        return ray_tpu.get(self._manager.delete.remote(job_id), timeout=60)

    def tail_job_logs(self, job_id: str, poll_s: float = 0.5):
        """Generator of new log text until the job finishes. Each poll
        ships only the unseen suffix; offsets are BYTES (len(str) would
        drift behind on multi-byte UTF-8 and re-yield garbled text)."""
        seen = 0
        while True:
            new, seen = ray_tpu.get(
                self._manager.logs_from.remote(job_id, seen), timeout=60)
            if new:
                yield new
            if self.get_job_status(job_id) not in (PENDING, RUNNING):
                new, seen = ray_tpu.get(
                    self._manager.logs_from.remote(job_id, seen),
                    timeout=60)
                if new:
                    yield new
                return
            time.sleep(poll_s)


def _get_or_create_manager():
    from ray_tpu.core.context import get_context

    try:
        return ray_tpu.get_actor(JOB_MANAGER_NAME)
    except Exception:  # noqa: BLE001 — not created yet
        ctx = get_context()
        cls = ray_tpu.remote(_JobManager)
        try:
            return cls.options(name=JOB_MANAGER_NAME).remote(
                ctx.head_addr, os.path.join(ctx.session_dir, "job_logs"))
        except Exception:  # noqa: BLE001 — lost the creation race
            return ray_tpu.get_actor(JOB_MANAGER_NAME)
