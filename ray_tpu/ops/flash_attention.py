"""Pallas TPU flash attention (fwd + bwd), with interpret mode off-TPU.

The reference has no fused attention of its own (torch SDPA/NCCL territory).
This kernel is the Pallas piece of the attention stack (SURVEY.md §7.6):
  - forward: grid over (batch*heads, q-blocks); each step streams its q block
    against K/V resident in VMEM, computing a numerically-stable softmax row
    and the logsumexp residual for the backward pass.
  - backward: FlashAttention-2 style two kernels — dq over q-blocks, dk/dv
    over k-blocks — recomputing probabilities from the saved logsumexp, so
    no O(T^2) tensor is ever materialized in HBM.
Layout is [batch, seq, heads, head_dim] at the API, transposed to
[batch*heads, seq, head_dim] for the MXU-friendly inner matmuls.
VMEM budget: K/V for one (batch, head) stay resident — fine through T≈16k at
head_dim 128; beyond that, fall back to ring attention across chips.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() == "cpu"


# ---- forward ---------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q):
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)            # [bq, d]
    k = k_ref[0].astype(jnp.float32)            # [T, d]
    v = v_ref[0].astype(jnp.float32)            # [T, d]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        t_k = k.shape[0]
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    m = jnp.max(s, axis=1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=1, keepdims=True)
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32) / l
    o_ref[0] = o.astype(o_ref.dtype)
    lse_ref[0, 0] = (m + jnp.log(l))[:, 0]


def _fwd(q3, k3, v3, *, scale, causal, block_q):
    bh, t, d = q3.shape
    t_k = k3.shape[1]
    nq = pl.cdiv(t, block_q)
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             block_q=block_q)
    o, lse = pl.pallas_call(
        kern,
        grid=(bh, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t_k, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(q3, k3, v3)
    return o, lse


# ---- backward --------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_q):
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    p = jnp.exp(s - lse[:, None])
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None])
    dq = jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32) * scale
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_k):
    ik = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)             # [T, d]
    k = k_ref[0].astype(jnp.float32)             # [bk, d]
    v = v_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)           # [T, d]
    lse = lse_ref[0, 0]                          # [T]
    delta = delta_ref[0, 0]                      # [T]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
    p = jnp.exp(s - lse[:, None])                # [T, bk]
    dv = jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta[:, None])               # [T, bk]
    dk = jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32) * scale
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, res, do3):
    q3, k3, v3, o3, lse = res
    bh, t, d = q3.shape
    t_k = k3.shape[1]
    delta = jnp.sum(do3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1, keepdims=False)[:, None, :]  # [bh, 1, t]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q),
        grid=(bh, pl.cdiv(t, block_q)),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t_k, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q3.dtype),
        interpret=_use_interpret(),
    )(q3, k3, v3, do3, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_k=block_k),
        grid=(bh, pl.cdiv(t_k, block_k)),
        in_specs=[
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, t), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_k, d), k3.dtype),
            jax.ShapeDtypeStruct((bh, t_k, d), v3.dtype),
        ],
        interpret=_use_interpret(),
    )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash3(q3, k3, v3, scale, causal, block_q, block_k):
    o, _ = _fwd(q3, k3, v3, scale=scale, causal=causal, block_q=block_q)
    return o


def _flash3_fwd(q3, k3, v3, scale, causal, block_q, block_k):
    o, lse = _fwd(q3, k3, v3, scale=scale, causal=causal, block_q=block_q)
    return o, (q3, k3, v3, o, lse)


def _flash3_bwd(scale, causal, block_q, block_k, res, do3):
    return _bwd(scale, causal, block_q, block_k, res, do3)


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    scale: Optional[float] = None, block_q: int = 256,
                    block_k: int = 256):
    """Fused causal attention. q, k, v: [B, T, H, D] -> [B, T, H, D]."""
    b, t, h, d = q.shape
    t_k = k.shape[1]
    scale = scale if scale is not None else d ** -0.5
    block_q = min(block_q, t)
    block_k = min(block_k, t_k)

    def to3(x):
        return x.transpose(0, 2, 1, 3).reshape(b * x.shape[2], x.shape[1], d)

    o3 = _flash3(to3(q), to3(k), to3(v), scale, causal, block_q, block_k)
    return o3.reshape(b, h, t, d).transpose(0, 2, 1, 3)
