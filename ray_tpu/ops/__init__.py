"""TPU kernels (Pallas) for the hot ops XLA doesn't fuse well enough.

Runs in Pallas interpret mode on CPU so the whole stack stays testable on the
virtual device mesh (SURVEY.md §4 strategy).
"""

from ray_tpu.ops.flash_attention import flash_attention

__all__ = ["flash_attention"]
