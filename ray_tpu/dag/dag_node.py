"""DAG node types and the bottom-up executor.

Ref parity: python/ray/dag/dag_node.py:23 (DAGNode: _bound_args,
_apply_recursive, execute), function_node.py (FunctionNode ->
fn.remote), class_node.py (ClassNode -> Class.remote, ClassMethodNode ->
handle.method.remote), input_node.py (InputNode placeholder bound at
execute time). Execution submits every node as a normal task/actor call
with upstream ObjectRefs as arguments, so the cluster scheduler
parallelizes independent branches for free.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple


class DAGNode:
    """A lazily-bound node; subclasses define how to submit themselves."""

    def __init__(self, args: Tuple, kwargs: Dict[str, Any]):
        self._bound_args = tuple(args)
        self._bound_kwargs = dict(kwargs)

    # -------------------------------------------------------- traversal

    def _resolve_args(self, cache, input_value):
        args = [a.
                _to_ref(cache, input_value) if isinstance(a, DAGNode) else a
                for a in self._bound_args]
        kwargs = {k: (v._to_ref(cache, input_value)
                      if isinstance(v, DAGNode) else v)
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _to_ref(self, cache: dict, input_value):
        """Submit this node (memoized — diamond deps execute once)."""
        if id(self) not in cache:
            cache[id(self)] = self._submit(cache, input_value)
        return cache[id(self)]

    def _submit(self, cache, input_value):
        raise NotImplementedError

    # -------------------------------------------------------- execution

    def execute(self, *input_values) -> Any:
        """Walk the graph, submit everything, return the root's ObjectRef
        (or actor handle for a ClassNode root)."""
        input_value = input_values[0] if input_values else None
        return self._to_ref({}, input_value)


class InputNode(DAGNode):
    """Placeholder bound to ``dag.execute(value)``'s argument
    (python/ray/dag/input_node.py). Usable as a context manager for
    parity with the reference's ``with InputNode() as inp:`` style."""

    def __init__(self):
        super().__init__((), {})

    def _to_ref(self, cache, input_value):
        return input_value

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class FunctionNode(DAGNode):
    """``remote_fn.bind(*args)`` — executes as ``remote_fn.remote(...)``."""

    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _submit(self, cache, input_value):
        args, kwargs = self._resolve_args(cache, input_value)
        return self._remote_fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """``ActorClass.bind(*ctor_args)`` — instantiated once per execute;
    method nodes hang off it."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls

    def _submit(self, cache, input_value):
        args, kwargs = self._resolve_args(cache, input_value)
        return self._actor_cls.remote(*args, **kwargs)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodStub(self, name)


class _MethodStub:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    """``class_node.method.bind(*args)`` — calls the method on the shared
    actor instance created by its ClassNode."""

    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method = method

    def _submit(self, cache, input_value):
        handle = self._class_node._to_ref(cache, input_value)
        args, kwargs = self._resolve_args(cache, input_value)
        return getattr(handle, self._method).remote(*args, **kwargs)
