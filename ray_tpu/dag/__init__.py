"""General DAG IR: build lazy task/actor graphs, execute them later.

Ref parity: ray.dag (python/ray/dag/dag_node.py:23 DAGNode,
function_node.py, class_node.py, input_node.py): ``fn.bind(...)`` builds a
node instead of executing; ``dag.execute(input)`` walks the graph
submitting tasks bottom-up. Serve's deployment graphs and Workflows both
compile through this IR (as in the reference).
"""

from ray_tpu.dag.dag_node import (ClassMethodNode, ClassNode, DAGNode,
                                  FunctionNode, InputNode)

__all__ = ["DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode",
           "InputNode"]
