"""Trainable: the unit a trial actor runs.

Ref analogs: python/ray/tune/trainable/trainable.py:75 (class API —
setup/step/save_checkpoint/load_checkpoint) and
trainable/function_trainable.py (function API: the user function runs on
its own thread and emits results via ``tune.report``); re-designed so both
share one ``train()`` contract the controller polls remotely.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Dict, Optional

RESULT_DONE = "__trial_done__"


class Trainable:
    """Class API. Subclass and override setup/step (+ optional
    save_checkpoint/load_checkpoint for PBT/pause support)."""

    def __init__(self, config: Dict[str, Any] = None):
        self.config = dict(config or {})
        self.iteration = 0
        self.setup(self.config)

    # -- override points --

    def setup(self, config: Dict[str, Any]):
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self) -> Any:
        """Return a picklable checkpoint payload."""
        return None

    def load_checkpoint(self, checkpoint: Any):
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        """Hot-swap config (PBT exploit). Return True if handled in place."""
        return False

    def cleanup(self):
        pass

    # -- controller-facing (invoked as actor methods) --

    def train(self) -> Dict[str, Any]:
        result = self.step()
        if not isinstance(result, dict):
            raise TypeError("step() must return a metrics dict")
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        return result

    def save(self) -> Any:
        return {"iteration": self.iteration,
                "payload": self.save_checkpoint()}

    def restore(self, checkpoint: Any):
        self.iteration = checkpoint.get("iteration", 0)
        self.load_checkpoint(checkpoint.get("payload"))

    def reset(self, new_config: Dict[str, Any]) -> bool:
        ok = self.reset_config(new_config)
        if ok:
            self.config = dict(new_config)
        return ok

    def stop(self):
        self.cleanup()


class FunctionTrainable(Trainable):
    """Wraps ``def train_fn(config)`` using ``ray_tpu.tune.report(...)``.

    The function runs on a daemon thread; ``train()`` blocks on its next
    report. A checkpoint passed to report() is retained for save().
    """

    _fn: Optional[Callable] = None  # bound by wrap()

    def setup(self, config):
        self._queue: "queue.Queue" = queue.Queue(maxsize=16)
        self._ckpt = config.pop("__checkpoint__", None)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tune_fn")
        self._started = False
        self._done = False

    def _run(self):
        from . import session as tune_session

        tune_session._set_reporter(self._report, self._ckpt)
        try:
            out = type(self)._fn(self.config)
            self._queue.put((RESULT_DONE, out if isinstance(out, dict)
                             else {}))
        except BaseException as e:  # noqa: BLE001 — surfaced via train()
            self._queue.put(("__error__", e))

    def _report(self, metrics: Dict[str, Any], checkpoint=None):
        if checkpoint is not None:
            self._latest_ckpt = checkpoint
        self._last_metrics = dict(metrics)
        self._queue.put(("report", dict(metrics)))

    def step(self):
        if not self._started:
            self._thread.start()
            self._started = True
        if self._done:
            return {"done": True}
        kind, payload = self._queue.get()
        if kind == "__error__":
            raise payload
        if kind == RESULT_DONE:
            self._done = True
            # the function finished: surface the last reported metrics so
            # they survive as the trial's final result
            payload = {**getattr(self, "_last_metrics", {}), **payload,
                       "done": True}
        return payload

    def train(self):
        result = self.step()
        if result.get("done"):
            # the terminal pump is not a training iteration
            result.setdefault("training_iteration", self.iteration)
            return result
        self.iteration += 1
        result.setdefault("training_iteration", self.iteration)
        return result

    def save_checkpoint(self):
        return getattr(self, "_latest_ckpt", None)

    def load_checkpoint(self, checkpoint):
        self._ckpt = checkpoint

    @classmethod
    def wrap(cls, fn: Callable) -> type:
        return type(f"func_{getattr(fn, '__name__', 'trainable')}",
                    (cls,), {"_fn": staticmethod(fn)})


def with_parameters(trainable, **params):
    """Bind large constant objects outside the config dict
    (ref: tune/trainable/util.py with_parameters)."""
    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        class _Bound(trainable):  # type: ignore[misc, valid-type]
            def setup(self, config):
                cfg = dict(config)
                cfg.update(params)
                super().setup(cfg)

        _Bound.__name__ = trainable.__name__
        return _Bound

    def fn(config):
        return trainable(config, **params)

    fn.__name__ = getattr(trainable, "__name__", "trainable")
    return fn
