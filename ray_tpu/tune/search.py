"""Search spaces and search algorithms.

Ref analogs: python/ray/tune/search/sample.py (Domain/Categorical/Float/
Integer, grid_search), python/ray/tune/search/basic_variant.py
(BasicVariantGenerator — grid cross-product x num_samples random draws),
python/ray/tune/search/search_algorithm.py. Re-designed small: a Domain is
a picklable sampler; variant generation is an explicit cross-product over
grid axes with independent random draws for stochastic axes.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Optional


class Domain:
    """A samplable hyperparameter axis."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)

    def __repr__(self):
        return f"choice({self.categories})"


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False,
                 q: Optional[float] = None):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        if self.log:
            import math

            v = math.exp(rng.uniform(math.log(self.lower),
                                     math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = round(v / self.q) * self.q
        return v

    def __repr__(self):
        return f"{'log' if self.log else ''}uniform({self.lower},{self.upper})"


class Integer(Domain):
    def __init__(self, lower: int, upper: int, q: int = 1):
        self.lower, self.upper, self.q = lower, upper, q

    def sample(self, rng):
        v = rng.randrange(self.lower, self.upper)
        return (v // self.q) * self.q

    def __repr__(self):
        return f"randint({self.lower},{self.upper})"


class GridSearch:
    """Marker for exhaustive axes (ref: tune/search/sample.py grid_search)."""

    def __init__(self, values):
        self.values = list(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, q=q)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def sample_from(fn) -> "SampleFrom":
    return SampleFrom(fn)


class SampleFrom(Domain):
    """Callable domain: fn(spec: dict so-far) -> value."""

    def __init__(self, fn):
        self.fn = fn

    def sample(self, rng):  # resolved later with the partial config
        raise RuntimeError("SampleFrom is resolved by the generator")


# --------------------------------------------------------------- generation


def _split_space(space: Dict[str, Any], prefix=()):
    """Walk a (possibly nested-dict) space; yield (path, domain-or-literal)."""
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, dict):
            yield from _split_space(v, path)
        else:
            yield path, v


def _set_path(cfg: dict, path, value):
    d = cfg
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def generate_variants(space: Dict[str, Any], num_samples: int,
                      seed: Optional[int] = None) -> Iterator[Dict[str, Any]]:
    """Cross-product of grid axes × num_samples random draws.

    Matches the reference's semantics (basic_variant.py): each of the
    `num_samples` repetitions enumerates the full grid; stochastic axes are
    redrawn per variant.
    """
    rng = random.Random(seed)
    leaves = list(_split_space(space))
    grid_axes = [(p, v.values) for p, v in leaves if isinstance(v, GridSearch)]
    grid_iter = list(itertools.product(*[vals for _, vals in grid_axes])) \
        if grid_axes else [()]
    for _ in range(num_samples):
        for combo in grid_iter:
            cfg: Dict[str, Any] = {}
            for p, v in leaves:
                if isinstance(v, GridSearch):
                    continue
                if isinstance(v, SampleFrom):
                    continue  # second pass, needs partial config
                _set_path(cfg, p, v.sample(rng) if isinstance(v, Domain)
                          else v)
            for (p, _), val in zip(grid_axes, combo):
                _set_path(cfg, p, val)
            for p, v in leaves:
                if isinstance(v, SampleFrom):
                    _set_path(cfg, p, v.fn(cfg))
            yield cfg


class Searcher:
    """Suggestion-based search base (ref: tune/search/searcher.py).

    Subclasses propose configs one at a time and receive completed-trial
    feedback; wraps external optimizers.
    """

    def __init__(self, metric: str = None, mode: str = "max"):
        self.metric, self.mode = metric, mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[dict] = None,
                          error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    """Default searcher: pre-expanded grid/random variants."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None, **kw):
        super().__init__(**kw)
        self._variants = list(generate_variants(space, num_samples, seed))
        self._idx = 0

    @property
    def total(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg


class RandomSearch(Searcher):
    """Unbounded random sampler over a space (no grid axes)."""

    def __init__(self, space: Dict[str, Any], seed: Optional[int] = None,
                 **kw):
        super().__init__(**kw)
        self._space = space
        self._rng = random.Random(seed)

    def suggest(self, trial_id: str):
        cfg: Dict[str, Any] = {}
        for p, v in _split_space(self._space):
            if isinstance(v, GridSearch):
                v = Categorical(v.values)
            _set_path(cfg, p, v.sample(self._rng)
                      if isinstance(v, Domain) else v)
        return cfg


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (Bergstra et al., NeurIPS 2011).

    Ref analog: the reference ships Bayesian-class searchers as wrappers
    (tune/search/hyperopt/hyperopt_search.py wraps hyperopt's TPE,
    tune/search/bayesopt, tune/search/optuna). Implemented natively here
    (no external optimizer dependency): completed trials are split into a
    good set (top ``gamma`` quantile by the objective) and a bad set; each
    candidate is drawn from the good set's Parzen density l(x) and ranked
    by the acquisition log l(x) - log g(x), factorized per axis.
    """

    def __init__(self, space: Dict[str, Any], *, metric: str = "reward",
                 mode: str = "max", n_initial_points: int = 10,
                 gamma: float = 0.25, n_candidates: int = 24,
                 seed: Optional[int] = None):
        super().__init__(metric=metric, mode=mode)
        self._leaves = list(_split_space(space))
        for p, v in self._leaves:
            if isinstance(v, SampleFrom):
                raise ValueError("TPESearcher does not support sample_from")
        self._rng = random.Random(seed)
        self._n_initial = n_initial_points
        self._gamma = gamma
        self._n_candidates = n_candidates
        self._live: Dict[str, Dict[str, Any]] = {}   # trial_id -> config
        self._observed: List[tuple] = []             # (config, score)

    # ------------------------------------------------------- observations

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._live.pop(trial_id, None)
        if cfg is None or error or not result or \
                self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._observed.append((cfg, score))

    # --------------------------------------------------------- suggesting

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._observed) < self._n_initial:
            cfg = self._random_config()
        else:
            cfg = self._tpe_config()
        self._live[trial_id] = cfg
        return cfg

    def _random_config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {}
        for p, v in self._leaves:
            if isinstance(v, GridSearch):
                v = Categorical(v.values)
            _set_path(cfg, p, v.sample(self._rng)
                      if isinstance(v, Domain) else v)
        return cfg

    def _tpe_config(self) -> Dict[str, Any]:
        ranked = sorted(self._observed, key=lambda cv: cv[1], reverse=True)
        n_good = max(1, int(self._gamma * len(ranked)))
        good = [c for c, _ in ranked[:n_good]]
        bad = [c for c, _ in ranked[n_good:]] or good
        best_cfg, best_score = None, None
        for _ in range(self._n_candidates):
            cfg: Dict[str, Any] = {}
            total = 0.0
            for p, v in self._leaves:
                if isinstance(v, GridSearch):
                    v = Categorical(v.values)
                if not isinstance(v, Domain):
                    _set_path(cfg, p, v)
                    continue
                gv = [_get_path(c, p) for c in good]
                bv = [_get_path(c, p) for c in bad]
                val, logratio = self._propose_axis(v, gv, bv)
                _set_path(cfg, p, val)
                total += logratio
            if best_score is None or total > best_score:
                best_cfg, best_score = cfg, total
        return best_cfg

    def _propose_axis(self, dom: Domain, good: list, bad: list):
        import math

        if isinstance(dom, Categorical):
            cats = dom.categories
            pg = _cat_probs(cats, good)
            pb = _cat_probs(cats, bad)
            i = self._rng.choices(range(len(cats)), weights=pg, k=1)[0]
            return cats[i], math.log(pg[i]) - math.log(pb[i])
        # numeric (Float / Integer): Parzen windows in (log-)space
        is_int = isinstance(dom, Integer)
        lo, hi = float(dom.lower), float(dom.upper)
        log = getattr(dom, "log", False)
        tf = math.log if log else (lambda x: float(x))
        t_lo, t_hi = tf(lo), tf(hi)
        g = [tf(v) for v in good]
        b = [tf(v) for v in bad]
        bw_g = max((t_hi - t_lo) / max(math.sqrt(len(g)), 1.0), 1e-9)
        bw_b = max((t_hi - t_lo) / max(math.sqrt(len(b)), 1.0), 1e-9)
        center = self._rng.choice(g)
        x = min(max(self._rng.gauss(center, bw_g), t_lo), t_hi)
        logratio = _parzen_logpdf(x, g, bw_g) - _parzen_logpdf(x, b, bw_b)
        val = math.exp(x) if log else x
        if is_int:
            val = int(min(max(round(val), dom.lower), dom.upper - 1))
            q = getattr(dom, "q", 1) or 1
            val = (val // q) * q
        elif getattr(dom, "q", None):
            val = round(val / dom.q) * dom.q
        return val, logratio


def _get_path(cfg: dict, path):
    d = cfg
    for k in path:
        d = d[k]
    return d


def _cat_probs(cats, values):
    """Category probabilities with add-one smoothing."""
    counts = [1.0] * len(cats)
    index = {c if not isinstance(c, (list, dict)) else repr(c): i
             for i, c in enumerate(cats)}
    for v in values:
        key = v if not isinstance(v, (list, dict)) else repr(v)
        if key in index:
            counts[index[key]] += 1.0
    total = sum(counts)
    return [c / total for c in counts]


def _parzen_logpdf(x, centers, bw):
    import math

    # log-mean-exp of N(x; ci, bw) over centers
    logs = [-0.5 * ((x - c) / bw) ** 2 - math.log(bw) for c in centers]
    m = max(logs)
    return m + math.log(sum(math.exp(v - m) for v in logs)
                        / len(centers))
