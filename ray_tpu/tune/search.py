"""Search spaces and search algorithms.

Ref analogs: python/ray/tune/search/sample.py (Domain/Categorical/Float/
Integer, grid_search), python/ray/tune/search/basic_variant.py
(BasicVariantGenerator — grid cross-product x num_samples random draws),
python/ray/tune/search/search_algorithm.py. Re-designed small: a Domain is
a picklable sampler; variant generation is an explicit cross-product over
grid axes with independent random draws for stochastic axes.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Dict, Iterator, List, Optional


class Domain:
    """A samplable hyperparameter axis."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)

    def __repr__(self):
        return f"choice({self.categories})"


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False,
                 q: Optional[float] = None):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        if self.log:
            import math

            v = math.exp(rng.uniform(math.log(self.lower),
                                     math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q:
            v = round(v / self.q) * self.q
        return v

    def __repr__(self):
        return f"{'log' if self.log else ''}uniform({self.lower},{self.upper})"


class Integer(Domain):
    def __init__(self, lower: int, upper: int, q: int = 1):
        self.lower, self.upper, self.q = lower, upper, q

    def sample(self, rng):
        v = rng.randrange(self.lower, self.upper)
        return (v // self.q) * self.q

    def __repr__(self):
        return f"randint({self.lower},{self.upper})"


class GridSearch:
    """Marker for exhaustive axes (ref: tune/search/sample.py grid_search)."""

    def __init__(self, values):
        self.values = list(values)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, q=q)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def sample_from(fn) -> "SampleFrom":
    return SampleFrom(fn)


class SampleFrom(Domain):
    """Callable domain: fn(spec: dict so-far) -> value."""

    def __init__(self, fn):
        self.fn = fn

    def sample(self, rng):  # resolved later with the partial config
        raise RuntimeError("SampleFrom is resolved by the generator")


# --------------------------------------------------------------- generation


def _split_space(space: Dict[str, Any], prefix=()):
    """Walk a (possibly nested-dict) space; yield (path, domain-or-literal)."""
    for k, v in space.items():
        path = prefix + (k,)
        if isinstance(v, dict):
            yield from _split_space(v, path)
        else:
            yield path, v


def _set_path(cfg: dict, path, value):
    d = cfg
    for k in path[:-1]:
        d = d.setdefault(k, {})
    d[path[-1]] = value


def generate_variants(space: Dict[str, Any], num_samples: int,
                      seed: Optional[int] = None) -> Iterator[Dict[str, Any]]:
    """Cross-product of grid axes × num_samples random draws.

    Matches the reference's semantics (basic_variant.py): each of the
    `num_samples` repetitions enumerates the full grid; stochastic axes are
    redrawn per variant.
    """
    rng = random.Random(seed)
    leaves = list(_split_space(space))
    grid_axes = [(p, v.values) for p, v in leaves if isinstance(v, GridSearch)]
    grid_iter = list(itertools.product(*[vals for _, vals in grid_axes])) \
        if grid_axes else [()]
    for _ in range(num_samples):
        for combo in grid_iter:
            cfg: Dict[str, Any] = {}
            for p, v in leaves:
                if isinstance(v, GridSearch):
                    continue
                if isinstance(v, SampleFrom):
                    continue  # second pass, needs partial config
                _set_path(cfg, p, v.sample(rng) if isinstance(v, Domain)
                          else v)
            for (p, _), val in zip(grid_axes, combo):
                _set_path(cfg, p, val)
            for p, v in leaves:
                if isinstance(v, SampleFrom):
                    _set_path(cfg, p, v.fn(cfg))
            yield cfg


class Searcher:
    """Suggestion-based search base (ref: tune/search/searcher.py).

    Subclasses propose configs one at a time and receive completed-trial
    feedback; wraps external optimizers.
    """

    def __init__(self, metric: str = None, mode: str = "max"):
        self.metric, self.mode = metric, mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[dict] = None,
                          error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    """Default searcher: pre-expanded grid/random variants."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None, **kw):
        super().__init__(**kw)
        self._variants = list(generate_variants(space, num_samples, seed))
        self._idx = 0

    @property
    def total(self) -> int:
        return len(self._variants)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg


class RandomSearch(Searcher):
    """Unbounded random sampler over a space (no grid axes)."""

    def __init__(self, space: Dict[str, Any], seed: Optional[int] = None,
                 **kw):
        super().__init__(**kw)
        self._space = space
        self._rng = random.Random(seed)

    def suggest(self, trial_id: str):
        cfg: Dict[str, Any] = {}
        for p, v in _split_space(self._space):
            if isinstance(v, GridSearch):
                v = Categorical(v.values)
            _set_path(cfg, p, v.sample(self._rng)
                      if isinstance(v, Domain) else v)
        return cfg
