"""ray_tpu.tune — hyperparameter tuning over trial actors.

Ref analog: python/ray/tune (Tuner tuner.py:59, TuneController
execution/tune_controller.py:80, Trainable trainable/trainable.py:75,
schedulers/, search/ — SURVEY.md §2.4). One trial = one actor; the
controller pumps ``train()`` futures and applies scheduler decisions.
"""

from .result_grid import ResultGrid
from .schedulers import (
    ASHAScheduler,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from .external_search import OptunaSearch
from .search import (
    BasicVariantGenerator,
    RandomSearch,
    Searcher,
    TPESearcher,
    choice,
    grid_search,
    loguniform,
    qrandint,
    quniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.train.config import CheckpointConfig, FailureConfig, RunConfig

from .session import get_checkpoint, report
from .trainable import FunctionTrainable, Trainable, with_parameters
from .trial import Trial
from .tuner import TuneConfig, Tuner, run

__all__ = [
    "Tuner", "TuneConfig", "run", "ResultGrid", "Trial",
    "RunConfig", "CheckpointConfig", "FailureConfig",
    "Trainable", "FunctionTrainable", "with_parameters",
    "report", "get_checkpoint",
    "TrialScheduler", "FIFOScheduler", "AsyncHyperBandScheduler",
    "ASHAScheduler", "HyperBandScheduler", "MedianStoppingRule",
    "PopulationBasedTraining",
    "Searcher", "BasicVariantGenerator", "RandomSearch", "TPESearcher",
    "OptunaSearch",
    "choice", "uniform", "loguniform", "quniform", "randint", "qrandint",
    "grid_search", "sample_from",
]

from ray_tpu.usage_stats import record_library_usage as _rlu
_rlu("tune")
del _rlu
