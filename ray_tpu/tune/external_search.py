"""External-optimizer searcher adapters.

Ref analog: tune/search/optuna/optuna_search.py (and the hyperopt/
bayesopt/BOHB siblings) — thin adapters that translate Tune's search
space + ask/tell protocol onto an external optimizer. This image is
sealed, so the adapter hard-gates on importability with a clear error
naming the native alternative (``TPESearcher`` implements the same
TPE algorithm class with no dependency); the translation layer itself
is fully unit-testable against a fake module.

Only Optuna is adapted: its ask-and-tell API is a documented, stable
protocol. hyperopt's equivalent requires reaching into Trials
internals, which is not worth maintaining against a library this image
cannot even install.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .search import (Categorical, Domain, Float, GridSearch, Integer,
                     SampleFrom, Searcher, _set_path, _split_space)


class OptunaSearch(Searcher):
    """Adapter onto an optuna ``Study`` via ask/tell.

    Space leaves map to distributions: ``Float`` -> suggest_float
    (log-scaled when the domain is loguniform; quantized via step),
    ``Integer`` -> suggest_int, ``Categorical``/``GridSearch`` ->
    suggest_categorical. ``sample_from`` is rejected (same as the
    reference's OptunaSearch, which cannot express callables).
    """

    def __init__(self, space: Dict[str, Any], *, metric: str = "reward",
                 mode: str = "max", seed: Optional[int] = None,
                 sampler=None, study=None):
        super().__init__(metric=metric, mode=mode)
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the 'optuna' package, which is "
                "not available on this sealed image; use the native "
                "TPESearcher (same TPE algorithm class, no external "
                "dependency) or pre-bake optuna into the image."
            ) from e
        self._optuna = optuna
        self._leaves = []
        for path, dom in _split_space(space):
            if isinstance(dom, SampleFrom):
                raise ValueError(
                    "OptunaSearch does not support sample_from")
            self._leaves.append((path, dom))
        if study is None:
            if sampler is None:
                sampler = optuna.samplers.TPESampler(seed=seed)
            study = optuna.create_study(
                direction="maximize" if mode == "max" else "minimize",
                sampler=sampler)
        self._study = study
        self._trials: Dict[str, Any] = {}  # tune trial_id -> optuna trial

    @staticmethod
    def _param_name(path) -> str:
        return ".".join(path)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        ot = self._study.ask()
        cfg: Dict[str, Any] = {}
        for path, dom in self._leaves:
            name = self._param_name(path)
            if isinstance(dom, Float):
                log = bool(getattr(dom, "log", False))
                # optuna rejects step together with log; log wins
                step = None if log else getattr(dom, "q", None)
                val = ot.suggest_float(name, dom.lower, dom.upper,
                                       log=log, step=step)
            elif isinstance(dom, Integer):
                # our Integer upper is EXCLUSIVE (randrange); optuna's
                # high is inclusive
                val = ot.suggest_int(
                    name, dom.lower, dom.upper - 1,
                    step=getattr(dom, "q", None) or 1)
            elif isinstance(dom, (Categorical, GridSearch)):
                values = (dom.categories if isinstance(dom, Categorical)
                          else dom.values)
                val = ot.suggest_categorical(name, list(values))
            elif isinstance(dom, Domain):
                raise TypeError(f"unsupported domain {type(dom).__name__}")
            else:
                val = dom  # constant leaf passes through unchanged
            _set_path(cfg, path, val)
        self._trials[trial_id] = ot
        return cfg

    def on_trial_complete(self, trial_id: str,
                          result: Optional[dict] = None,
                          error: bool = False):
        ot = self._trials.pop(trial_id, None)
        if ot is None:
            return
        if error or not result or self.metric not in result:
            state = self._optuna.trial.TrialState.FAIL
            self._study.tell(ot, state=state)
            return
        self._study.tell(ot, float(result[self.metric]))
