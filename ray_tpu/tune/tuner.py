"""Tuner: the experiment-level entry point.

Ref analogs: python/ray/tune/tuner.py:59 (Tuner.fit :337) and
python/ray/tune/tune.py:293 (tune.run). ``Tuner(trainable, param_space=...,
tune_config=TuneConfig(...), run_config=RunConfig(...)).fit()``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Union

import ray_tpu
from ray_tpu.train.config import RunConfig

from .execution import TuneController
from .result_grid import ResultGrid
from .search import BasicVariantGenerator, Searcher
from .trainable import FunctionTrainable, Trainable


@dataclasses.dataclass
class TuneConfig:
    """Ref analog: python/ray/tune/tune_config.py."""

    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 0
    search_alg: Optional[Searcher] = None
    scheduler: Any = None
    time_budget_s: Optional[float] = None
    seed: Optional[int] = None
    checkpoint_frequency: int = 0
    max_failures: int = 0
    resources_per_trial: Optional[Dict[str, float]] = None


class Tuner:
    def __init__(self, trainable: Union[type, Callable],
                 *, param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self._tc = tune_config or TuneConfig()
        self._rc = run_config or RunConfig()
        self._space = param_space or {}
        self._trainable = self._as_trainable_cls(trainable)
        # Trainers (train.BaseTrainer) carry their own resource needs.
        if hasattr(trainable, "_tune_resources"):
            self._tc.resources_per_trial = trainable._tune_resources()

    @staticmethod
    def _as_trainable_cls(trainable) -> type:
        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            return trainable
        if callable(trainable):
            return FunctionTrainable.wrap(trainable)
        raise TypeError(f"not a trainable: {trainable!r}")

    def fit(self) -> ResultGrid:
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self._tc
        searcher = tc.search_alg or BasicVariantGenerator(
            self._space, num_samples=tc.num_samples, seed=tc.seed,
            metric=tc.metric, mode=tc.mode)
        # PBT needs periodic checkpoints to exploit from.
        ckpt_freq = tc.checkpoint_frequency
        from .schedulers import PopulationBasedTraining

        if isinstance(tc.scheduler, PopulationBasedTraining) and not \
                ckpt_freq:
            ckpt_freq = tc.scheduler.interval
        controller = TuneController(
            self._trainable,
            searcher=searcher,
            scheduler=tc.scheduler,
            metric=tc.metric,
            mode=tc.mode,
            max_concurrent=tc.max_concurrent_trials,
            num_samples=tc.num_samples if tc.search_alg is not None else 0,
            resources_per_trial=tc.resources_per_trial,
            stop=getattr(self._rc, "stop", None),
            max_failures=tc.max_failures,
            checkpoint_frequency=ckpt_freq,
            storage_path=self._rc.storage_path,
            experiment_name=self._rc.name or "experiment",
            time_budget_s=tc.time_budget_s,
        )
        controller.run()
        return ResultGrid(controller.trials, metric=tc.metric, mode=tc.mode)


def run(trainable, *, config: Optional[Dict[str, Any]] = None,
        num_samples: int = 1, metric: Optional[str] = None,
        mode: str = "max", scheduler=None, search_alg=None, stop=None,
        max_concurrent_trials: int = 0, storage_path: Optional[str] = None,
        name: Optional[str] = None, resources_per_trial=None,
        **_ignored) -> ResultGrid:
    """Functional entry point (ref: tune/tune.py:293 tune.run)."""
    rc = RunConfig(name=name, storage_path=storage_path)
    rc.stop = stop  # type: ignore[attr-defined]
    return Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric, mode=mode, num_samples=num_samples,
            scheduler=scheduler, search_alg=search_alg,
            max_concurrent_trials=max_concurrent_trials,
            resources_per_trial=resources_per_trial),
        run_config=rc,
    ).fit()
