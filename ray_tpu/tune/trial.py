"""Trial state record (ref analog: python/ray/tune/experiment/trial.py)."""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclasses.dataclass
class Trial:
    config: Dict[str, Any]
    trial_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:8])
    status: str = PENDING
    last_result: Dict[str, Any] = dataclasses.field(default_factory=dict)
    metric_history: List[Dict[str, Any]] = dataclasses.field(
        default_factory=list)
    iteration: int = 0
    error: Optional[str] = None
    checkpoint: Any = None           # latest in-memory checkpoint payload
    checkpoint_iter: int = 0
    start_time: float = dataclasses.field(default_factory=time.time)
    # scheduler scratch (e.g. ASHA bracket/rung assignment)
    scheduler_data: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def metric_value(self, metric: str):
        return self.last_result.get(metric)

    def is_finished(self) -> bool:
        return self.status in (TERMINATED, ERROR)

    def public_state(self) -> dict:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "status": self.status,
            "iteration": self.iteration,
            "last_result": self.last_result,
            "error": self.error,
        }
