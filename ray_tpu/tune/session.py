"""Worker-side tune session: ``tune.report`` / ``tune.get_checkpoint``.

Ref analog: python/ray/tune's `session` (air/session.py) as used from inside
function trainables.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

_local = threading.local()


def _set_reporter(reporter: Callable, checkpoint: Any = None):
    _local.reporter = reporter
    _local.checkpoint = checkpoint


def report(metrics: Dict[str, Any], *, checkpoint: Any = None):
    rep = getattr(_local, "reporter", None)
    if rep is None:
        raise RuntimeError("tune.report() called outside a tune session")
    rep(metrics, checkpoint)


def get_checkpoint() -> Optional[Any]:
    return getattr(_local, "checkpoint", None)
