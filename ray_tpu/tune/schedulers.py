"""Trial schedulers: FIFO, ASHA, HyperBand, median-stopping, PBT.

Ref analogs: python/ray/tune/schedulers/trial_scheduler.py (decision enum),
async_hyperband.py:19 (ASHA brackets/rungs), hyperband.py,
median_stopping_rule.py, pbt.py:219 (exploit/explore). Re-designed around a
single ``on_result(trials, trial, result) -> decision`` hook; PBT signals a
config+checkpoint swap via the ``UPDATE`` decision after mutating the trial
record in place.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

from .trial import RUNNING, TERMINATED, Trial

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"
UPDATE = "UPDATE"  # config/checkpoint changed; controller must re-seat actor


class TrialScheduler:
    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration"):
        if mode not in ("max", "min"):
            raise ValueError("mode must be 'max' or 'min'")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr

    def _score(self, result: Dict[str, Any]) -> float:
        v = result.get(self.metric)
        if v is None:
            raise KeyError(f"result missing scheduler metric "
                           f"'{self.metric}'")
        return float(v) if self.mode == "max" else -float(v)

    def on_result(self, trials: List[Trial], trial: Trial,
                  result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trials: List[Trial], trial: Trial):
        pass


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (ref: trial_scheduler.py FIFOScheduler)."""


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (ref: schedulers/async_hyperband.py:19).

    Rungs at grace_period * reduction_factor^k; a trial reaching a rung
    stops unless its score is in the top 1/reduction_factor of everything
    recorded at that rung (async — no waiting for full brackets).
    """

    def __init__(self, metric=None, mode="max",
                 time_attr="training_iteration", grace_period: int = 1,
                 max_t: int = 100, reduction_factor: float = 4,
                 brackets: int = 1):
        super().__init__(metric, mode, time_attr)
        self.grace_period = grace_period
        self.max_t = max_t
        self.rf = reduction_factor
        # rung milestones, smallest first, per bracket
        self._brackets: List[Dict[float, List[float]]] = []
        for s in range(brackets):
            rungs = {}
            t = grace_period * (self.rf ** s)
            while t < max_t:
                rungs[t] = []
                t *= self.rf
            self._brackets.append(rungs)
        self._rr = 0

    def _bracket_for(self, trial: Trial) -> Dict[float, List[float]]:
        idx = trial.scheduler_data.get("bracket")
        if idx is None:
            idx = self._rr % len(self._brackets)
            self._rr += 1
            trial.scheduler_data["bracket"] = idx
        return self._brackets[idx]

    def on_result(self, trials, trial, result) -> str:
        t = result.get(self.time_attr, trial.iteration)
        if t >= self.max_t:
            return STOP
        rungs = self._bracket_for(trial)
        score = self._score(result)
        decision = CONTINUE
        for milestone in sorted(rungs, reverse=True):
            if t < milestone:
                continue
            passed = trial.scheduler_data.setdefault("rungs_passed", set())
            if milestone in passed:
                break
            passed.add(milestone)
            recorded = rungs[milestone]
            recorded.append(score)
            if len(recorded) >= self.rf:
                cutoff_rank = max(1, int(len(recorded) / self.rf))
                cutoff = sorted(recorded, reverse=True)[cutoff_rank - 1]
                if score < cutoff:
                    decision = STOP
            break
        return decision


# The reference exposes HyperBand both sync and async; ASHA is the
# recommended implementation (async_hyperband.py docstring) — alias it.
HyperBandScheduler = AsyncHyperBandScheduler
ASHAScheduler = AsyncHyperBandScheduler


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-best is below the median of other trials'
    running means at the same step (ref: median_stopping_rule.py)."""

    def __init__(self, metric=None, mode="max",
                 time_attr="training_iteration", grace_period: int = 1,
                 min_samples_required: int = 3):
        super().__init__(metric, mode, time_attr)
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        self._means: Dict[str, List[float]] = {}

    def on_result(self, trials, trial, result) -> str:
        t = result.get(self.time_attr, trial.iteration)
        score = self._score(result)
        hist = self._means.setdefault(trial.trial_id, [])
        hist.append(score)
        if t < self.grace_period:
            return CONTINUE
        other_means = [sum(h) / len(h) for tid, h in self._means.items()
                       if tid != trial.trial_id and h]
        if len(other_means) < self.min_samples:
            return CONTINUE
        median = sorted(other_means)[len(other_means) // 2]
        best = max(hist)
        return STOP if best < median else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (ref: schedulers/pbt.py:219).

    Every ``perturbation_interval`` steps, a bottom-quantile trial clones a
    top-quantile trial's checkpoint (exploit) and perturbs hyperparameters
    (explore). The swap is communicated by mutating the trial record
    (config + checkpoint) and returning UPDATE; the controller re-seats the
    actor (reset_config or restart+restore).
    """

    def __init__(self, metric=None, mode="max",
                 time_attr="training_iteration",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Dict[str, Any] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(metric, mode, time_attr)
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self._rng = random.Random(seed)

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from .search import Domain

        new = dict(config)
        for key, spec in self.mutations.items():
            cur = new.get(key)
            resample = cur is None or self._rng.random() < self.resample_p
            if isinstance(spec, Domain):
                if resample:
                    new[key] = spec.sample(self._rng)
                elif isinstance(cur, (int, float)):
                    new[key] = cur * self._rng.choice([0.8, 1.2])
            elif isinstance(spec, list):
                if resample or cur not in spec:
                    new[key] = self._rng.choice(spec)
                else:
                    i = spec.index(cur)
                    j = min(len(spec) - 1, max(0, i + self._rng.choice(
                        [-1, 1])))
                    new[key] = spec[j]
            elif callable(spec):
                new[key] = spec()
            if isinstance(new.get(key), float) and isinstance(cur, int):
                new[key] = int(new[key])
        return new

    def on_result(self, trials, trial, result) -> str:
        t = result.get(self.time_attr, trial.iteration)
        last = trial.scheduler_data.get("last_perturb", 0)
        if t - last < self.interval:
            return CONTINUE
        trial.scheduler_data["last_perturb"] = t
        active = [tr for tr in trials
                  if tr.status == RUNNING and self.metric in tr.last_result]
        if len(active) < 2:
            return CONTINUE
        ranked = sorted(
            active, key=lambda tr: self._score(tr.last_result), reverse=True)
        k = max(1, int(len(ranked) * self.quantile))
        top, bottom = ranked[:k], ranked[-k:]
        if trial not in bottom or trial in top:
            return CONTINUE
        donor = self._rng.choice(top)
        if donor.checkpoint is None:
            return CONTINUE
        trial.config = self._explore(donor.config)
        trial.checkpoint = donor.checkpoint
        trial.checkpoint_iter = donor.checkpoint_iter
        return UPDATE
