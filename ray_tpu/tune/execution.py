"""TuneController: the experiment event loop.

Ref analog: python/ray/tune/execution/tune_controller.py:80 — an event-driven
loop that seats trials on actors, pumps ``train()`` results, applies
scheduler decisions, and checkpoints experiment state. Re-designed around
``wait()`` over in-flight train futures instead of the reference's
actor-manager event system (one trial = one actor here; the runtime already
multiplexes actors over worker processes).
"""

from __future__ import annotations

import json
import os
import time
import traceback
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.object_ref import ObjectRef

from . import schedulers as S
from .trial import (ERROR, PAUSED, PENDING, RUNNING, TERMINATED, Trial)
from .trainable import FunctionTrainable, Trainable


class _TrialRunner:
    """Driver-side handle pairing a Trial with its live actor."""

    def __init__(self, trial: Trial, actor, train_future=None):
        self.trial = trial
        self.actor = actor
        self.future: Optional[ObjectRef] = train_future
        self.failures = 0


class TuneController:
    def __init__(self, trainable_cls: type, *, searcher, scheduler=None,
                 metric: Optional[str] = None, mode: str = "max",
                 max_concurrent: int = 0, resources_per_trial=None,
                 stop=None, max_failures: int = 0,
                 checkpoint_frequency: int = 0,
                 storage_path: Optional[str] = None,
                 experiment_name: str = "experiment",
                 time_budget_s: Optional[float] = None,
                 num_samples: int = 0,
                 trial_executor_kwargs=None):
        self._cls = trainable_cls
        self._searcher = searcher
        self._scheduler = scheduler or S.FIFOScheduler(metric=metric,
                                                      mode=mode)
        if self._scheduler.metric is None:
            self._scheduler.metric = metric
        self.metric, self.mode = metric, mode
        self._resources = dict(resources_per_trial or {"CPU": 1})
        self._stop_criteria = stop
        self._max_failures = max_failures
        self._ckpt_freq = checkpoint_frequency
        self._time_budget = time_budget_s
        self.trials: List[Trial] = []
        self._runners: Dict[str, _TrialRunner] = {}
        self._max_concurrent = max_concurrent or self._default_concurrency()
        # bounds suggestion-based searchers (TPE etc.) that never return
        # None on their own; 0 = unbounded (pre-expanded searchers exhaust)
        self._num_samples = num_samples
        self._exhausted = False
        self._storage = storage_path
        self._name = experiment_name
        if self._storage:
            os.makedirs(self._exp_dir(), exist_ok=True)

    def _exp_dir(self) -> str:
        return os.path.join(self._storage, self._name)

    def _default_concurrency(self) -> int:
        try:
            cpus = ray_tpu.cluster_resources().get("CPU", 1)
            need = max(1.0, self._resources.get("CPU", 1))
            return max(1, int(cpus / need))
        except Exception:
            return 4

    # ------------------------------------------------------------- lifecycle

    def _make_actor(self, trial: Trial):
        actor_cls = ray_tpu.remote(self._cls)
        cfg = dict(trial.config)
        if trial.checkpoint is not None and issubclass(self._cls,
                                                      FunctionTrainable):
            # trial.checkpoint holds save()'s {'iteration','payload'}
            # wrapper; the user-facing tune.get_checkpoint() must see the
            # payload they reported, not the wrapper
            ckpt = _maybe_get(trial.checkpoint)
            if isinstance(ckpt, dict) and set(ckpt) == {"iteration",
                                                        "payload"}:
                ckpt = ckpt["payload"]
            cfg["__checkpoint__"] = ckpt
        handle = actor_cls.options(
            num_cpus=self._resources.get("CPU", 1),
            num_tpus=self._resources.get("TPU", 0) or None,
            resources={k: v for k, v in self._resources.items()
                       if k not in ("CPU", "TPU")} or None,
        ).remote(cfg)
        if trial.checkpoint is not None and not issubclass(
                self._cls, FunctionTrainable):
            ray_tpu.get(handle.restore.remote(_maybe_get(trial.checkpoint)))
        return handle

    def _start_trial(self, trial: Trial):
        actor = self._make_actor(trial)
        runner = _TrialRunner(trial, actor)
        runner.future = actor.train.remote()
        trial.status = RUNNING
        self._runners[trial.trial_id] = runner

    def _stop_trial(self, trial: Trial, status: str, error: str = None):
        runner = self._runners.pop(trial.trial_id, None)
        if runner is not None:
            try:
                runner.actor.stop.remote()
            except Exception:
                pass
            try:
                ray_tpu.kill(runner.actor)
            except Exception:
                pass
        trial.status = status
        trial.error = error
        self._searcher.on_trial_complete(trial.trial_id, trial.last_result,
                                         error=status == ERROR)
        self._scheduler.on_trial_complete(self.trials, trial)

    # ------------------------------------------------------------- main loop

    def _fill_trials(self):
        while len(self._runners) < self._max_concurrent:
            # Resume paused trials whenever a slot frees, regardless of
            # searcher exhaustion — gating this on `not _exhausted` livelocks
            # custom PAUSE-ing schedulers once the searcher runs dry
            # (round-1 ADVICE, medium).
            paused = [t for t in self.trials if t.status == PAUSED]
            if paused:
                trial = paused[0]
                self._start_trial(trial)
                continue
            if self._exhausted:
                break
            if self._num_samples and len(self.trials) >= self._num_samples:
                self._exhausted = True
                break
            tid = f"t{len(self.trials):05d}"
            cfg = self._searcher.suggest(tid)
            if cfg is None:
                self._exhausted = True
                break
            trial = Trial(config=cfg, trial_id=tid)
            self.trials.append(trial)
            self._start_trial(trial)

    def _should_stop_trial(self, trial: Trial, result: dict) -> bool:
        if result.get("done"):
            return True
        crit = self._stop_criteria
        if crit is None:
            return False
        if callable(crit):
            return bool(crit(trial.trial_id, result))
        for key, bound in crit.items():
            if key in result:
                if key == "training_iteration" or self.mode == "max":
                    if result[key] >= bound:
                        return True
                elif result[key] <= bound:
                    return True
        return False

    def _maybe_checkpoint(self, runner: _TrialRunner):
        trial = runner.trial
        if self._ckpt_freq and trial.iteration > 0 and \
                trial.iteration % self._ckpt_freq == 0 and \
                trial.iteration > trial.checkpoint_iter:
            # resolve eagerly: a pending save ref would be lost if this
            # actor is later killed (stop/exploit) before executing it
            trial.checkpoint = ray_tpu.get(runner.actor.save.remote())
            trial.checkpoint_iter = trial.iteration

    def _handle_result(self, runner: _TrialRunner, result: dict):
        trial = runner.trial
        trial.last_result = result
        trial.metric_history.append(result)
        trial.iteration = result.get("training_iteration",
                                     trial.iteration + 1)
        self._maybe_checkpoint(runner)
        if self._should_stop_trial(trial, result):
            self._stop_trial(trial, TERMINATED)
            return
        try:
            decision = S.CONTINUE if self._scheduler.metric is None else \
                self._scheduler.on_result(self.trials, trial, result)
        except KeyError:
            decision = S.CONTINUE
        if decision == S.STOP:
            self._stop_trial(trial, TERMINATED)
        elif decision == S.PAUSE:
            trial.checkpoint = _maybe_get(runner.actor.save.remote())
            trial.checkpoint_iter = trial.iteration
            self._runners.pop(trial.trial_id, None)
            try:
                ray_tpu.kill(runner.actor)
            except Exception:
                pass
            trial.status = PAUSED
        elif decision == S.UPDATE:
            # PBT exploit/explore: try in-place reset, else restart actor
            # from the donor checkpoint already placed on the trial record.
            ok = False
            try:
                ok = ray_tpu.get(
                    runner.actor.reset.remote(trial.config))
            except Exception:
                ok = False
            if ok:
                try:
                    ray_tpu.get(runner.actor.restore.remote(
                        _maybe_get(trial.checkpoint)))
                except Exception:
                    ok = False
            if not ok:
                old = self._runners.pop(trial.trial_id)
                try:
                    ray_tpu.kill(old.actor)
                except Exception:
                    pass
                self._start_trial(trial)
            else:
                runner.future = runner.actor.train.remote()
        else:
            runner.future = runner.actor.train.remote()

    def _handle_error(self, runner: _TrialRunner, err: BaseException):
        trial = runner.trial
        runner.failures += 1
        if runner.failures <= self._max_failures:
            self._runners.pop(trial.trial_id, None)
            try:
                ray_tpu.kill(runner.actor)
            except Exception:
                pass
            self._start_trial(trial)
            self._runners[trial.trial_id].failures = runner.failures
        else:
            self._stop_trial(trial, ERROR, error="".join(
                traceback.format_exception_only(type(err), err)).strip())

    def step(self) -> bool:
        """One pump of the loop. Returns False when the experiment is over."""
        self._fill_trials()
        futures = {r.future: r for r in self._runners.values()
                   if r.future is not None}
        if not futures:
            return any(t.status == PAUSED for t in self.trials)
        ready, _ = ray_tpu.wait(list(futures), num_returns=1, timeout=30.0)
        for ref in ready:
            runner = futures[ref]
            runner.future = None
            try:
                result = ray_tpu.get(ref)
            except BaseException as e:  # noqa: BLE001 — trial failure path
                self._handle_error(runner, e)
                continue
            self._handle_result(runner, result)
        return True

    def run(self, callbacks: Optional[List[Callable]] = None):
        start = time.time()
        while self.step():
            if self._time_budget and time.time() - start > self._time_budget:
                for t in list(self.trials):
                    if not t.is_finished():
                        self._stop_trial(t, TERMINATED)
                break
            if self._storage:
                self._save_experiment_state()
            for cb in callbacks or []:
                cb(self)
        # resolve any checkpoint refs so results outlive shutdown
        for t in self.trials:
            t.checkpoint = _maybe_get(t.checkpoint)
        if self._storage:
            self._save_experiment_state()

    # -------------------------------------------------------------- persist

    def _save_experiment_state(self):
        state = {
            "name": self._name,
            "trials": [t.public_state() for t in self.trials],
            "timestamp": time.time(),
        }
        path = os.path.join(self._exp_dir(), "experiment_state.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f, default=str)
        os.replace(tmp, path)


def _maybe_get(v):
    return ray_tpu.get(v) if isinstance(v, ObjectRef) else v
