"""ResultGrid: what Tuner.fit returns (ref: python/ray/tune/result_grid.py)."""

from __future__ import annotations

from typing import List, Optional

from ray_tpu.train.config import Result

from .trial import ERROR, Trial


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str] = None,
                 mode: str = "max"):
        self._trials = trials
        self._metric, self._mode = metric, mode

    def __len__(self):
        return len(self._trials)

    def __getitem__(self, i) -> Result:
        return self._to_result(self._trials[i])

    def __iter__(self):
        return (self._to_result(t) for t in self._trials)

    @staticmethod
    def _to_result(t: Trial) -> Result:
        r = Result(metrics=t.last_result, checkpoint=t.checkpoint,
                   error=RuntimeError(t.error) if t.error else None,
                   metrics_history=t.metric_history)
        r.config = t.config  # type: ignore[attr-defined]
        return r

    @property
    def errors(self) -> List[str]:
        return [t.error for t in self._trials if t.status == ERROR]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("no metric given to get_best_result")
        scored = [t for t in self._trials if metric in t.last_result]
        if not scored:
            raise RuntimeError("no trial reported the metric "
                               f"'{metric}'")
        best = (max if mode == "max" else min)(
            scored, key=lambda t: t.last_result[metric])
        return self._to_result(best)

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for t in self._trials:
            row = {"trial_id": t.trial_id, "status": t.status}
            row.update({f"config/{k}": v for k, v in t.config.items()
                        if not isinstance(v, dict)})
            row.update({k: v for k, v in t.last_result.items()
                        if not isinstance(v, dict)})
            rows.append(row)
        return pd.DataFrame(rows)
