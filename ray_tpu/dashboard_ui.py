"""Dashboard SPA: a single-file vanilla-JS client over the JSON API.

Ref analog: the reference's React/TS dashboard client
(dashboard/client/src/ — jobs/actors/nodes/metrics/serve pages backed by
the same REST endpoints). Re-design: no build toolchain — one hash-routed
HTML document served by dashboard.py, reading /api/* every 2 s. Pages:
overview, nodes, actors, tasks (+summary), objects, placement groups,
jobs, metrics, events (the cluster event log), serve, timeline (SVG
lanes over ray_tpu.tracing events).

Colors follow a validated light/dark palette (categorical slots for
timeline lanes, status colors only for alive/dead state, always beside a
text label — never color alone).
"""

INDEX_HTML = r"""<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
:root {
  color-scheme: light;
  --surface-1: #fcfcfb; --surface-2: #f0efec;
  --text-primary: #0b0b0b; --text-secondary: #52514e;
  --border: #d8d7d2;
  --series-1: #2a78d6; --series-2: #eb6834; --series-3: #1baf7a;
  --good: #008300; --serious: #e34948; --warning: #eda100;
}
@media (prefers-color-scheme: dark) {
  :root {
    color-scheme: dark;
    --surface-1: #1a1a19; --surface-2: #383835;
    --text-primary: #ffffff; --text-secondary: #c3c2b7;
    --border: #44443f;
    --series-1: #3987e5; --series-2: #d95926; --series-3: #199e70;
    --good: #1baf7a; --serious: #e66767; --warning: #c98500;
  }
}
* { box-sizing: border-box; }
body { font-family: ui-monospace, Menlo, monospace; margin: 0;
       background: var(--surface-1); color: var(--text-primary); }
nav { display: flex; gap: 2px; padding: 8px 12px; flex-wrap: wrap;
      border-bottom: 1px solid var(--border); position: sticky; top: 0;
      background: var(--surface-1); }
nav a { color: var(--text-secondary); text-decoration: none;
        padding: 4px 10px; border-radius: 6px; font-size: 13px; }
nav a.active { background: var(--surface-2); color: var(--text-primary); }
main { padding: 16px; max-width: 1200px; }
h2 { font-size: 15px; margin: 4px 0 12px; }
.tiles { display: flex; gap: 12px; flex-wrap: wrap; margin-bottom: 16px; }
.tile { background: var(--surface-2); border-radius: 8px;
        padding: 10px 16px; min-width: 120px; }
.tile .v { font-size: 22px; font-weight: 600; }
.tile .k { font-size: 11px; color: var(--text-secondary); }
table { border-collapse: collapse; width: 100%; font-size: 12px; }
th { text-align: left; color: var(--text-secondary); font-weight: 500;
     border-bottom: 1px solid var(--border); padding: 4px 8px;
     position: sticky; top: 41px; background: var(--surface-1); }
td { border-bottom: 1px solid var(--border); padding: 4px 8px;
     max-width: 360px; overflow: hidden; text-overflow: ellipsis;
     white-space: nowrap; }
.status { display: inline-flex; align-items: center; gap: 5px; }
.dot { width: 8px; height: 8px; border-radius: 50%; display: inline-block; }
.ok .dot { background: var(--good); } .bad .dot { background: var(--serious); }
.warn .dot { background: var(--warning); }
#tl-wrap { overflow-x: auto; border: 1px solid var(--border);
           border-radius: 8px; background: var(--surface-1); }
.legend { display: flex; gap: 16px; margin: 8px 0; font-size: 12px;
          color: var(--text-secondary); align-items: center; }
.legend .sw { width: 10px; height: 10px; border-radius: 3px;
              display: inline-block; margin-right: 5px; }
#tooltip { position: fixed; pointer-events: none; display: none;
           background: var(--surface-2); color: var(--text-primary);
           border: 1px solid var(--border); border-radius: 6px;
           padding: 6px 9px; font-size: 12px; z-index: 10; }
.muted { color: var(--text-secondary); font-size: 12px; }
input[type=search] { background: var(--surface-2); border: 1px solid
  var(--border); color: var(--text-primary); border-radius: 6px;
  padding: 4px 8px; margin-bottom: 10px; font: inherit; }
</style></head>
<body>
<nav id="nav"></nav>
<main id="main"></main>
<div id="tooltip"></div>
<script>
"use strict";
const PAGES = ["overview","nodes","actors","tasks","objects",
               "placement_groups","jobs","metrics","events","serve",
               "timeline"];
const $ = (s) => document.querySelector(s);
const esc = (x) => String(x ?? "").replace(/[&<>]/g,
  c => ({"&":"&amp;","<":"&lt;",">":"&gt;"}[c]));
let timer = null, filterText = "";

function nav() {
  const page = location.hash.replace("#","") || "overview";
  $("#nav").innerHTML = PAGES.map(p =>
    `<a href="#${p}" class="${p===page?"active":""}">${p.replace("_"," ")}`
    + `</a>`).join("");
  return page;
}
async function j(url) {
  const r = await fetch(url);
  if (!r.ok) throw new Error(url + " -> " + r.status);
  return r.json();
}
function statusCell(s) {
  const up = ["ALIVE","RUNNING","READY","FINISHED","CREATED","INFO","ok",
              true];
  const bad = ["DEAD","FAILED","LOST","ERROR","error"];
  const cls = up.includes(s) ? "ok" : (bad.includes(s) ? "bad" : "warn");
  return `<span class="status ${cls}"><span class="dot"></span>`
       + `${esc(s)}</span>`;
}
function table(rows, cols, statusCols) {
  statusCols = statusCols || [];
  const f = filterText.toLowerCase();
  const shown = f ? rows.filter(r =>
    JSON.stringify(r).toLowerCase().includes(f)) : rows;
  return `<input type="search" placeholder="filter…" value="${esc(filterText)}"
    oninput="filterText=this.value;render(false)">
    <div class="muted">${shown.length} of ${rows.length} rows</div>
    <table><tr>${cols.map(c=>`<th>${c}</th>`).join("")}</tr>` +
    shown.slice(0, 200).map(r => "<tr>" + cols.map(c => {
      let v = r[c];
      if (v && typeof v === "object") v = JSON.stringify(v);
      return "<td>" + (statusCols.includes(c) ? statusCell(r[c])
                                              : esc(v)) + "</td>";
    }).join("") + "</tr>").join("") + "</table>";
}
function tiles(list) {
  return `<div class="tiles">` + list.map(([k, v]) =>
    `<div class="tile"><div class="v">${esc(v)}</div>` +
    `<div class="k">${esc(k)}</div></div>`).join("") + `</div>`;
}

const RENDER = {
  async overview() {
    const [c, s] = await Promise.all([j("/api/cluster"),
                                      j("/api/summary/tasks")]);
    const rt = c.resources_total || {}, ra = c.resources_available || {};
    const states = Object.entries(s.by_state || {})
      .map(([k, v]) => `${k}: ${v}`).join("  ") || "none";
    return `<h2>cluster</h2>` + tiles([
      ["nodes", c.nodes],
      ["CPU avail / total", `${ra.CPU ?? 0} / ${rt.CPU ?? 0}`],
      ["TPU avail / total", `${ra.TPU ?? 0} / ${rt.TPU ?? 0}`],
      ["tasks seen", s.total ?? 0],
    ]) + `<h2>task states</h2><div class="muted">${esc(states)}</div>`;
  },
  async nodes() {
    return `<h2>nodes</h2>` + table(await j("/api/nodes"),
      ["node_idx","alive","is_remote","resources_total",
       "resources_available","labels"], ["alive"]);
  },
  async actors() {
    return `<h2>actors</h2>` + table(await j("/api/actors"),
      ["actor_id","class_name","name","state","node_idx","pid",
       "num_restarts"], ["state"]);
  },
  async tasks() {
    const [rows, sum] = await Promise.all([j("/api/tasks"),
                                           j("/api/summary/tasks")]);
    const byState = {};
    for (const counts of Object.values(sum.by_func_name || {}))
      for (const [st, n] of Object.entries(counts))
        byState[st] = (byState[st] || 0) + n;
    const states = Object.entries(byState);
    const enriched = rows.map(r => {
      const ph = r.phase_ms || {};
      const f = v => v === undefined ? "" : v.toFixed(1);
      return {...r, sched_wait_ms: f(ph.sched_wait),
              arg_fetch_ms: f(ph.arg_fetch), exec_ms: f(ph.exec),
              e2e_ms: f(ph.e2e),
              straggler: r.straggler ? "STRAGGLER" : ""};
    });
    return `<h2>tasks</h2>` + (states.length ? tiles(states) : "") +
      table(enriched, ["task_id","name","state","node_idx","worker_id",
                   "sched_wait_ms","arg_fetch_ms","exec_ms","e2e_ms",
                   "straggler"], ["state"]);
  },
  async objects() {
    return `<h2>objects</h2>` + table(await j("/api/objects"),
      ["object_id","size_bytes","node_idx","spilled","pinned"]);
  },
  async placement_groups() {
    return `<h2>placement groups</h2>` +
      table(await j("/api/placement_groups"),
            ["pg_id","name","strategy","state","bundles"], ["state"]);
  },
  async jobs() {
    return `<h2>jobs</h2>` + table(await j("/api/jobs"),
      ["job_id","entrypoint","status","submitted_at","message"],
      ["status"]);
  },
  async metrics() {
    const rows = await j("/api/metrics");
    return `<h2>metrics</h2>` + table(rows,
      ["name","type","tags","value","description"]);
  },
  async events() {
    const rows = await j("/api/cluster_events");
    rows.reverse();  // newest first
    for (const r of rows)
      r.when = new Date(r.ts * 1000).toLocaleTimeString();
    const bySev = {};
    for (const r of rows) bySev[r.severity] = (bySev[r.severity]||0) + 1;
    return `<h2>cluster events</h2>` +
      tiles(Object.entries(bySev)) +
      table(rows, ["when","severity","type","source","node_idx",
                   "entity_id","message"], ["severity"]);
  },
  async serve() {
    let apps;
    try { apps = await j("/api/serve/applications"); }
    catch (e) { return `<h2>serve</h2><div class="muted">serve not `
                     + `running</div>`; }
    return `<h2>serve deployments</h2>` + table(apps,
      ["app","deployment","target_replicas","running_replicas","version"],
      []);
  },
  async timeline() {
    const ev = (await j("/api/timeline")).filter(e => e.ph === "X");
    if (!ev.length) return `<h2>timeline</h2>` +
      `<div class="muted">no complete-span events yet</div>`;
    const t0 = Math.min(...ev.map(e => e.ts));
    const t1 = Math.max(...ev.map(e => e.ts + (e.dur || 0)));
    const lanes = [...new Set(ev.map(e => `${e.pid}/${e.tid}`))].sort();
    const CATS = ["task","span","actor"];
    const color = (e) => {
      const c = (e.cat || "task").toLowerCase();
      const i = CATS.indexOf(CATS.find(k => c.includes(k)) ?? "task");
      return `var(--series-${(i < 0 ? 0 : i) + 1})`;
    };
    const W = 1040, H = lanes.length * 26 + 30, L = 150;
    const sx = (t) => L + (t - t0) / Math.max(t1 - t0, 1) * (W - L - 16);
    let bars = "";
    for (const e of ev.slice(-500)) {
      const y = lanes.indexOf(`${e.pid}/${e.tid}`) * 26 + 24;
      const x = sx(e.ts), w = Math.max(sx(e.ts + (e.dur || 0)) - x, 2);
      bars += `<rect x="${x.toFixed(1)}" y="${y}" width="${w.toFixed(1)}"
        height="14" rx="4" fill="${color(e)}" data-tip="${esc(e.name)}
        — ${((e.dur||0)/1000).toFixed(2)} ms"></rect>`;
    }
    const labels = lanes.map((l, i) =>
      `<text x="4" y="${i * 26 + 35}" fill="var(--text-secondary)"
       font-size="11">${esc(l.length > 22 ? l.slice(0, 22) + "…" : l)}
       </text>`).join("");
    return `<h2>timeline <span class="muted">(${ev.length} events,
      ${((t1 - t0) / 1e6).toFixed(2)} s window)</span></h2>
      <div class="legend">
        <span><span class="sw" style="background:var(--series-1)"></span>
        task</span>
        <span><span class="sw" style="background:var(--series-2)"></span>
        span</span>
        <span><span class="sw" style="background:var(--series-3)"></span>
        actor</span></div>
      <div id="tl-wrap"><svg width="${W}" height="${H}"
        font-family="inherit">${labels}${bars}</svg></div>`;
  },
};

async function render(resetFilter = true) {
  if (resetFilter) filterText = "";
  const page = nav();
  try {
    $("#main").innerHTML = await (RENDER[page] || RENDER.overview)();
  } catch (e) {
    $("#main").innerHTML = `<div class="muted">error: ${esc(e)}</div>`;
  }
}
window.addEventListener("hashchange", () => render());
document.addEventListener("mousemove", (ev) => {
  const tgt = ev.target.closest("[data-tip]");
  const tip = $("#tooltip");
  if (tgt) {
    tip.style.display = "block";
    tip.textContent = tgt.getAttribute("data-tip");
    tip.style.left = (ev.clientX + 14) + "px";
    tip.style.top = (ev.clientY + 10) + "px";
  } else tip.style.display = "none";
});
render();
timer = setInterval(() => {
  if (!document.hidden && !filterText) render(false);
}, 2000);
</script></body></html>"""
