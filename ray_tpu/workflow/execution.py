"""Workflow executor: topological DAG run with durable step checkpoints.

Ref analog: python/ray/workflow/workflow_executor.py:32 (the in-flight
dict of step futures + completion persistence) and workflow/api.py (the
public run/resume surface). Differences by design: storage is a local
directory tree (the reference's filesystem storage backend) and the DAG is
the general ray_tpu.dag IR — no separate @workflow.step decorator layer
(the reference also moved to plain dag.bind graphs).

Layout: ``{base}/{workflow_id}/dag.pkl`` (the pickled DAG, so resume works
in a fresh process), ``steps/{step_id}.pkl`` (one per completed step),
``status`` (RUNNING | SUCCESSFUL | RESUMABLE | FAILED — FAILED means the
DAG itself is invalid and resume cannot help).
"""

from __future__ import annotations

import os
import pickle
import shutil
from typing import Any, Dict, List, Optional

import ray_tpu
from ray_tpu.dag import ClassNode, DAGNode, InputNode


class WorkflowStatus:
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    RESUMABLE = "RESUMABLE"


_storage_base = os.environ.get("RAY_TPU_WORKFLOW_STORAGE",
                               "/tmp/ray_tpu/workflows")


def init(storage: Optional[str] = None):
    """Set the workflow storage root (reference: workflow.init(storage))."""
    global _storage_base
    if storage:
        _storage_base = storage


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage_base, workflow_id)


def _write_status(workflow_id: str, status: str):
    with open(os.path.join(_wf_dir(workflow_id), "status"), "w") as f:
        f.write(status)


# ------------------------------------------------------------ topology


def _topo_order(root: DAGNode) -> List[DAGNode]:
    """Stable DFS postorder — step ids must be identical across runs of
    the same (unpickled) DAG for resume to match checkpoints."""
    order: List[DAGNode] = []
    seen: set = set()

    def visit(node: DAGNode):
        if id(node) in seen:
            return
        seen.add(id(node))
        deps = list(node._bound_args) + list(node._bound_kwargs.values())
        if hasattr(node, "_class_node"):
            deps.append(node._class_node)
        for d in deps:
            if isinstance(d, DAGNode):
                visit(d)
        order.append(node)

    visit(root)
    return order


def _step_id(index: int, node: DAGNode) -> str:
    name = getattr(getattr(node, "_remote_fn", None), "_name", "") or \
        type(node).__name__.lower()
    return f"step_{index}_{name}"


# ------------------------------------------------------------ execution


def _execute(workflow_id: str, root: DAGNode, input_value) -> Any:
    """Run the DAG: submit steps whose deps are ready, persist each step
    result as it lands, and surface the root's value."""
    wf_dir = _wf_dir(workflow_id)
    steps_dir = os.path.join(wf_dir, "steps")
    os.makedirs(steps_dir, exist_ok=True)
    order = _topo_order(root)
    for node in order:
        if isinstance(node, ClassNode):
            _write_status(workflow_id, WorkflowStatus.FAILED)
            raise ValueError(
                "workflows checkpoint pure task DAGs; actor (ClassNode) "
                "steps are not durable — use a FunctionNode graph")

    values: Dict[int, Any] = {}      # id(node) -> checkpointed value
    refs: Dict[int, Any] = {}        # id(node) -> in-flight ObjectRef
    ref_to_node: Dict[Any, DAGNode] = {}
    step_ids = {id(n): _step_id(i, n) for i, n in enumerate(order)}

    def resolve(a):
        if isinstance(a, InputNode):
            return input_value
        if isinstance(a, DAGNode):
            return values[id(a)] if id(a) in values else refs[id(a)]
        return a

    _write_status(workflow_id, WorkflowStatus.RUNNING)
    try:
        for node in order:
            if isinstance(node, InputNode):
                continue
            ckpt = os.path.join(steps_dir, step_ids[id(node)] + ".pkl")
            if os.path.exists(ckpt):
                with open(ckpt, "rb") as f:
                    values[id(node)] = pickle.load(f)
                continue
            args = [resolve(a) for a in node._bound_args]
            kwargs = {k: resolve(v)
                      for k, v in node._bound_kwargs.items()}
            ref = node._remote_fn.remote(*args, **kwargs)
            refs[id(node)] = ref
            ref_to_node[ref] = node

        # persist results in completion order (reference: executor's
        # in-flight dict + checkpoint-on-complete)
        outstanding = list(ref_to_node)
        while outstanding:
            done, outstanding = ray_tpu.wait(
                outstanding, num_returns=1, timeout=None)
            for ref in done:
                node = ref_to_node[ref]
                value = ray_tpu.get(ref)
                sid = step_ids[id(node)]
                tmp = os.path.join(steps_dir, sid + ".tmp")
                with open(tmp, "wb") as f:
                    pickle.dump(value, f, protocol=5)
                os.replace(tmp, os.path.join(steps_dir, sid + ".pkl"))
                values[id(node)] = value
    except Exception:
        _write_status(workflow_id, WorkflowStatus.RESUMABLE)
        raise
    out = values[id(order[-1])]
    tmp = os.path.join(wf_dir, "output.tmp")
    with open(tmp, "wb") as f:
        pickle.dump(out, f, protocol=5)
    os.replace(tmp, os.path.join(wf_dir, "output.pkl"))
    _write_status(workflow_id, WorkflowStatus.SUCCESSFUL)
    return out


# ------------------------------------------------------------ public API


def run(dag: DAGNode, *, workflow_id: Optional[str] = None,
        input: Any = None) -> Any:  # noqa: A002 - ref-parity kwarg
    """Execute a DAG durably; returns the final output value."""
    import uuid

    workflow_id = workflow_id or f"wf_{uuid.uuid4().hex[:10]}"
    wf_dir = _wf_dir(workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    from ray_tpu.core.serialization import dumps as _dumps

    with open(os.path.join(wf_dir, "dag.pkl"), "wb") as f:
        f.write(_dumps((dag, input)))
    return _execute(workflow_id, dag, input)


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None,
              input: Any = None):
    """Like run(), but returns a concurrent.futures.Future."""
    import concurrent.futures
    import uuid

    workflow_id = workflow_id or f"wf_{uuid.uuid4().hex[:10]}"
    pool = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    fut = pool.submit(run, dag, workflow_id=workflow_id, input=input)
    fut.workflow_id = workflow_id
    pool.shutdown(wait=False)
    return fut


def resume(workflow_id: str) -> Any:
    """Re-run a RESUMABLE/failed workflow; completed steps short-circuit
    from their checkpoints (reference: workflow.resume)."""
    wf_dir = _wf_dir(workflow_id)
    dag_path = os.path.join(wf_dir, "dag.pkl")
    if not os.path.exists(dag_path):
        raise ValueError(f"no such workflow: {workflow_id}")
    from ray_tpu.core.serialization import loads as _loads

    with open(dag_path, "rb") as f:
        dag, input_value = _loads(f.read())
    return _execute(workflow_id, dag, input_value)


def get_status(workflow_id: str) -> str:
    try:
        with open(os.path.join(_wf_dir(workflow_id), "status")) as f:
            return f.read().strip()
    except OSError:
        raise ValueError(f"no such workflow: {workflow_id}")


def get_output(workflow_id: str) -> Any:
    """Output of a SUCCESSFUL workflow (reference: workflow.get_output)."""
    path = os.path.join(_wf_dir(workflow_id), "output.pkl")
    if not os.path.exists(path):
        status = get_status(workflow_id)
        raise ValueError(
            f"workflow {workflow_id} has no output (status: {status})")
    with open(path, "rb") as f:
        return pickle.load(f)


def list_all() -> List[tuple]:
    """[(workflow_id, status)] for every stored workflow."""
    if not os.path.isdir(_storage_base):
        return []
    out = []
    for wid in sorted(os.listdir(_storage_base)):
        try:
            out.append((wid, get_status(wid)))
        except ValueError:
            continue
    return out


def delete(workflow_id: str):
    shutil.rmtree(_wf_dir(workflow_id), ignore_errors=True)
