"""Durable workflows: run a task DAG with per-step checkpoints + resume.

Ref parity: ray.workflow (python/ray/workflow/api.py run/run_async/resume/
get_status/get_output/list_all; workflow_executor.py:32 executes the DAG
step-by-step, checkpointing every step result to storage so a crashed or
cancelled workflow resumes from its last completed step rather than
rerunning from scratch).
"""

from ray_tpu.workflow.execution import (WorkflowStatus, delete, get_output,
                                        get_status, init, list_all, resume,
                                        run, run_async)

__all__ = ["run", "run_async", "resume", "get_status", "get_output",
           "list_all", "delete", "init", "WorkflowStatus"]

from ray_tpu.usage_stats import record_library_usage as _rlu
_rlu("workflow")
del _rlu
