"""Offline RL: logged-experience datasets, behavior cloning, discrete CQL.

Ref analogs: rllib/offline/ (JsonWriter/JsonReader over logged
SampleBatches, `input_="dataset"` configs) and the offline algorithms
(rllib/algorithms/bc, rllib/algorithms/cql). Re-design: datasets are
.npz shards of column arrays (numpy-native, zero-copy into jnp); both
learners are single jitted XLA updates; evaluation runs the greedy
policy in a fresh env on the driver (no rollout fleet — offline
algorithms never sample).

CQL here is the discrete-action form: the DQN double-Q TD loss plus the
conservative penalty alpha * E[logsumexp_a Q(s,a) - Q(s, a_data)]
(Kumar et al. 2020, eq. 4 with the sampled-action term collapsed to the
closed discrete form).
"""

from __future__ import annotations

import glob
import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import sample_batch as SB
from .algorithm import Algorithm, AlgorithmConfig
from .env import VectorEnv, make_env
from .models import entropy_of, forward, init_actor_critic, logp_of
from .sample_batch import SampleBatch, concat_samples

# ---------------------------------------------------------------- dataset IO


def save_batches(path: str, batches: List[SampleBatch]) -> List[str]:
    """Write SampleBatches as .npz shards under ``path``; returns files.

    Ref analog: rllib/offline/json_writer.py (one file per batch; columns
    keyed exactly as SampleBatch keys)."""
    os.makedirs(path, exist_ok=True)
    files = []
    for i, b in enumerate(batches):
        f = os.path.join(path, f"batch-{i:05d}.npz")
        np.savez_compressed(f, **{k: np.asarray(v) for k, v in b.items()})
        files.append(f)
    return files


def load_batches(path: str) -> SampleBatch:
    """Read every shard under ``path`` into one concatenated SampleBatch
    (ref: rllib/offline/json_reader.py)."""
    files = sorted(glob.glob(os.path.join(path, "*.npz")))
    if not files:
        raise FileNotFoundError(f"no .npz shards under {path}")
    batches = []
    for f in files:
        with np.load(f) as z:
            batches.append(SampleBatch({k: z[k] for k in z.files}))
    return concat_samples(batches)


def collect_dataset(env_name, path: str, *, num_steps: int = 4096,
                    num_envs: int = 8, epsilon: float = 0.3,
                    weights: Optional[Dict[str, np.ndarray]] = None,
                    hiddens=(64, 64), seed: int = 0) -> List[str]:
    """Roll an epsilon-greedy behavior policy and log (s, a, r, s', done)
    shards — the offline-RL data-generation step (ref: the reference's
    `rllib train ... --output` logged-experience path)."""
    vec = VectorEnv(env_name, num_envs, seed=seed)
    params = weights or {
        k: np.asarray(v) for k, v in init_actor_critic(
            jax.random.key(seed), vec.observation_dim, vec.num_actions,
            hiddens).items()}
    rng = np.random.default_rng(seed)
    T = num_steps // num_envs
    obs_buf = np.zeros((T, num_envs, vec.observation_dim), np.float32)
    act_buf = np.zeros((T, num_envs), np.int64)
    rew_buf = np.zeros((T, num_envs), np.float32)
    done_buf = np.zeros((T, num_envs), np.bool_)
    next_buf = np.zeros((T, num_envs, vec.observation_dim), np.float32)
    obs = vec.obs
    for t in range(T):
        logits, _ = forward(params, jnp.asarray(obs))
        acts = np.asarray(jnp.argmax(logits, axis=-1))
        explore = rng.random(num_envs) < epsilon
        acts = np.where(explore,
                        rng.integers(0, vec.num_actions, num_envs), acts)
        obs_buf[t] = obs
        act_buf[t] = acts
        obs, rews, dones = vec.step(acts)
        next_buf[t] = vec.final_obs
        rew_buf[t] = rews
        done_buf[t] = dones & ~vec.truncateds
    flat = lambda x: x.reshape((T * num_envs,) + x.shape[2:])  # noqa: E731
    batch = SampleBatch({SB.OBS: flat(obs_buf), SB.ACTIONS: flat(act_buf),
                         SB.REWARDS: flat(rew_buf),
                         SB.DONES: flat(done_buf),
                         SB.NEXT_OBS: flat(next_buf)})
    return save_batches(path, [batch])


# ------------------------------------------------------------- algorithms


class OfflineConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class)
        self.input_path = ""          # directory of .npz shards
        self.train_batch_size = 256
        self.num_updates_per_iter = 64
        self.evaluation_episodes = 3


class _OfflineAlgorithm(Algorithm):
    """Shared shape: load the dataset once, minibatch-update per step,
    evaluate greedily in a fresh env."""

    _config_cls = OfflineConfig

    def setup(self, config):
        cfg = config.get("__algo_config__")
        cfg = cfg.copy() if cfg is not None else self.get_default_config()
        cfg.update_from_dict(
            {k: v for k, v in config.items() if k != "__algo_config__"})
        self.algo_config = cfg
        if not cfg.input_path:
            raise ValueError(
                "offline algorithms need config.offline_data(input_path=...)")
        self.dataset = load_batches(cfg.input_path)
        probe = make_env(cfg.env)
        self._obs_dim = probe.observation_dim
        self._num_actions = probe.num_actions
        self._rng = np.random.default_rng(cfg.seed)
        self._num_env_steps = 0  # offline: no env interaction
        self._make_learner(cfg)

    def _make_learner(self, cfg):
        raise NotImplementedError

    def _minibatch(self) -> SampleBatch:
        n = self.dataset.count
        idx = self._rng.integers(0, n, self.algo_config.train_batch_size)
        return SampleBatch({k: v[idx] for k, v in self.dataset.items()})

    def evaluate_policy(self) -> float:
        env = make_env(self.algo_config.env)
        rets = []
        w = self.get_policy_weights()
        for ep in range(self.algo_config.evaluation_episodes):
            obs = env.reset(seed=40_000 + self.iteration * 10 + ep)
            total, done = 0.0, False
            while not done:
                logits, _ = forward(w, jnp.asarray(obs[None]))
                obs, r, done, _ = env.step(int(jnp.argmax(logits[0])))
                total += r
            rets.append(total)
        return float(np.mean(rets))

    def step(self) -> dict:
        metrics = self.training_step()
        metrics["episode_reward_mean"] = self.evaluate_policy()
        metrics["dataset_size"] = self.dataset.count
        return metrics

    def cleanup(self):
        pass


class BCConfig(OfflineConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BC)

    def offline_data(self, *, input_path: str) -> "BCConfig":
        self.input_path = input_path
        return self


class BC(_OfflineAlgorithm):
    """Behavior cloning: maximize log pi(a_data | s) (ref:
    rllib/algorithms/bc/bc.py — MARWIL with beta=0)."""

    _config_cls = BCConfig

    def _make_learner(self, cfg):
        self.params = init_actor_critic(
            jax.random.key(cfg.seed), self._obs_dim, self._num_actions,
            cfg.model_hiddens)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        ent_coeff = cfg.entropy_coeff

        def loss_fn(params, batch):
            logits, _ = forward(params, batch[SB.OBS])
            logp = logp_of(logits, batch[SB.ACTIONS])
            ent = entropy_of(logits).mean()
            loss = -logp.mean() - ent_coeff * ent
            return loss, {"bc_logp": logp.mean(), "entropy": ent}

        @jax.jit
        def train_step(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        self._train_step = train_step

    def training_step(self) -> dict:
        metrics = {}
        for _ in range(self.algo_config.num_updates_per_iter):
            mb = self._minibatch()
            self.params, self.opt_state, metrics = self._train_step(
                self.params, self.opt_state,
                {SB.OBS: jnp.asarray(mb[SB.OBS]),
                 SB.ACTIONS: jnp.asarray(mb[SB.ACTIONS])})
        return {k: float(v) for k, v in metrics.items()}

    def get_policy_weights(self):
        return {k: np.asarray(v) for k, v in self.params.items()}

    def save_checkpoint(self):
        return {"weights": self.get_policy_weights()}

    def load_checkpoint(self, checkpoint):
        if checkpoint:
            self.params = {k: jnp.asarray(v)
                           for k, v in checkpoint["weights"].items()}


class CQLConfig(OfflineConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or CQL)
        self.cql_alpha = 1.0
        self.target_update_every = 8  # learner updates between target syncs
        self.lr = 3e-4

    def offline_data(self, *, input_path: str) -> "CQLConfig":
        self.input_path = input_path
        return self


class CQL(_OfflineAlgorithm):
    """Discrete conservative Q-learning: double-DQN TD loss on logged
    transitions + alpha * (logsumexp_a Q - Q(s, a_data))."""

    _config_cls = CQLConfig

    def _make_learner(self, cfg):
        self.params = init_actor_critic(
            jax.random.key(cfg.seed), self._obs_dim, self._num_actions,
            cfg.model_hiddens)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        gamma, alpha = cfg.gamma, cfg.cql_alpha

        def loss_fn(params, target_params, batch):
            q_all, _ = forward(params, batch[SB.OBS])
            q_data = jnp.take_along_axis(
                q_all, batch[SB.ACTIONS][:, None], axis=1).squeeze(-1)
            q_next_t, _ = forward(target_params, batch[SB.NEXT_OBS])
            q_next_o, _ = forward(params, batch[SB.NEXT_OBS])
            a_star = jnp.argmax(q_next_o, axis=1)
            q_next = jnp.take_along_axis(
                q_next_t, a_star[:, None], axis=1).squeeze(-1)
            not_done = 1.0 - batch[SB.DONES].astype(jnp.float32)
            target = batch[SB.REWARDS] + gamma * not_done * q_next
            td = optax.huber_loss(
                q_data, jax.lax.stop_gradient(target), delta=1.0).mean()
            # conservative penalty: push down unseen actions' Q
            cql = (jax.nn.logsumexp(q_all, axis=1) - q_data).mean()
            loss = td + alpha * cql
            return loss, {"td_loss": td, "cql_penalty": cql,
                          "q_data_mean": q_data.mean()}

        @jax.jit
        def train_step(params, target_params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        self._train_step = train_step
        self._updates = 0

    def training_step(self) -> dict:
        metrics = {}
        for _ in range(self.algo_config.num_updates_per_iter):
            mb = self._minibatch()
            self.params, self.opt_state, metrics = self._train_step(
                self.params, self.target_params, self.opt_state,
                {k: jnp.asarray(v) for k, v in mb.items()
                 if k in (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.DONES,
                          SB.NEXT_OBS)})
            self._updates += 1
            if self._updates % self.algo_config.target_update_every == 0:
                self.target_params = jax.tree.map(jnp.copy, self.params)
        return {k: float(v) for k, v in metrics.items()}

    def get_policy_weights(self):
        return {k: np.asarray(v) for k, v in self.params.items()}

    def save_checkpoint(self):
        return {"weights": self.get_policy_weights()}

    def load_checkpoint(self, checkpoint):
        if checkpoint:
            self.params = {k: jnp.asarray(v)
                           for k, v in checkpoint["weights"].items()}
            self.target_params = jax.tree.map(jnp.copy, self.params)


class MARWILConfig(OfflineConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or MARWIL)
        self.beta = 1.0            # 0 => exact BC (ref: marwil.py beta)
        self.vf_coeff = 1.0
        self.ma_adv_momentum = 1e-2  # moving-average advantage norm rate

    def offline_data(self, *, input_path: str) -> "MARWILConfig":
        self.input_path = input_path
        return self


class MARWIL(_OfflineAlgorithm):
    """Monotonic advantage re-weighted imitation learning (Wang et al.
    2018). Ref analog: rllib/algorithms/marwil/marwil.py — BC whose
    log-likelihood is weighted by exp(beta * normalized advantage), with
    a critic supplying the baseline. Advantages here are one-step TD
    residuals r + gamma*V(s') - V(s) against the jointly-trained value
    head (the logged .npz shards carry transitions, not whole episodes,
    so Monte-Carlo returns are not reconstructible), normalized by the
    reference's moving-average-of-squares estimate.
    """

    _config_cls = MARWILConfig

    def _make_learner(self, cfg):
        self.params = init_actor_critic(
            jax.random.key(cfg.seed), self._obs_dim, self._num_actions,
            cfg.model_hiddens)
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        # moving average of squared advantages: the exp() weight is
        # exp(beta * adv / sqrt(ma)) so beta stays scale-free
        self.ma_adv_sq = jnp.asarray(1.0)
        beta, vf_coeff = cfg.beta, cfg.vf_coeff
        ent_coeff, gamma = cfg.entropy_coeff, cfg.gamma
        momentum = cfg.ma_adv_momentum

        def loss_fn(params, batch, ma_adv_sq):
            logits, values = forward(params, batch[SB.OBS])
            _, v_next = forward(params, batch[SB.NEXT_OBS])
            not_done = 1.0 - batch[SB.DONES].astype(jnp.float32)
            target = jax.lax.stop_gradient(
                batch[SB.REWARDS] + gamma * not_done * v_next)
            adv = target - values
            vf_loss = jnp.mean(adv ** 2)
            ma = ma_adv_sq + momentum * (
                jnp.mean(jax.lax.stop_gradient(adv) ** 2) - ma_adv_sq)
            w = jnp.exp(jnp.clip(
                beta * jax.lax.stop_gradient(adv) / jnp.sqrt(ma + 1e-8),
                -10.0, 10.0))
            logp = logp_of(logits, batch[SB.ACTIONS])
            ent = entropy_of(logits).mean()
            policy_loss = -jnp.mean(w * logp)
            loss = policy_loss + vf_coeff * vf_loss - ent_coeff * ent
            return loss, ({"policy_loss": policy_loss, "vf_loss": vf_loss,
                           "adv_weight_mean": w.mean(), "entropy": ent},
                          ma)

        @jax.jit
        def train_step(params, opt_state, ma_adv_sq, batch):
            (loss, (metrics, ma)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch, ma_adv_sq)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, ma, metrics

        self._train_step = train_step

    def training_step(self) -> dict:
        metrics = {}
        for _ in range(self.algo_config.num_updates_per_iter):
            mb = self._minibatch()
            self.params, self.opt_state, self.ma_adv_sq, metrics = \
                self._train_step(
                    self.params, self.opt_state, self.ma_adv_sq,
                    {k: jnp.asarray(v) for k, v in mb.items()
                     if k in (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.DONES,
                              SB.NEXT_OBS)})
        return {k: float(v) for k, v in metrics.items()}

    def get_policy_weights(self):
        return {k: np.asarray(v) for k, v in self.params.items()}

    def save_checkpoint(self):
        return {"weights": self.get_policy_weights(),
                "ma_adv_sq": float(self.ma_adv_sq)}

    def load_checkpoint(self, checkpoint):
        if checkpoint:
            self.params = {k: jnp.asarray(v)
                           for k, v in checkpoint["weights"].items()}
            self.ma_adv_sq = jnp.asarray(
                checkpoint.get("ma_adv_sq", 1.0))
