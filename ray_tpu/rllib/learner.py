"""Learners: jitted JAX updates (PPO clipped surrogate, IMPALA V-trace).

Ref analogs: rllib/core/learner/learner.py:229 (Learner.update :1230) and
learner_group.py:61 — re-designed TPU-first: the whole SGD minibatch step
(forward+backward+adam) is ONE jitted XLA program; a LearnerGroup of N
learner actors does synchronous data-parallel updates by averaging grads
(the JAX analog of the reference's TorchDDPRLModule wrapping).

V-trace follows Espeholt et al. 2018 (IMPALA), computed with lax.scan.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import sample_batch as SB
from .models import entropy_of, forward, init_actor_critic, logp_of
from .sample_batch import SampleBatch


class PPOLearner:
    """Clipped-surrogate PPO (ref: rllib/algorithms/ppo/ppo_torch_policy.py
    loss; here one jitted minibatch step)."""

    def __init__(self, obs_dim: int, num_actions: int, *, lr: float = 3e-4,
                 clip_param: float = 0.2, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.01, grad_clip: float = 0.5,
                 hiddens=(64, 64), seed: int = 0):
        self.params = init_actor_critic(jax.random.key(seed), obs_dim,
                                        num_actions, hiddens)
        self.tx = optax.chain(optax.clip_by_global_norm(grad_clip),
                              optax.adam(lr))
        self.opt_state = self.tx.init(self.params)

        def loss_fn(params, batch):
            logits, values = forward(params, batch[SB.OBS])
            logp = logp_of(logits, batch[SB.ACTIONS])
            ratio = jnp.exp(logp - batch[SB.ACTION_LOGP])
            adv = batch[SB.ADVANTAGES]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
            pi_loss = -surr.mean()
            vf_loss = jnp.mean((values - batch[SB.VALUE_TARGETS]) ** 2)
            ent = entropy_of(logits).mean()
            total = pi_loss + vf_coeff * vf_loss - entropy_coeff * ent
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": ent,
                           "kl": jnp.mean(batch[SB.ACTION_LOGP] - logp)}

        @jax.jit
        def train_step(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        @jax.jit
        def grad_step(params, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            metrics["total_loss"] = loss
            return grads, metrics

        @jax.jit
        def apply_grads_step(params, opt_state, grads):
            updates, opt_state = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._train_step = train_step
        self._grad_step = grad_step
        self._apply_grads = apply_grads_step

    # ----- local update path -----

    def update(self, batch: SampleBatch, *, num_epochs: int = 4,
               minibatch_size: int = 128, seed: int = 0) -> dict:
        rng = np.random.default_rng(seed)
        metrics = {}
        dev = {k: jnp.asarray(v) for k, v in batch.items()}
        for _ in range(num_epochs):
            shuffled = SampleBatch(batch).shuffle(rng)
            got_one = False
            for mb in shuffled.minibatches(minibatch_size):
                got_one = True
                self.params, self.opt_state, metrics = self._train_step(
                    self.params, self.opt_state,
                    {k: jnp.asarray(v) for k, v in mb.items()})
            if not got_one:  # batch smaller than one minibatch
                self.params, self.opt_state, metrics = self._train_step(
                    self.params, self.opt_state, dev)
        return {k: float(v) for k, v in metrics.items()}

    # ----- distributed (grad-averaging) path -----

    def compute_grads(self, batch: SampleBatch):
        grads, metrics = self._grad_step(
            self.params, {k: jnp.asarray(v) for k, v in batch.items()})
        return ({k: np.asarray(v) for k, v in grads.items()},
                {k: float(v) for k, v in metrics.items()})

    def apply_grads(self, grads: Dict[str, np.ndarray]):
        self.params, self.opt_state = self._apply_grads(
            self.params, self.opt_state,
            {k: jnp.asarray(v) for k, v in grads.items()})

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.params.items()}

    def set_weights(self, weights: Dict[str, np.ndarray]):
        self.params = {k: jnp.asarray(v) for k, v in weights.items()}


def vtrace(behaviour_logp, target_logp, rewards, dones, values,
           bootstrap_value, gamma: float, clip_rho: float = 1.0,
           clip_c: float = 1.0):
    """V-trace targets (Espeholt et al. 2018, eqs. 1-2), time-major [T, N].

    Returns (vs [T,N], pg_advantages [T,N]).
    """
    rho = jnp.minimum(jnp.exp(target_logp - behaviour_logp), clip_rho)
    c = jnp.minimum(jnp.exp(target_logp - behaviour_logp), clip_c)
    discounts = gamma * (1.0 - dones.astype(jnp.float32))
    values_next = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = rho * (rewards + discounts * values_next - values)

    def scan_fn(acc, xs):
        delta_t, discount_t, c_t = xs
        acc = delta_t + discount_t * c_t * acc
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        scan_fn, jnp.zeros_like(bootstrap_value),
        (deltas, discounts, c), reverse=True)
    vs = values + vs_minus_v
    vs_next = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = rho * (rewards + discounts * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class ImpalaLearner:
    """V-trace actor-critic learner (ref: rllib/algorithms/impala/)."""

    def __init__(self, obs_dim: int, num_actions: int, *, lr: float = 5e-4,
                 gamma: float = 0.99, vf_coeff: float = 0.5,
                 entropy_coeff: float = 0.01, grad_clip: float = 40.0,
                 clip_rho: float = 1.0, clip_c: float = 1.0,
                 hiddens=(64, 64), seed: int = 0):
        self.params = init_actor_critic(jax.random.key(seed), obs_dim,
                                        num_actions, hiddens)
        self.tx = optax.chain(optax.clip_by_global_norm(grad_clip),
                              optax.adam(lr))
        self.opt_state = self.tx.init(self.params)

        def loss_fn(params, batch):
            T, N = batch[SB.ACTIONS].shape
            obs_flat = batch[SB.OBS].reshape(T * N, -1)
            logits, values = forward(params, obs_flat)
            logits = logits.reshape(T, N, -1)
            values = values.reshape(T, N)
            target_logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits),
                batch[SB.ACTIONS][..., None], axis=-1).squeeze(-1)
            _, bootstrap_value = forward(params, batch["bootstrap_obs"])
            vs, pg_adv = vtrace(
                batch[SB.ACTION_LOGP], target_logp, batch[SB.REWARDS],
                batch[SB.DONES], values, bootstrap_value, gamma,
                clip_rho, clip_c)
            pi_loss = -jnp.mean(target_logp * pg_adv)
            vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
            ent = entropy_of(logits.reshape(T * N, -1)).mean()
            total = pi_loss + vf_coeff * vf_loss - entropy_coeff * ent
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": ent}

        @jax.jit
        def train_step(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        self._train_step = train_step

    def update(self, batch: SampleBatch) -> dict:
        self.params, self.opt_state, metrics = self._train_step(
            self.params, self.opt_state,
            {k: jnp.asarray(v) for k, v in batch.items()})
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.params.items()}

    def set_weights(self, weights: Dict[str, np.ndarray]):
        self.params = {k: jnp.asarray(v) for k, v in weights.items()}


class LearnerGroup:
    """Synchronous data-parallel group over learner actors.

    Ref analog: rllib/core/learner/learner_group.py:61. ``num_learners=0``
    keeps a single local learner (in-process, owns the accelerator);
    ``num_learners>=1`` spawns learner actors that compute grads on batch
    shards, averaged here and applied everywhere (DDP-equivalent update).
    """

    def __init__(self, make_learner, num_learners: int = 0):
        import ray_tpu

        self.num_learners = num_learners
        if num_learners == 0:
            self.local = make_learner()
            self.remotes = []
        else:
            self.local = make_learner()  # weight source / averaging site

            class _LearnerActor:
                def __init__(self, payload):
                    from ray_tpu.core.serialization import loads
                    self.learner = loads(payload)()

                def compute_grads(self, shard):
                    return self.learner.compute_grads(shard)

                def set_weights(self, w):
                    self.learner.set_weights(w)

                def ping(self):
                    return True

            from ray_tpu.core.serialization import dumps

            payload = dumps(make_learner)
            cls = ray_tpu.remote(_LearnerActor)
            self.remotes = [cls.options(num_cpus=0).remote(payload)
                            for _ in range(num_learners)]
            w = self.local.get_weights()
            ray_tpu.get([r.set_weights.remote(w) for r in self.remotes],
                        timeout=120)

    def update(self, batch: SampleBatch, **kw) -> dict:
        import ray_tpu

        if not self.remotes:
            return self.local.update(batch, **kw) \
                if kw else self.local.update(batch)
        n = len(self.remotes)
        size = batch.count // n
        shards = [batch.slice(i * size, (i + 1) * size) for i in range(n)]
        outs = ray_tpu.get(
            [r.compute_grads.remote(s)
             for r, s in zip(self.remotes, shards)], timeout=300)
        grads = {k: np.mean([g[k] for g, _ in outs], axis=0)
                 for k in outs[0][0]}
        self.local.apply_grads(grads)
        w = self.local.get_weights()
        ray_tpu.get([r.set_weights.remote(w) for r in self.remotes],
                    timeout=120)
        return outs[0][1]

    def get_weights(self):
        return self.local.get_weights()

    def set_weights(self, w):
        import ray_tpu

        self.local.set_weights(w)
        if self.remotes:
            ray_tpu.get([r.set_weights.remote(w) for r in self.remotes],
                        timeout=120)
