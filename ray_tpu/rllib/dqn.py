"""DQN: off-policy Q-learning with replay, target network, double-Q.

Ref analogs: rllib/algorithms/dqn/dqn.py:38 (DQNConfig: buffer/epsilon/
target-update knobs, training_step :637 — sample rollouts -> store ->
replay-sample -> learn -> update priorities -> sync target) and
dqn_rainbow_learner / torch policy losses. TPU-first re-design: the whole
update (double-Q target, Huber loss, Adam step, |TD| for priorities) is
ONE jitted XLA program; the replay buffer hands it a contiguous numpy
batch (replay_buffers.py), so the accelerator never sees Python-loop
assembly.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu

from . import sample_batch as SB
from .algorithm import Algorithm, AlgorithmConfig
from .models import forward, init_actor_critic
from .replay_buffers import PrioritizedReplayBuffer, ReplayBuffer
from .sample_batch import SampleBatch, concat_samples


class DQNConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DQN)
        self.lr = 5e-4
        self.train_batch_size = 64
        self.replay_buffer_capacity = 50_000
        self.prioritized_replay = True
        self.prioritized_replay_alpha = 0.6
        self.prioritized_replay_beta = 0.4
        self.num_steps_sampled_before_learning_starts = 1000
        self.target_network_update_freq = 500   # env steps
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.02
        self.epsilon_timesteps = 10_000
        self.double_q = True
        self.num_updates_per_iter = 32


class DQNLearner:
    """Online + target Q-nets; one jitted double-DQN update."""

    def __init__(self, obs_dim: int, num_actions: int, *, lr: float,
                 gamma: float, hiddens=(64, 64), double_q: bool = True,
                 seed: int = 0):
        self.params = init_actor_critic(
            jax.random.key(seed), obs_dim, num_actions, hiddens)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.opt = optax.adam(lr)
        self.opt_state = self.opt.init(self.params)

        def loss_fn(params, target_params, batch):
            obs = batch[SB.OBS]
            q_all, _ = forward(params, obs)
            q_sel = jnp.take_along_axis(
                q_all, batch[SB.ACTIONS][:, None], axis=1).squeeze(-1)
            q_next_t, _ = forward(target_params, batch[SB.NEXT_OBS])
            if double_q:
                # action choice by the ONLINE net, value by the target net
                q_next_o, _ = forward(params, batch[SB.NEXT_OBS])
                a_star = jnp.argmax(q_next_o, axis=1)
            else:
                a_star = jnp.argmax(q_next_t, axis=1)
            q_next = jnp.take_along_axis(
                q_next_t, a_star[:, None], axis=1).squeeze(-1)
            not_done = 1.0 - batch[SB.DONES].astype(jnp.float32)
            target = batch[SB.REWARDS] + gamma * not_done * q_next
            td = q_sel - jax.lax.stop_gradient(target)
            weights = batch.get("weights")
            huber = optax.huber_loss(td, jnp.zeros_like(td), delta=1.0)
            if weights is not None:
                huber = huber * weights
            return jnp.mean(huber), jnp.abs(td)

        @jax.jit
        def train_step(params, target_params, opt_state, batch):
            (loss, td_abs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td_abs

        self._train_step = train_step

    def update(self, batch: SampleBatch) -> dict:
        # plain dict: dict subclasses are opaque leaves to jax pytrees
        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k in (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.DONES,
                       SB.NEXT_OBS, "weights")}
        self.params, self.opt_state, loss, td_abs = self._train_step(
            self.params, self.target_params, self.opt_state, jb)
        return {"loss": float(loss), "td_abs": np.asarray(td_abs)}

    def sync_target(self):
        """Hard target copy (ref: target_network_update_freq semantics)."""
        self.target_params = jax.tree.map(jnp.copy, self.params)

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.params.items()}

    def set_weights(self, weights: Dict[str, np.ndarray]):
        self.params = {k: jnp.asarray(v) for k, v in weights.items()}
        self.sync_target()


class DQN(Algorithm):
    _config_cls = DQNConfig

    def _make_learner_factory(self, cfg, obs_dim, num_actions):
        def make():
            return DQNLearner(obs_dim, num_actions, lr=cfg.lr,
                              gamma=cfg.gamma, hiddens=cfg.model_hiddens,
                              double_q=cfg.double_q, seed=cfg.seed)

        return make

    def setup(self, config):
        super().setup(config)
        cfg = self.algo_config
        buf_cls = (PrioritizedReplayBuffer if cfg.prioritized_replay
                   else ReplayBuffer)
        kw = ({"alpha": cfg.prioritized_replay_alpha}
              if cfg.prioritized_replay else {})
        self.replay = buf_cls(cfg.replay_buffer_capacity,
                              seed=cfg.seed, **kw)
        self._last_target_sync = 0

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._num_env_steps / max(cfg.epsilon_timesteps, 1))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def training_step(self) -> dict:
        cfg = self.algo_config
        eps = self._epsilon()
        batches = ray_tpu.get(
            [w.sample_transitions.remote(eps) for w in self.workers],
            timeout=300)
        fresh = concat_samples(batches)
        self.replay.add(fresh)
        self._num_env_steps += fresh.count

        metrics = {"env_steps_this_iter": fresh.count, "epsilon": eps,
                   "replay_size": len(self.replay)}
        learner = self.learners.local  # DQN updates are local/single-chip
        if self.replay.num_added >= \
                cfg.num_steps_sampled_before_learning_starts:
            losses = []
            for _ in range(cfg.num_updates_per_iter):
                if cfg.prioritized_replay:
                    sample = self.replay.sample(
                        cfg.train_batch_size,
                        beta=cfg.prioritized_replay_beta)
                else:
                    sample = self.replay.sample(cfg.train_batch_size)
                if sample is None:
                    break
                out = learner.update(sample)
                losses.append(out["loss"])
                self.replay.update_priorities(sample["batch_indexes"],
                                              out["td_abs"])
            if losses:
                metrics["loss"] = float(np.mean(losses))
            # hard target sync every target_network_update_freq env steps
            if self._num_env_steps - self._last_target_sync >= \
                    cfg.target_network_update_freq:
                learner.sync_target()
                self._last_target_sync = self._num_env_steps
            self._sync_weights()
        return metrics
