"""Multi-agent RL: shared environments, per-policy batches and learners.

Ref analogs: rllib/env/multi_agent_env.py:32 (MultiAgentEnv — dict-keyed
obs/rewards/dones per agent), rllib/policy/sample_batch.py:1322
(MultiAgentBatch: policy_id -> SampleBatch + env_steps), and the
policy_mapping_fn config (algorithm_config.multi_agent()). Scoped
TPU-first: one PPO learner per policy (each update one jitted XLA
program); rollouts collect per-policy trajectories on CPU actors and GAE
them per agent before shipping.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu

from . import sample_batch as SB
from .sample_batch import SampleBatch, compute_gae, concat_samples


class MultiAgentEnv:
    """All step/reset dicts are keyed by agent id. "__all__" in dones
    ends the episode (reference semantics)."""

    agent_ids: Tuple[str, ...]
    observation_dim: int
    num_actions: int
    max_episode_steps: int = 500

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, int]
             ) -> Tuple[Dict[str, np.ndarray], Dict[str, float],
                        Dict[str, bool], dict]:
        raise NotImplementedError


class MultiAgentBatch:
    """policy_id -> SampleBatch, plus the env-step count the batches were
    collected over (ref: sample_batch.py:1322)."""

    def __init__(self, policy_batches: Dict[str, SampleBatch],
                 env_steps: int):
        self.policy_batches = policy_batches
        self.env_steps = env_steps

    def __getitem__(self, policy_id: str) -> SampleBatch:
        return self.policy_batches[policy_id]

    @property
    def agent_steps(self) -> int:
        return sum(b.count for b in self.policy_batches.values())

    @staticmethod
    def concat(batches: List["MultiAgentBatch"]) -> "MultiAgentBatch":
        pids = {p for b in batches for p in b.policy_batches}
        merged = {
            pid: concat_samples([b.policy_batches[pid] for b in batches
                                 if pid in b.policy_batches])
            for pid in pids
        }
        return MultiAgentBatch(merged, sum(b.env_steps for b in batches))


class MultiAgentRolloutWorker:
    """Steps ONE multi-agent env; each agent acts with its mapped
    policy's weights; per-agent trajectories are GAE-postprocessed and
    grouped by policy (ref: rollout_worker sample + policy_map)."""

    def __init__(self, env_creator, policy_ids: List[str],
                 policy_mapping_fn: Callable[[str], str],
                 rollout_len: int, gamma: float, lam: float,
                 hiddens=(64, 64), seed: int = 0):
        from .policy import JaxPolicy

        self.env: MultiAgentEnv = env_creator()
        self.policy_ids = list(policy_ids)
        self.mapping = policy_mapping_fn
        self.rollout_len = rollout_len
        self.gamma, self.lam = gamma, lam
        self.policies = {
            pid: JaxPolicy(self.env.observation_dim, self.env.num_actions,
                           hiddens, seed=seed + i)
            for i, pid in enumerate(self.policy_ids)
        }
        self._obs = self.env.reset(seed)
        self._ep_rewards: Dict[str, float] = {}
        self.completed_returns: List[float] = []

    def sample(self) -> MultiAgentBatch:
        # per-agent trajectory columns, grouped later by policy
        traj: Dict[str, Dict[str, list]] = {
            a: {k: [] for k in ("obs", "act", "rew", "done", "logp", "vf")}
            for a in self.env.agent_ids
        }
        for _ in range(self.rollout_len):
            actions: Dict[str, int] = {}
            for agent, obs in self._obs.items():
                pol = self.policies[self.mapping(agent)]
                a, logp, vf, _ = pol.compute_actions(obs[None, :])
                actions[agent] = int(a[0])
                t = traj[agent]
                t["obs"].append(obs)
                t["act"].append(int(a[0]))
                t["logp"].append(float(logp[0]))
                t["vf"].append(float(vf[0]))
            next_obs, rewards, dones, _ = self.env.step(actions)
            for agent in actions:
                traj[agent]["rew"].append(rewards.get(agent, 0.0))
                traj[agent]["done"].append(bool(dones.get(
                    agent, dones.get("__all__", False))))
                self._ep_rewards[agent] = self._ep_rewards.get(
                    agent, 0.0) + rewards.get(agent, 0.0)
            if dones.get("__all__"):
                self.completed_returns.append(
                    sum(self._ep_rewards.values()))
                self._ep_rewards.clear()
                next_obs = self.env.reset()
            self._obs = next_obs

        by_policy: Dict[str, List[SampleBatch]] = {}
        steps = 0
        for agent, t in traj.items():
            if not t["obs"]:
                continue
            steps = max(steps, len(t["obs"]))
            pol = self.policies[self.mapping(agent)]
            obs = np.asarray(t["obs"], np.float32)
            rew = np.asarray(t["rew"], np.float32)[:, None]
            vf = np.asarray(t["vf"], np.float32)[:, None]
            done = np.asarray(t["done"], np.bool_)[:, None]
            last_v = pol.value(self._obs[agent][None, :]) \
                if agent in self._obs else np.zeros(1, np.float32)
            adv, targets = compute_gae(rew, vf, done, last_v,
                                       self.gamma, self.lam)
            by_policy.setdefault(self.mapping(agent), []).append(
                SampleBatch({
                    SB.OBS: obs,
                    SB.ACTIONS: np.asarray(t["act"], np.int64),
                    SB.REWARDS: rew[:, 0],
                    SB.DONES: done[:, 0],
                    SB.ACTION_LOGP: np.asarray(t["logp"], np.float32),
                    SB.VF_PREDS: vf[:, 0],
                    SB.ADVANTAGES: adv[:, 0],
                    SB.VALUE_TARGETS: targets[:, 0],
                }))
        return MultiAgentBatch(
            {pid: concat_samples(bs) for pid, bs in by_policy.items()},
            env_steps=steps)

    def set_weights(self, weights: Dict[str, dict]):
        for pid, w in weights.items():
            self.policies[pid].set_weights(w)

    def episode_metrics(self) -> dict:
        rets, self.completed_returns = self.completed_returns, []
        return {"episode_returns": rets}


class MultiAgentPPO:
    """One PPO learner per policy; each training step samples from the
    rollout actors and updates every policy with ITS agents' experience
    (ref: algorithms/ppo with config.multi_agent(policies=...,
    policy_mapping_fn=...))."""

    def __init__(self, env_creator, *, policies: List[str],
                 policy_mapping_fn: Callable[[str], str],
                 num_rollout_workers: int = 2, rollout_len: int = 128,
                 gamma: float = 0.99, lam: float = 0.95, lr: float = 3e-4,
                 hiddens=(64, 64), seed: int = 0, sgd_minibatch: int = 128,
                 num_epochs: int = 4):
        from .learner import PPOLearner

        probe = env_creator()
        self.policy_ids = list(policies)
        self.learners = {
            pid: PPOLearner(probe.observation_dim, probe.num_actions,
                            lr=lr, hiddens=hiddens, seed=seed + i)
            for i, pid in enumerate(self.policy_ids)
        }
        worker_cls = ray_tpu.remote(MultiAgentRolloutWorker)
        self.workers = [
            worker_cls.options(num_cpus=1).remote(
                env_creator, self.policy_ids, policy_mapping_fn,
                rollout_len, gamma, lam, hiddens, seed=seed + 100 * i)
            for i in range(num_rollout_workers)
        ]
        self._minibatch = sgd_minibatch
        self._epochs = num_epochs
        self._episode_returns: List[float] = []
        self.num_env_steps = 0
        self._sync_weights()

    def _sync_weights(self):
        w_ref = ray_tpu.put({pid: ln.get_weights()
                             for pid, ln in self.learners.items()})
        ray_tpu.get([w.set_weights.remote(w_ref) for w in self.workers],
                    timeout=300)

    def train(self) -> dict:
        batches = ray_tpu.get([w.sample.remote() for w in self.workers],
                              timeout=300)
        ma = MultiAgentBatch.concat(batches)
        self.num_env_steps += ma.env_steps  # concat already summed
        metrics: dict = {"env_steps": self.num_env_steps}
        for pid, batch in ma.policy_batches.items():
            out = self.learners[pid].update(
                batch, num_epochs=self._epochs,
                minibatch_size=min(self._minibatch, batch.count))
            metrics[f"{pid}/loss"] = out.get("loss")
        self._sync_weights()
        for m in ray_tpu.get([w.episode_metrics.remote()
                              for w in self.workers], timeout=300):
            self._episode_returns.extend(m["episode_returns"])
        if self._episode_returns:
            metrics["episode_reward_mean"] = float(
                np.mean(self._episode_returns[-50:]))
        return metrics

    def get_weights(self) -> Dict[str, dict]:
        return {pid: ln.get_weights() for pid, ln in self.learners.items()}

    def cleanup(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
