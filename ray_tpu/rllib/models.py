"""Policy networks: plain-JAX MLP actor-critic.

Ref analog: rllib/models/torch/fcnet.py (FullyConnectedNetwork) +
core/rl_module/rl_module.py:229 — re-designed as a pure function + params
pytree so the learner update is one jitted XLA program (MXU-friendly
batched matmuls, no module framework needed at this scale).
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, jnp.ndarray]


def init_actor_critic(rng, obs_dim: int, num_actions: int,
                      hiddens: Sequence[int] = (64, 64)) -> Params:
    params: Params = {}
    keys = jax.random.split(rng, 2 * len(hiddens) + 2)
    sizes = [obs_dim, *hiddens]
    for i in range(len(hiddens)):
        params[f"w{i}"] = _ortho(keys[2 * i], (sizes[i], sizes[i + 1]),
                                 gain=jnp.sqrt(2.0))
        params[f"b{i}"] = jnp.zeros((sizes[i + 1],))
    params["w_pi"] = _ortho(keys[-2], (sizes[-1], num_actions), gain=0.01)
    params["b_pi"] = jnp.zeros((num_actions,))
    params["w_v"] = _ortho(keys[-1], (sizes[-1], 1), gain=1.0)
    params["b_v"] = jnp.zeros((1,))
    return params


def _ortho(rng, shape, gain: float):
    a = jax.random.normal(rng, shape)
    q, r = jnp.linalg.qr(a if shape[0] >= shape[1] else a.T)
    q = q * jnp.sign(jnp.diag(r))
    if shape[0] < shape[1]:
        q = q.T
    return gain * q[: shape[0], : shape[1]]


def forward(params: Params, obs: jnp.ndarray
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """-> (logits [B, A], value [B])."""
    x = obs
    # hidden-layer count is static pytree structure, so jit-safe
    n = sum(1 for k in params if k.startswith("w") and k[1:].isdigit())
    for i in range(n):
        x = jnp.tanh(x @ params[f"w{i}"] + params[f"b{i}"])
    logits = x @ params["w_pi"] + params["b_pi"]
    value = (x @ params["w_v"] + params["b_v"]).squeeze(-1)
    return logits, value


def logp_of(logits: jnp.ndarray, actions: jnp.ndarray) -> jnp.ndarray:
    logps = jax.nn.log_softmax(logits)
    return jnp.take_along_axis(logps, actions[:, None], axis=1).squeeze(-1)


def entropy_of(logits: jnp.ndarray) -> jnp.ndarray:
    logps = jax.nn.log_softmax(logits)
    return -jnp.sum(jnp.exp(logps) * logps, axis=-1)
