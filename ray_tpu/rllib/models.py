"""Policy networks: plain-JAX MLP actor-critic.

Ref analog: rllib/models/torch/fcnet.py (FullyConnectedNetwork) +
core/rl_module/rl_module.py:229 — re-designed as a pure function + params
pytree so the learner update is one jitted XLA program (MXU-friendly
batched matmuls, no module framework needed at this scale).
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, jnp.ndarray]


def init_actor_critic(rng, obs_dim: int, num_actions: int,
                      hiddens: Sequence[int] = (64, 64)) -> Params:
    params: Params = {}
    keys = jax.random.split(rng, 2 * len(hiddens) + 2)
    sizes = [obs_dim, *hiddens]
    for i in range(len(hiddens)):
        params[f"w{i}"] = _ortho(keys[2 * i], (sizes[i], sizes[i + 1]),
                                 gain=jnp.sqrt(2.0))
        params[f"b{i}"] = jnp.zeros((sizes[i + 1],))
    params["w_pi"] = _ortho(keys[-2], (sizes[-1], num_actions), gain=0.01)
    params["b_pi"] = jnp.zeros((num_actions,))
    params["w_v"] = _ortho(keys[-1], (sizes[-1], 1), gain=1.0)
    params["b_v"] = jnp.zeros((1,))
    return params


def _ortho(rng, shape, gain: float):
    a = jax.random.normal(rng, shape)
    q, r = jnp.linalg.qr(a if shape[0] >= shape[1] else a.T)
    q = q * jnp.sign(jnp.diag(r))
    if shape[0] < shape[1]:
        q = q.T
    return gain * q[: shape[0], : shape[1]]


def forward(params: Params, obs: jnp.ndarray
            ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """-> (logits [B, A], value [B])."""
    x = obs
    # hidden-layer count is static pytree structure, so jit-safe
    n = sum(1 for k in params if k.startswith("w") and k[1:].isdigit())
    for i in range(n):
        x = jnp.tanh(x @ params[f"w{i}"] + params[f"b{i}"])
    logits = x @ params["w_pi"] + params["b_pi"]
    value = (x @ params["w_v"] + params["b_v"]).squeeze(-1)
    return logits, value


# ------------------------------------------------- continuous (SAC) nets


def init_gaussian_actor(rng, obs_dim: int, action_dim: int,
                        hiddens: Sequence[int] = (64, 64)) -> Params:
    """Tanh-squashed diagonal-Gaussian policy trunk + (mean, log_std)
    heads (ref analog: rllib SACTorchModel's policy net,
    rllib/algorithms/sac/sac_torch_model.py — re-done as a pure fn)."""
    params: Params = {}
    keys = jax.random.split(rng, len(hiddens) + 2)
    sizes = [obs_dim, *hiddens]
    for i in range(len(hiddens)):
        params[f"w{i}"] = _ortho(keys[i], (sizes[i], sizes[i + 1]),
                                 gain=jnp.sqrt(2.0))
        params[f"b{i}"] = jnp.zeros((sizes[i + 1],))
    params["w_mu"] = _ortho(keys[-2], (sizes[-1], action_dim), gain=0.01)
    params["b_mu"] = jnp.zeros((action_dim,))
    params["w_ls"] = _ortho(keys[-1], (sizes[-1], action_dim), gain=0.01)
    params["b_ls"] = jnp.zeros((action_dim,))
    return params


def gaussian_forward(params: Params, obs: jnp.ndarray
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """-> (mean [B, A], log_std [B, A]), log_std clamped to a sane range."""
    x = obs
    n = sum(1 for k in params if k.startswith("w") and k[1:].isdigit())
    for i in range(n):
        x = jnp.tanh(x @ params[f"w{i}"] + params[f"b{i}"])
    mu = x @ params["w_mu"] + params["b_mu"]
    log_std = jnp.clip(x @ params["w_ls"] + params["b_ls"], -20.0, 2.0)
    return mu, log_std


def squashed_sample(params: Params, obs: jnp.ndarray, rng,
                    scale: float, shift: float = 0.0
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Reparameterized a = shift + scale*tanh(u), u ~ N(mu, std);
    -> (a, logp) with the tanh change-of-variables correction. For an
    action box [low, high], scale = (high-low)/2 and shift =
    (high+low)/2 (the shift doesn't enter the log-det)."""
    mu, log_std = gaussian_forward(params, obs)
    std = jnp.exp(log_std)
    u = mu + std * jax.random.normal(rng, mu.shape)
    logp_u = jnp.sum(
        -0.5 * ((u - mu) / std) ** 2 - log_std
        - 0.5 * jnp.log(2.0 * jnp.pi), axis=-1)
    a = jnp.tanh(u)
    # d/du [scale*tanh(u)] = scale*(1-tanh^2): subtract its log per dim
    logp = logp_u - jnp.sum(
        jnp.log(scale * (1.0 - a ** 2) + 1e-6), axis=-1)
    return shift + scale * a, logp


def init_q_net(rng, obs_dim: int, action_dim: int,
               hiddens: Sequence[int] = (64, 64)) -> Params:
    """Q(s, a) -> scalar: MLP over the concatenated [obs, action]."""
    params: Params = {}
    keys = jax.random.split(rng, len(hiddens) + 1)
    sizes = [obs_dim + action_dim, *hiddens]
    for i in range(len(hiddens)):
        params[f"w{i}"] = _ortho(keys[i], (sizes[i], sizes[i + 1]),
                                 gain=jnp.sqrt(2.0))
        params[f"b{i}"] = jnp.zeros((sizes[i + 1],))
    params["w_q"] = _ortho(keys[-1], (sizes[-1], 1), gain=1.0)
    params["b_q"] = jnp.zeros((1,))
    return params


def q_forward(params: Params, obs: jnp.ndarray, act: jnp.ndarray
              ) -> jnp.ndarray:
    x = jnp.concatenate([obs, act], axis=-1)
    n = sum(1 for k in params if k.startswith("w") and k[1:].isdigit())
    for i in range(n):
        x = jnp.tanh(x @ params[f"w{i}"] + params[f"b{i}"])
    return (x @ params["w_q"] + params["b_q"]).squeeze(-1)


def logp_of(logits: jnp.ndarray, actions: jnp.ndarray) -> jnp.ndarray:
    logps = jax.nn.log_softmax(logits)
    return jnp.take_along_axis(logps, actions[:, None], axis=1).squeeze(-1)


def entropy_of(logits: jnp.ndarray) -> jnp.ndarray:
    logps = jax.nn.log_softmax(logits)
    return -jnp.sum(jnp.exp(logps) * logps, axis=-1)
