"""IMPALA: asynchronous off-policy training with V-trace correction.

Ref analog: rllib/algorithms/impala/impala.py:552 (async sample queue +
aggregation, :685 training_step). Pipelined: every rollout worker keeps
``num_inflight_per_worker`` sample futures outstanding (rollout latency is
hidden behind the learner), and ``num_aggregation_batches`` completed
rollouts are coalesced into one [T, N_total] batch per learner update —
the reference's aggregation actors exist to feed the learner large
batches the same way; here the concat is driver-side numpy and the update
is one XLA call, so the accelerator sees few large programs instead of
many small ones. Batches may be several updates stale; V-trace corrects.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

import ray_tpu

from .algorithm import Algorithm, AlgorithmConfig
from .learner import ImpalaLearner
from .sample_batch import SampleBatch


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self.lr = 5e-4
        self.grad_clip = 40.0
        self.clip_rho = 1.0
        self.clip_c = 1.0
        self.max_updates_per_step = 8
        # pipeline depth: outstanding rollouts per worker
        self.num_inflight_per_worker = 2
        # rollouts merged per learner update (fixed -> stable XLA shapes)
        self.num_aggregation_batches = 2


def _concat_time_major(batches: List[SampleBatch]) -> SampleBatch:
    """Merge [T, Ni] rollouts along the env axis -> [T, sum(Ni)]."""
    out = {}
    for k in batches[0]:
        axis = 0 if k == "bootstrap_obs" else 1
        out[k] = np.concatenate([b[k] for b in batches], axis=axis)
    return SampleBatch(out)


class IMPALA(Algorithm):
    _config_cls = IMPALAConfig

    def _make_learner_factory(self, cfg, obs_dim, num_actions):
        def make():
            return ImpalaLearner(
                obs_dim, num_actions, lr=cfg.lr, gamma=cfg.gamma,
                vf_coeff=cfg.vf_coeff, entropy_coeff=cfg.entropy_coeff,
                grad_clip=cfg.grad_clip, clip_rho=cfg.clip_rho,
                clip_c=cfg.clip_c, hiddens=cfg.model_hiddens,
                seed=cfg.seed)

        return make

    def setup(self, config):
        super().setup(config)
        cfg = self.algo_config
        # prime the pipeline: K outstanding rollouts per worker
        self._inflight: Dict = {}
        for w in self.workers:
            for _ in range(cfg.num_inflight_per_worker):
                self._inflight[w.sample_time_major.remote()] = w

    def training_step(self) -> dict:
        cfg = self.algo_config
        metrics: dict = {}
        steps = 0
        updates = 0
        agg = max(1, min(cfg.num_aggregation_batches, len(self._inflight)))
        while updates < cfg.max_updates_per_step:
            done, _ = ray_tpu.wait(list(self._inflight), num_returns=agg,
                                   timeout=600)
            if len(done) < agg:
                raise TimeoutError(
                    f"IMPALA: only {len(done)}/{agg} rollouts completed "
                    f"within 600s — rollout workers dead or stalled")
            batches = ray_tpu.get(list(done), timeout=600)
            workers_done = [self._inflight.pop(r) for r in done]
            merged = _concat_time_major(batches)
            # one large update instead of `agg` small ones
            metrics = self.learners.local.update(merged)
            updates += 1
            steps += merged["actions"].size
            # refresh weights once per update round (once per distinct
            # worker), then refill the pipeline slots
            w_ref = ray_tpu.put(self.learners.get_weights())
            for w in dict((id(x), x) for x in workers_done).values():
                w.set_weights.remote(w_ref)
            for w in workers_done:
                self._inflight[w.sample_time_major.remote()] = w
        self._num_env_steps += steps
        metrics["env_steps_this_iter"] = steps
        metrics["updates_this_iter"] = updates
        metrics["aggregation"] = agg
        return metrics
