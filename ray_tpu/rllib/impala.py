"""IMPALA: asynchronous off-policy training with V-trace correction.

Ref analog: rllib/algorithms/impala/impala.py:552 (async sample queue,
:685 training_step). Re-designed: each rollout worker keeps one in-flight
``sample_time_major`` future; as futures complete, the learner consumes
them immediately (off-policy — the batch may be a few updates stale, which
V-trace corrects) and the worker is restarted with fresh weights. The
object plane carries the sample batches, exercising worker->learner
transfer exactly like the reference's aggregation path.
"""

from __future__ import annotations

from typing import Dict

import ray_tpu

from .algorithm import Algorithm, AlgorithmConfig
from .learner import ImpalaLearner


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or IMPALA)
        self.lr = 5e-4
        self.grad_clip = 40.0
        self.clip_rho = 1.0
        self.clip_c = 1.0
        self.max_updates_per_step = 8


class IMPALA(Algorithm):
    _config_cls = IMPALAConfig

    def _make_learner_factory(self, cfg, obs_dim, num_actions):
        def make():
            return ImpalaLearner(
                obs_dim, num_actions, lr=cfg.lr, gamma=cfg.gamma,
                vf_coeff=cfg.vf_coeff, entropy_coeff=cfg.entropy_coeff,
                grad_clip=cfg.grad_clip, clip_rho=cfg.clip_rho,
                clip_c=cfg.clip_c, hiddens=cfg.model_hiddens,
                seed=cfg.seed)

        return make

    def setup(self, config):
        super().setup(config)
        # one in-flight rollout per worker, started immediately
        self._inflight: Dict = {
            w.sample_time_major.remote(): w for w in self.workers}

    def training_step(self) -> dict:
        cfg = self.algo_config
        metrics: dict = {}
        steps = 0
        updates = 0
        while updates < cfg.max_updates_per_step:
            done, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                   timeout=600)
            ref = done[0]
            worker = self._inflight.pop(ref)
            batch = ray_tpu.get(ref, timeout=600)
            # learner consumes the (possibly stale) batch; V-trace corrects
            metrics = self.learners.local.update(batch)
            updates += 1
            steps += batch[  # time-major [T, N]
                "actions"].size
            # restart the worker with fresh weights
            worker.set_weights.remote(
                ray_tpu.put(self.learners.get_weights()))
            self._inflight[worker.sample_time_major.remote()] = worker
        self._num_env_steps += steps
        metrics["env_steps_this_iter"] = steps
        metrics["updates_this_iter"] = updates
        return metrics
