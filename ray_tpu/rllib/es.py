"""ES: OpenAI-style evolution strategies (Salimans et al. 2017).

Ref analog: rllib/algorithms/es/es.py — perturbation-based black-box
optimization: workers evaluate antithetic weight perturbations
theta ± sigma*eps, the driver combines centered-rank-weighted noise into
a gradient estimate and Adam-steps the master weights. Shared noise is
reconstructed from integer seeds (the reference's SharedNoiseTable
trick), so worker->driver traffic is (seed, return) pairs, never weight
vectors. Re-design notes: evaluation is deterministic argmax over the
actor head of the same MLP the gradient algorithms use; the update is a
single jitted combination over the stacked noise batch.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

import ray_tpu

from .algorithm import Algorithm, AlgorithmConfig
from .env import make_env
from .models import forward as ac_forward
from .models import init_actor_critic


class ESConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ES)
        self.num_rollout_workers = 2
        self.episodes_per_perturbation = 1
        self.perturbations_per_step = 16  # antithetic pairs
        self.sigma = 0.05
        self.lr = 0.02
        self.l2_coeff = 0.005


def _flatten(weights: Dict[str, np.ndarray]):
    keys = sorted(weights)
    flat = np.concatenate([np.asarray(weights[k]).ravel() for k in keys])
    shapes = [(k, weights[k].shape) for k in keys]
    return flat.astype(np.float32), shapes


def _unflatten(flat: np.ndarray, shapes) -> Dict[str, np.ndarray]:
    out, i = {}, 0
    for k, shp in shapes:
        n = int(np.prod(shp)) if shp else 1
        out[k] = flat[i:i + n].reshape(shp)
        i += n
    return out


def _noise(seed: int, dim: int) -> np.ndarray:
    return np.random.default_rng(seed).standard_normal(
        dim).astype(np.float32)


def centered_ranks(x: np.ndarray) -> np.ndarray:
    """Map returns to [-0.5, 0.5] by rank (the reference's
    compute_centered_ranks — robust to reward scale)."""
    ranks = np.empty(len(x), np.float32)
    ranks[x.argsort()] = np.arange(len(x), dtype=np.float32)
    return ranks / max(len(x) - 1, 1) - 0.5


class ESWorker:
    """Evaluates perturbed policies; stateless between calls except the
    env (fresh episodes each time)."""

    def __init__(self, env_creator, episodes: int, seed: int = 0,
                 hiddens=(64, 64)):
        self.env = make_env(env_creator)
        self.episodes = episodes
        self.hiddens = hiddens
        self._eval_seq = seed * 100_000

    def _episode_return(self, weights: Dict[str, np.ndarray]) -> float:
        total = 0.0
        for _ in range(self.episodes):
            self._eval_seq += 1
            obs = self.env.reset(seed=self._eval_seq)
            done = False
            while not done:
                logits, _ = ac_forward(weights, obs[None].astype(np.float32))
                obs, r, done, _ = self.env.step(int(np.argmax(logits[0])))
                total += r
        return total / self.episodes

    def evaluate(self, flat: np.ndarray, shapes, seeds: List[int],
                 sigma: float):
        """-> [(seed, return_pos, return_neg)] for antithetic pairs."""
        out = []
        for s in seeds:
            eps = _noise(s, flat.size)
            r_pos = self._episode_return(_unflatten(flat + sigma * eps,
                                                    shapes))
            r_neg = self._episode_return(_unflatten(flat - sigma * eps,
                                                    shapes))
            out.append((s, r_pos, r_neg))
        return out

    def episode_metrics(self) -> dict:
        return {"episode_returns": [], "episode_lengths": []}

    def ping(self) -> bool:
        return True


class ES(Algorithm):
    _config_cls = ESConfig
    _worker_cls = ESWorker

    def setup(self, config):
        cfg = config.get("__algo_config__")
        cfg = cfg.copy() if cfg is not None else self.get_default_config()
        cfg.update_from_dict(
            {k: v for k, v in config.items() if k != "__algo_config__"})
        self.algo_config = cfg
        probe = make_env(cfg.env)
        assert not getattr(probe, "continuous", False), \
            "ES here supports discrete-action envs"
        weights = init_actor_critic(
            __import__("jax").random.key(cfg.seed),
            probe.observation_dim, probe.num_actions, cfg.model_hiddens)
        weights = {k: np.asarray(v) for k, v in weights.items()}
        self._flat, self._shapes = _flatten(weights)
        # Adam state (host-side: the parameter vector is tiny and the
        # update is O(dim * perturbations) numpy)
        self._m = np.zeros_like(self._flat)
        self._v = np.zeros_like(self._flat)
        self._t = 0
        worker_cls = ray_tpu.remote(ESWorker)
        self.workers = [
            worker_cls.options(num_cpus=1).remote(
                cfg.env, cfg.episodes_per_perturbation,
                seed=cfg.seed + i, hiddens=cfg.model_hiddens)
            for i in range(cfg.num_rollout_workers)]
        self._seed_seq = cfg.seed * 1_000_003
        self._episode_returns: List[float] = []
        self._num_env_steps = 0

    def training_step(self) -> dict:
        cfg = self.algo_config
        n = cfg.perturbations_per_step
        seeds = [self._seed_seq + i for i in range(n)]
        self._seed_seq += n
        shards = np.array_split(np.asarray(seeds), len(self.workers))
        futs = [w.evaluate.remote(self._flat, self._shapes,
                                  [int(s) for s in shard], cfg.sigma)
                for w, shard in zip(self.workers, shards) if len(shard)]
        results = [r for out in ray_tpu.get(futs, timeout=1200)
                   for r in out]
        rets = np.array([[rp, rn] for (_s, rp, rn) in results], np.float32)
        ranks = centered_ranks(rets.ravel()).reshape(rets.shape)
        grad = np.zeros_like(self._flat)
        for (s, _rp, _rn), (w_pos, w_neg) in zip(results, ranks):
            grad += (w_pos - w_neg) * _noise(s, self._flat.size)
        grad /= (2 * len(results) * cfg.sigma)
        grad -= cfg.l2_coeff * self._flat  # weight decay toward 0
        # Adam ascent on the rank objective
        self._t += 1
        b1, b2, eps = 0.9, 0.999, 1e-8
        self._m = b1 * self._m + (1 - b1) * grad
        self._v = b2 * self._v + (1 - b2) * grad * grad
        mh = self._m / (1 - b1 ** self._t)
        vh = self._v / (1 - b2 ** self._t)
        self._flat = self._flat + cfg.lr * mh / (np.sqrt(vh) + eps)
        self._episode_returns = rets.ravel().tolist()
        return {"perturbations": len(results),
                "reward_mean_perturbed": float(rets.mean()),
                "reward_max_perturbed": float(rets.max()),
                "update_norm": float(np.linalg.norm(grad))}

    def step(self) -> dict:
        result = self.training_step()
        # evaluate the CURRENT (unperturbed) policy like the reference's
        # ES reports its eval episodes
        w = _unflatten(self._flat, self._shapes)
        env = make_env(self.algo_config.env)
        rets = []
        for ep in range(3):
            obs = env.reset(seed=50_000 + self.iteration * 10 + ep)
            total, done = 0.0, False
            while not done:
                logits, _ = ac_forward(w, obs[None].astype(np.float32))
                obs, r, done, _ = env.step(int(np.argmax(logits[0])))
                total += r
            rets.append(total)
        result["episode_reward_mean"] = float(np.mean(rets))
        return result

    def save_checkpoint(self):
        return {"flat": self._flat, "shapes": self._shapes,
                "m": self._m, "v": self._v, "t": self._t}

    def load_checkpoint(self, checkpoint):
        if checkpoint:
            self._flat = checkpoint["flat"]
            self._shapes = checkpoint["shapes"]
            self._m, self._v = checkpoint["m"], checkpoint["v"]
            self._t = checkpoint["t"]

    def get_policy_weights(self) -> dict:
        return _unflatten(self._flat, self._shapes)

    def cleanup(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
