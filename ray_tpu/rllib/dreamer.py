"""DreamerV3: world-model RL — learn in imagination (Hafner et al. 2023).

Ref analog: rllib/algorithms/dreamerv3/ (the reference's TF
implementation of the same paper). TPU-first re-design: the entire
update — RSSM world model (GRU recurrence + categorical latents with
straight-through gradients, symlog decoder/reward heads, KL balancing
with free bits), imagination rollout under the prior, twohot-symlog
critic with an EMA target, and a REINFORCE actor with return
normalization — is ONE jitted JAX program over a batch of replayed
subsequences; `lax.scan` carries both the posterior unroll over real
steps and the imagination unroll over horizon steps, so XLA sees a
single static graph. The host side only steps the (CPU) environment
and maintains the sequence replay buffer.

Sized-down defaults (MLP encoder, 8x8 categorical latent) target the
CI-class envs in ``env.py``; the architecture is the paper's.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .env import make_env

# ------------------------------------------------------------ utilities


def symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


class DreamerLearner:
    """The jitted world-model + actor-critic update."""

    def __init__(self, obs_dim: int, num_actions: int, *,
                 deter: int = 128, groups: int = 8, classes: int = 8,
                 hidden: int = 128, horizon: int = 15,
                 gamma: float = 0.985, lam: float = 0.95,
                 wm_lr: float = 3e-4, ac_lr: float = 3e-4,
                 entropy_coef: float = 1e-3, free_bits: float = 1.0,
                 seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.deter = deter
        self.groups = groups
        self.classes = classes
        self.stoch = groups * classes
        self.horizon = horizon
        self.gamma = gamma
        self.lam = lam

        k = jax.random.split(jax.random.key(seed), 16)
        h, D, S, A = hidden, deter, self.stoch, num_actions
        glorot = jax.nn.initializers.glorot_uniform()

        def lin(key, i, o):
            return {"w": glorot(key, (i, o)), "b": jnp.zeros(o)}

        wm = {
            "enc1": lin(k[0], obs_dim, h), "enc2": lin(k[1], h, h),
            # GRU over [stoch, action] -> deter
            "gru_x": lin(k[2], S + A, 3 * D), "gru_h": lin(k[3], D, 3 * D),
            "prior1": lin(k[4], D, h), "prior2": lin(k[5], h, S),
            "post1": lin(k[6], D + h, h), "post2": lin(k[7], h, S),
            "dec1": lin(k[8], D + S, h), "dec2": lin(k[9], h, obs_dim),
            "rew1": lin(k[10], D + S, h), "rew2": lin(k[11], h, 1),
            "cont1": lin(k[12], D + S, h), "cont2": lin(k[13], h, 1),
        }
        ac = {
            "actor1": lin(k[14], D + S, h),
            "actor2": lin(jax.random.fold_in(k[14], 1), h, A),
            "critic1": lin(k[15], D + S, h),
            "critic2": lin(jax.random.fold_in(k[15], 1), h, 1),
        }
        self._wm_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                   optax.adam(wm_lr))
        self._ac_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                   optax.adam(ac_lr))
        self._state = {
            "wm": wm, "ac": ac, "target": jax.tree.map(jnp.copy, ac),
            "wm_opt": self._wm_opt.init(wm), "ac_opt": self._ac_opt.init(ac),
            # running return-scale for actor normalization (paper: S)
            "ret_scale": jnp.ones(()),
        }
        G, C = groups, classes

        def mlp(p, n1, n2, x, act=jax.nn.silu):
            x = act(x @ p[n1]["w"] + p[n1]["b"])
            return x @ p[n2]["w"] + p[n2]["b"]

        def gru(p, hprev, x):
            gx = x @ p["gru_x"]["w"] + p["gru_x"]["b"]
            gh = hprev @ p["gru_h"]["w"] + p["gru_h"]["b"]
            r = jax.nn.sigmoid(gx[..., :D] + gh[..., :D])
            z = jax.nn.sigmoid(gx[..., D:2 * D] + gh[..., D:2 * D])
            n = jnp.tanh(gx[..., 2 * D:] + r * gh[..., 2 * D:])
            return (1 - z) * n + z * hprev

        def sample_latent(logits, rng):
            """Straight-through categorical sample per group, with the
            paper's 1% uniform mix for stable KLs."""
            lg = logits.reshape(logits.shape[:-1] + (G, C))
            probs = 0.99 * jax.nn.softmax(lg) + 0.01 / C
            lg = jnp.log(probs)
            idx = jax.random.categorical(rng, lg)
            onehot = jax.nn.one_hot(idx, C)
            st = onehot + probs - jax.lax.stop_gradient(probs)
            # return MIXED logits flat [..., S]; kl() regroups
            return (st.reshape(logits.shape[:-1] + (S,)),
                    lg.reshape(logits.shape))

        def kl(lhs_logits, rhs_logits):
            """KL(lhs || rhs) summed over groups; inputs already mixed."""
            a = lhs_logits.reshape(lhs_logits.shape[:-1] + (G, C))
            b = rhs_logits.reshape(rhs_logits.shape[:-1] + (G, C))
            pa = jax.nn.softmax(a)
            return jnp.sum(pa * (jax.nn.log_softmax(a)
                                 - jax.nn.log_softmax(b)), axis=(-2, -1))

        def observe(wm, obs_seq, act_seq, rng):
            """Posterior unroll over a real subsequence.

            obs_seq [B,L,obs], act_seq [B,L,A] (action taken AT each
            step). Returns deter/stoch/prior/post logits per step."""
            B, L = obs_seq.shape[0], obs_seq.shape[1]
            embed = mlp(wm, "enc1", "enc2", symlog(obs_seq))

            def step(carry, t):
                hprev, sprev, rng = carry
                rng, sub = jax.random.split(rng)
                hcur = gru(wm, hprev, jnp.concatenate(
                    [sprev, act_seq[:, t]], -1))
                prior_logits = mlp(wm, "prior1", "prior2", hcur)
                post_in = jnp.concatenate([hcur, embed[:, t]], -1)
                post_logits = mlp(wm, "post1", "post2", post_in)
                stoch, post_lg = sample_latent(post_logits, sub)
                _, prior_lg = sample_latent(prior_logits, sub)
                return (hcur, stoch, rng), (hcur, stoch, prior_lg,
                                            post_lg)

            h0 = jnp.zeros((B, D))
            s0 = jnp.zeros((B, S))
            (_, _, _), (hs, ss, prior_lg, post_lg) = jax.lax.scan(
                step, (h0, s0, rng), jnp.arange(L))
            # scan stacks on axis 0 = time; move to [B, L, ...]
            move = lambda x: jnp.moveaxis(x, 0, 1)  # noqa: E731
            return move(hs), move(ss), move(prior_lg), move(post_lg)

        def wm_loss(wm, obs, act, rew, cont, rng):
            hs, ss, prior_lg, post_lg = observe(wm, obs, act, rng)
            feat = jnp.concatenate([hs, ss], -1)
            recon = mlp(wm, "dec1", "dec2", feat)
            rloss = jnp.mean(jnp.sum(
                (recon - symlog(obs)) ** 2, -1))
            rpred = mlp(wm, "rew1", "rew2", feat)[..., 0]
            rew_loss = jnp.mean((rpred - symlog(rew)) ** 2)
            cpred = mlp(wm, "cont1", "cont2", feat)[..., 0]
            cont_loss = jnp.mean(
                jnp.maximum(cpred, 0) - cpred * cont
                + jnp.log1p(jnp.exp(-jnp.abs(cpred))))
            sg = jax.lax.stop_gradient
            dyn = jnp.maximum(free_bits, jnp.mean(
                kl(sg(post_lg), prior_lg)))
            rep = jnp.maximum(free_bits, jnp.mean(
                kl(post_lg, sg(prior_lg))))
            loss = rloss + rew_loss + cont_loss + 0.5 * dyn + 0.1 * rep
            return loss, (hs, ss, rloss, rew_loss, dyn)

        def imagine(wm, ac, h0, s0, rng):
            """Roll the prior forward under the actor for H steps from
            every posterior state (flattened starts [N, ...])."""

            def step(carry, _):
                h, s, rng = carry
                rng, ka, ks = jax.random.split(rng, 3)
                feat = jnp.concatenate([h, s], -1)
                logits = mlp(ac, "actor1", "actor2", feat)
                a = jax.random.categorical(ka, logits)
                aoh = jax.nn.one_hot(a, A)
                hn = gru(wm, h, jnp.concatenate([s, aoh], -1))
                prior_logits = mlp(wm, "prior1", "prior2", hn)
                sn, _ = sample_latent(prior_logits, ks)
                return (hn, sn, rng), (feat, a, logits)

            (_, _, _), (feats, acts, logitss) = jax.lax.scan(
                step, (h0, s0, rng), None, length=horizon)
            return feats, acts, logitss  # [H, N, ...]

        def ac_loss(ac, wm, target, ret_scale, h0, s0, rng):
            sg = jax.lax.stop_gradient
            feats, acts, logitss = imagine(sg(wm), ac, h0, s0, rng)
            rew = symexp(mlp(sg(wm), "rew1", "rew2", feats)[..., 0])
            cont = jax.nn.sigmoid(
                mlp(sg(wm), "cont1", "cont2", feats)[..., 0])
            disc = gamma * cont
            tvalues = symexp(mlp(target, "critic1", "critic2",
                                 sg(feats))[..., 0])  # [H, N]

            # lambda-returns for state t bootstrap from the SUCCESSOR's
            # reward/discount/value:
            #   R_t = r_{t+1} + d_{t+1} ((1-lam) v_{t+1} + lam R_{t+1})
            # (same-step bootstrapping double-counts the current state
            # and was measured leaving the actor at max entropy)
            def ret_step(nxt, t):
                r = rew[t + 1] + disc[t + 1] * (
                    (1 - lam) * sg(tvalues[t + 1]) + lam * nxt)
                return r, r

            last = sg(tvalues[-1])
            _, rets = jax.lax.scan(ret_step, last,
                                   jnp.arange(horizon - 2, -1, -1))
            rets = rets[::-1]  # [H-1, N]: targets for steps 0..H-2

            # critic: symlog regression toward lambda-returns
            vpred = mlp(ac, "critic1", "critic2",
                        feats[:-1])[..., 0]
            critic_loss = jnp.mean((vpred - symlog(sg(rets))) ** 2)

            # actor: REINFORCE on normalized advantage + entropy
            scale = jnp.maximum(1.0, ret_scale)
            adv = sg((rets - tvalues[:-1]) / scale)
            logp = jax.nn.log_softmax(logitss[:-1])
            taken = jnp.take_along_axis(logp, acts[:-1][..., None],
                                        -1)[..., 0]
            probs = jax.nn.softmax(logitss[:-1])
            ent = -jnp.mean(jnp.sum(probs * logp, -1))
            actor_loss = -jnp.mean(taken * adv) - entropy_coef * ent
            new_scale = jnp.percentile(sg(rets), 95) - jnp.percentile(
                sg(rets), 5)
            return actor_loss + critic_loss, (
                critic_loss, actor_loss, ent, jnp.mean(rets), new_scale)

        @jax.jit
        def update(state, obs, act_idx, rew, cont, rng):
            wm, ac = state["wm"], state["ac"]
            # the transition INTO step t is driven by the action taken
            # at t-1; the buffer stores the action taken AT t
            taken = jax.nn.one_hot(act_idx, A)
            act = jnp.concatenate(
                [jnp.zeros_like(taken[:, :1]), taken[:, :-1]], axis=1)
            rng, k1, k2 = jax.random.split(rng, 3)
            (wl, (hs, ss, rloss, rew_loss, dyn)), gw = \
                jax.value_and_grad(wm_loss, has_aux=True)(
                    wm, obs, act, rew, cont, k1)
            upd, wm_opt = self._wm_opt.update(gw, state["wm_opt"], wm)
            wm = optax.apply_updates(wm, upd)

            h0 = jax.lax.stop_gradient(hs.reshape(-1, D))
            s0 = jax.lax.stop_gradient(ss.reshape(-1, S))
            (al, (cl, aol, ent, mret, new_scale)), ga = \
                jax.value_and_grad(ac_loss, has_aux=True)(
                    ac, wm, state["target"], state["ret_scale"],
                    h0, s0, k2)
            upd, ac_opt = self._ac_opt.update(ga, state["ac_opt"], ac)
            ac = optax.apply_updates(ac, upd)
            target = jax.tree.map(lambda t, o: 0.98 * t + 0.02 * o,
                                  state["target"], ac)
            ret_scale = 0.99 * state["ret_scale"] + 0.01 * new_scale
            new_state = {"wm": wm, "ac": ac, "target": target,
                         "wm_opt": wm_opt, "ac_opt": ac_opt,
                         "ret_scale": ret_scale}
            metrics = {"wm_loss": wl, "recon_loss": rloss,
                       "reward_loss": rew_loss, "kl_dyn": dyn,
                       "critic_loss": cl, "actor_loss": aol,
                       "entropy": ent, "imag_return_mean": mret}
            return new_state, metrics

        # acting: posterior filter for one env step (batch 1)
        def policy_step(wm, ac, h, s, obs, aprev, rng, greedy):
            rng, k1, k2 = jax.random.split(rng, 3)
            embed = mlp(wm, "enc1", "enc2", symlog(obs))
            h = gru(wm, h, jnp.concatenate([s, aprev], -1))
            post_in = jnp.concatenate([h, embed], -1)
            post_logits = mlp(wm, "post1", "post2", post_in)
            s, _ = sample_latent(post_logits, k1)
            logits = mlp(ac, "actor1", "actor2",
                         jnp.concatenate([h, s], -1))
            a = jnp.where(greedy, jnp.argmax(logits, -1),
                          jax.random.categorical(k2, logits))
            return h, s, a

        self._update = update
        self._policy_step = jax.jit(policy_step)
        self._rng = jax.random.key(seed + 1)

    # ------------------------------------------------------------ API

    def update(self, obs, actions, rewards, continues) -> Dict[str, float]:
        import jax

        self._rng, k = jax.random.split(self._rng)
        self._state, metrics = self._update(
            self._state, obs.astype(np.float32), actions.astype(np.int32),
            rewards.astype(np.float32), continues.astype(np.float32), k)
        return {k2: float(v) for k2, v in metrics.items()}

    def init_policy_state(self):
        import jax.numpy as jnp

        return (jnp.zeros((1, self.deter)), jnp.zeros((1, self.stoch)),
                jnp.zeros((1, self.num_actions)))

    def act(self, pol_state, obs, greedy: bool = False):
        import jax
        import jax.numpy as jnp

        h, s, aprev = pol_state
        self._rng, k = jax.random.split(self._rng)
        h, s, a = self._policy_step(
            self._state["wm"], self._state["ac"], h, s,
            jnp.asarray(obs, jnp.float32)[None], aprev, k,
            jnp.asarray(greedy))
        action = int(a[0])
        aoh = jnp.zeros((1, self.num_actions)).at[0, action].set(1.0)
        return (h, s, aoh), action


# --------------------------------------------------------------- replay


class SequenceBuffer:
    """Ring buffer of (obs, action, reward, continue) steps; samples
    fixed-length subsequences for the world model."""

    def __init__(self, capacity: int, obs_dim: int, seed: int = 0):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.act = np.zeros(capacity, np.int32)
        self.rew = np.zeros(capacity, np.float32)
        self.cont = np.ones(capacity, np.float32)
        self.idx = 0
        self.full = False
        self._rng = np.random.default_rng(seed)

    def add(self, obs, action, reward, cont):
        i = self.idx
        self.obs[i] = obs
        self.act[i] = action
        self.rew[i] = reward
        self.cont[i] = cont
        self.idx = (i + 1) % self.capacity
        self.full = self.full or self.idx == 0

    def __len__(self):
        return self.capacity if self.full else self.idx

    def sample(self, batch: int, length: int):
        n = len(self)
        # logical time order starts at the write head once the ring has
        # wrapped — physical windows crossing the seam would stitch the
        # newest steps onto the oldest with no cont=0 separator
        base = self.idx if self.full else 0
        starts = self._rng.integers(0, n - length + 1, batch)
        sel = (base + starts[:, None]
               + np.arange(length)[None, :]) % self.capacity
        return (self.obs[sel], self.act[sel], self.rew[sel],
                self.cont[sel])


# ------------------------------------------------------------ algorithm


class DreamerV3Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or DreamerV3)
        self.env = "CartPole-v1"
        self.batch_size = 16
        self.seq_length = 32
        self.replay_capacity = 50_000
        self.env_steps_per_iter = 500
        self.updates_per_iter = 8
        self.warmup_steps = 1000
        self.horizon = 15
        self.deter = 128
        self.hidden = 128
        self.train_ratio_note = ("updates_per_iter/env_steps_per_iter "
                                 "is the paper's train ratio knob")


class DreamerV3(Algorithm):
    """Single-process Dreamer: the env is cheap, the update is jitted;
    rollout actors would add only IPC here (the reference's DreamerV3
    likewise defaults to 0 rollout workers)."""

    _config_cls = DreamerV3Config

    def setup(self, config: dict):
        cfg = config.get("__algo_config__") or self.get_default_config()
        cfg = cfg.copy()
        cfg.update_from_dict(
            {k: v for k, v in config.items() if k != "__algo_config__"})
        self.algo_config = cfg
        self.env = make_env(cfg.env)
        self.learner = DreamerLearner(
            self.env.observation_dim, self.env.num_actions,
            deter=cfg.deter, hidden=cfg.hidden, horizon=cfg.horizon,
            seed=cfg.seed)
        self.buffer = SequenceBuffer(cfg.replay_capacity,
                                     self.env.observation_dim,
                                     seed=cfg.seed)
        self._obs = self.env.reset(seed=cfg.seed)
        self._pol = self.learner.init_policy_state()
        self._episode_return = 0.0
        self._episode_returns: list = []
        self._num_env_steps = 0

    def training_step(self) -> dict:
        cfg = self.algo_config
        for _ in range(cfg.env_steps_per_iter):
            if self._num_env_steps < cfg.warmup_steps:
                action = int(np.random.default_rng(
                    self._num_env_steps).integers(self.env.num_actions))
            else:
                self._pol, action = self.learner.act(self._pol, self._obs)
            nxt, rew, done, info = self.env.step(action)
            truncated = bool(info.get("truncated"))
            self.buffer.add(self._obs, action, rew,
                            0.0 if (done and not truncated) else 1.0)
            self._episode_return += rew
            self._num_env_steps += 1
            if done:
                self._episode_returns.append(self._episode_return)
                self._episode_return = 0.0
                self._obs = self.env.reset()
                self._pol = self.learner.init_policy_state()
            else:
                self._obs = nxt
        metrics: dict = {}
        if len(self.buffer) > max(cfg.warmup_steps,
                                  cfg.seq_length * cfg.batch_size // 4):
            for _ in range(cfg.updates_per_iter):
                obs, act, rew, cont = self.buffer.sample(
                    cfg.batch_size, cfg.seq_length)
                metrics = self.learner.update(obs, act, rew, cont)
        metrics["env_steps_this_iter"] = cfg.env_steps_per_iter
        if self._episode_returns:
            recent = self._episode_returns[-20:]
            metrics["episode_reward_mean"] = float(np.mean(recent))
        return metrics

    def step(self) -> dict:
        metrics = self.training_step()
        metrics["num_env_steps_sampled"] = self._num_env_steps
        return metrics

    def evaluate(self, episodes: int = 5) -> float:
        """Greedy-policy mean return."""
        total = 0.0
        for e in range(episodes):
            obs = self.env.reset(seed=10_000 + e)
            pol = self.learner.init_policy_state()
            done, ret = False, 0.0
            while not done:
                pol, action = self.learner.act(pol, obs, greedy=True)
                obs, rew, done, _ = self.env.step(action)
                ret += rew
            total += ret
        self._obs = self.env.reset()
        self._pol = self.learner.init_policy_state()
        return total / episodes

    def save_checkpoint(self):
        import jax

        return {"state": jax.tree.map(np.asarray, self.learner._state),
                "num_env_steps": self._num_env_steps}

    def load_checkpoint(self, checkpoint):
        import jax
        import jax.numpy as jnp

        if checkpoint:
            self.learner._state = jax.tree.map(
                jnp.asarray, checkpoint["state"])
            self._num_env_steps = checkpoint.get("num_env_steps", 0)

    def cleanup(self):
        pass
