"""AlphaZero: self-play MCTS + policy/value network (Silver et al. 2017).

Ref analog: rllib/algorithms/alpha_zero/ — MCTS-guided self-play on a
perfect-information game, training a shared policy+value net on
(state, mcts_policy, outcome) tuples. Re-design notes: self-play
workers are runtime actors evaluating leaves with a NUMPY forward of
the tiny net (single-position MCTS evals are latency-bound — a jitted
XLA call per node would be dominated by dispatch), while the learner's
update is one jitted JAX program (policy cross-entropy + value MSE +
L2, Adam) that runs on the accelerator when present. Weights cross the
object plane as numpy dicts, like every other algorithm here.

The built-in game is TicTacToe (canonical two-plane board encoding from
the side-to-move's perspective) — the smallest game whose optimal play
is learnable in a CI-sized test, mirroring how the reference's
alpha_zero tests use toy envs (cartpole-with-MCTS) rather than Go.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

import ray_tpu

from .algorithm import Algorithm, AlgorithmConfig

# ---------------------------------------------------------------- game


class TicTacToe:
    """Perfect-information 2-player game with the canonical interface
    MCTS needs: state is a length-9 int8 vector in {-1, 0, +1} from the
    perspective of the player to move (+1 = own stones)."""

    num_actions = 9
    observation_dim = 18  # two planes: own stones, opponent stones

    _LINES = ((0, 1, 2), (3, 4, 5), (6, 7, 8), (0, 3, 6), (1, 4, 7),
              (2, 5, 8), (0, 4, 8), (2, 4, 6))

    @staticmethod
    def initial() -> np.ndarray:
        return np.zeros(9, np.int8)

    @staticmethod
    def legal(state: np.ndarray) -> np.ndarray:
        return state == 0

    @staticmethod
    def step(state: np.ndarray, action: int) -> np.ndarray:
        """Apply own move, then flip perspective to the next player."""
        nxt = state.copy()
        nxt[action] = 1
        return -nxt

    @classmethod
    def outcome(cls, state: np.ndarray) -> Optional[float]:
        """Terminal value FROM THE PERSPECTIVE OF THE PLAYER TO MOVE:
        -1 if the previous move won (opponent stones, -1 here, line up),
        0 for a draw, None if the game continues."""
        for a, b, c in cls._LINES:
            s = int(state[a]) + int(state[b]) + int(state[c])
            if s == -3:
                return -1.0
        if not (state == 0).any():
            return 0.0
        return None

    @staticmethod
    def encode(state: np.ndarray) -> np.ndarray:
        return np.concatenate([(state == 1), (state == -1)]).astype(
            np.float32)


_GAMES = {"tictactoe": TicTacToe}

# ------------------------------------------------------------- network


def _init_net(rng: np.random.Generator, obs_dim: int, num_actions: int,
              hiddens: Tuple[int, ...]) -> Dict[str, np.ndarray]:
    w, sizes = {}, (obs_dim,) + tuple(hiddens)
    for i in range(len(hiddens)):
        fan_in = sizes[i]
        w[f"w{i}"] = rng.normal(
            0, math.sqrt(2.0 / fan_in), (sizes[i], sizes[i + 1])
        ).astype(np.float32)
        w[f"b{i}"] = np.zeros(sizes[i + 1], np.float32)
    h = hiddens[-1]
    w["wp"] = rng.normal(0, 0.01, (h, num_actions)).astype(np.float32)
    w["bp"] = np.zeros(num_actions, np.float32)
    w["wv"] = rng.normal(0, 0.01, (h, 1)).astype(np.float32)
    w["bv"] = np.zeros(1, np.float32)
    w["__n_hidden__"] = np.int64(len(hiddens))
    return w


def _np_forward(w: Dict[str, np.ndarray], obs: np.ndarray
                ) -> Tuple[np.ndarray, float]:
    """Numpy policy/value forward for single-position MCTS leaf evals."""
    h = obs
    for i in range(int(w["__n_hidden__"])):
        h = np.maximum(h @ w[f"w{i}"] + w[f"b{i}"], 0.0)
    logits = h @ w["wp"] + w["bp"]
    logits = logits - logits.max()
    p = np.exp(logits)
    p /= p.sum()
    v = float(np.tanh(h @ w["wv"] + w["bv"])[0])
    return p, v


# ---------------------------------------------------------------- MCTS


class MCTS:
    """PUCT search over the game tree; values are always from the
    perspective of the node's player-to-move (negamax backup)."""

    def __init__(self, game, weights, *, sims: int = 64, c_puct: float = 1.5,
                 dirichlet_alpha: float = 0.6, noise_frac: float = 0.25,
                 rng: Optional[np.random.Generator] = None):
        self.game = game
        self.w = weights
        self.sims = sims
        self.c = c_puct
        self.alpha = dirichlet_alpha
        self.noise_frac = noise_frac
        self.rng = rng or np.random.default_rng()

    def policy(self, state: np.ndarray, temperature: float = 1.0
               ) -> np.ndarray:
        """Run sims; return the visit-count policy at the root."""
        root = _Node(prior=1.0)
        self._expand(root, state)
        if root.children:  # root exploration noise (self-play diversity)
            noise = self.rng.dirichlet(
                [self.alpha] * len(root.children))
            for i, ch in enumerate(root.children.values()):
                ch.prior = (1 - self.noise_frac) * ch.prior \
                    + self.noise_frac * noise[i]
        for _ in range(self.sims):
            self._simulate(root, state)
        counts = np.zeros(self.game.num_actions, np.float32)
        for a, ch in root.children.items():
            counts[a] = ch.visits
        if temperature < 1e-3:
            out = np.zeros_like(counts)
            out[int(counts.argmax())] = 1.0
            return out
        counts = counts ** (1.0 / temperature)
        return counts / counts.sum()

    def _expand(self, node: "_Node", state: np.ndarray) -> float:
        term = self.game.outcome(state)
        if term is not None:
            node.terminal = term
            return term
        p, v = _np_forward(self.w, self.game.encode(state))
        legal = self.game.legal(state)
        p = p * legal
        total = p.sum()
        p = p / total if total > 1e-8 else legal / legal.sum()
        for a in np.flatnonzero(legal):
            node.children[int(a)] = _Node(prior=float(p[a]))
        return v

    def _simulate(self, node: "_Node", state: np.ndarray) -> float:
        """One descent; returns the value from ``state``'s perspective."""
        if node.terminal is not None:
            node.visits += 1
            node.value_sum += node.terminal
            return node.terminal
        if not node.children:  # leaf: expand + evaluate
            v = self._expand(node, state)
            node.visits += 1
            node.value_sum += v
            return v
        sqrt_n = math.sqrt(node.visits)
        best, best_score = None, -1e9
        for a, ch in node.children.items():
            q = (ch.value_sum / ch.visits) if ch.visits else 0.0
            # child value is from the OPPONENT's perspective
            score = -q + self.c * ch.prior * sqrt_n / (1 + ch.visits)
            if score > best_score:
                best, best_score = a, score
        child = node.children[best]
        v = -self._simulate(child, self.game.step(state, best))
        node.visits += 1
        node.value_sum += v
        return v


class _Node:
    __slots__ = ("prior", "visits", "value_sum", "children", "terminal")

    def __init__(self, prior: float):
        self.prior = prior
        self.visits = 0
        self.value_sum = 0.0
        self.children: Dict[int, _Node] = {}
        self.terminal: Optional[float] = None


# ------------------------------------------------------------ learner


class AlphaZeroLearner:
    """Jitted policy-CE + value-MSE + L2 Adam update."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens=(64, 64), lr=1e-2, l2=1e-4, seed=0):
        import jax
        import jax.numpy as jnp
        import optax

        self.num_actions = num_actions
        self._np = _init_net(np.random.default_rng(seed), obs_dim,
                             num_actions, tuple(hiddens))
        self._n_hidden = int(self._np.pop("__n_hidden__"))
        self._opt = optax.adam(lr)
        params = {k: jnp.asarray(v) for k, v in self._np.items()}
        self._state = (params, self._opt.init(params))
        n_hidden = self._n_hidden

        def loss_fn(params, obs, pi, z):
            h = obs
            for i in range(n_hidden):
                h = jax.nn.relu(h @ params[f"w{i}"] + params[f"b{i}"])
            logits = h @ params["wp"] + params["bp"]
            v = jnp.tanh(h @ params["wv"] + params["bv"])[:, 0]
            logp = jax.nn.log_softmax(logits)
            policy_loss = -jnp.mean(jnp.sum(pi * logp, axis=-1))
            value_loss = jnp.mean((v - z) ** 2)
            l2_loss = sum(jnp.sum(p ** 2) for k, p in params.items()
                          if k.startswith("w"))
            return policy_loss + value_loss + l2 * l2_loss, (
                policy_loss, value_loss)

        @jax.jit
        def update(state, obs, pi, z):
            params, opt_state = state
            (loss, (pl, vl)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, obs, pi, z)
            updates, opt_state = self._opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss, pl, vl

        self._update = update

    def update(self, obs: np.ndarray, pi: np.ndarray, z: np.ndarray
               ) -> dict:
        self._state, loss, pl, vl = self._update(
            self._state, obs.astype(np.float32), pi.astype(np.float32),
            z.astype(np.float32))
        return {"total_loss": float(loss), "policy_loss": float(pl),
                "value_loss": float(vl)}

    def get_weights(self) -> Dict[str, np.ndarray]:
        w = {k: np.asarray(v) for k, v in self._state[0].items()}
        w["__n_hidden__"] = np.int64(self._n_hidden)
        return w


# ------------------------------------------------------ self-play actor


class SelfPlayWorker:
    """Plays G games of MCTS self-play per call; returns training
    tuples (encoded_state, mcts_policy, outcome_for_player_to_move)."""

    def __init__(self, game_name: str, sims: int, temperature_moves: int,
                 seed: int = 0):
        self.game = _GAMES[game_name]
        self.sims = sims
        self.temp_moves = temperature_moves
        self.rng = np.random.default_rng(seed)
        self.weights: Optional[dict] = None

    def set_weights(self, w: dict):
        self.weights = dict(w)

    def play(self, num_games: int) -> Tuple[np.ndarray, np.ndarray,
                                            np.ndarray, dict]:
        game = self.game
        all_obs: List[np.ndarray] = []
        all_pi: List[np.ndarray] = []
        all_z: List[float] = []
        lengths = []
        for _ in range(num_games):
            mcts = MCTS(game, self.weights, sims=self.sims, rng=self.rng)
            state = game.initial()
            trajectory = []  # (obs, pi) per ply, perspective-local
            move = 0
            while True:
                term = game.outcome(state)
                if term is not None:
                    # walk back: term is from the CURRENT player-to-move's
                    # perspective; alternate signs up the trajectory
                    z = term
                    for obs, pi in reversed(trajectory):
                        z = -z
                        all_obs.append(obs)
                        all_pi.append(pi)
                        all_z.append(z)
                    lengths.append(move)
                    break
                temp = 1.0 if move < self.temp_moves else 1e-4
                pi = mcts.policy(state, temperature=temp)
                trajectory.append((game.encode(state), pi))
                action = int(self.rng.choice(game.num_actions, p=pi))
                state = game.step(state, action)
                move += 1
        return (np.stack(all_obs), np.stack(all_pi),
                np.asarray(all_z, np.float32),
                {"games": num_games,
                 "mean_length": float(np.mean(lengths))})


# ---------------------------------------------------------- algorithm


class AlphaZeroConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or AlphaZero)
        self.game = "tictactoe"
        self.num_rollout_workers = 2
        self.mcts_sims = 48
        self.games_per_worker = 8
        self.temperature_moves = 4
        self.train_epochs = 4
        self.batch_size = 256
        self.model_hiddens = (64, 64)
        self.lr = 1e-2
        self.replay_capacity = 8192


class AlphaZero(Algorithm):
    _config_cls = AlphaZeroConfig

    def setup(self, config: dict):
        cfg = config.get("__algo_config__") or self.get_default_config()
        cfg = cfg.copy()
        cfg.update_from_dict(
            {k: v for k, v in config.items() if k != "__algo_config__"})
        self.algo_config = cfg
        game = _GAMES[cfg.game]
        self.learner = AlphaZeroLearner(
            game.observation_dim, game.num_actions,
            hiddens=tuple(cfg.model_hiddens), lr=cfg.lr, seed=cfg.seed)
        worker_cls = ray_tpu.remote(SelfPlayWorker)
        self.workers = [
            worker_cls.options(num_cpus=1).remote(
                cfg.game, cfg.mcts_sims, cfg.temperature_moves,
                seed=cfg.seed + 1 + i)
            for i in range(cfg.num_rollout_workers)]
        self._replay: List[Tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        self._replay_size = 0
        self._rng = np.random.default_rng(cfg.seed)
        self._num_env_steps = 0
        self._sync_weights()

    def _sync_weights(self):
        w_ref = ray_tpu.put(self.learner.get_weights())
        ray_tpu.get([w.set_weights.remote(w_ref) for w in self.workers],
                    timeout=300)

    def training_step(self) -> dict:
        cfg = self.algo_config
        outs = ray_tpu.get(
            [w.play.remote(cfg.games_per_worker) for w in self.workers],
            timeout=600)
        games = 0
        for obs, pi, z, info in outs:
            self._replay.append((obs, pi, z))
            self._replay_size += len(z)
            self._num_env_steps += len(z)
            games += info["games"]
        while self._replay_size > cfg.replay_capacity and \
                len(self._replay) > 1:
            old = self._replay.pop(0)
            self._replay_size -= len(old[2])
        obs = np.concatenate([o for o, _, _ in self._replay])
        pi = np.concatenate([p for _, p, _ in self._replay])
        z = np.concatenate([zz for _, _, zz in self._replay])
        metrics: dict = {}
        n = len(z)
        for _ in range(cfg.train_epochs):
            idx = self._rng.permutation(n)[:cfg.batch_size]
            metrics = self.learner.update(obs[idx], pi[idx], z[idx])
        self._sync_weights()
        metrics.update(games_this_iter=games, replay_size=n,
                       env_steps_this_iter=n)
        return metrics

    def step(self) -> dict:
        metrics = self.training_step()
        metrics["num_env_steps_sampled"] = self._num_env_steps
        return metrics

    def save_checkpoint(self):
        return {"weights": self.learner.get_weights(),
                "num_env_steps": self._num_env_steps}

    def load_checkpoint(self, checkpoint):
        if checkpoint:
            w = dict(checkpoint["weights"])
            import jax.numpy as jnp

            n_hidden = int(w.pop("__n_hidden__"))
            params = {k: jnp.asarray(v) for k, v in w.items()}
            self.learner._n_hidden = n_hidden
            self.learner._state = (params,
                                   self.learner._opt.init(params))
            self._num_env_steps = checkpoint.get("num_env_steps", 0)
            self._sync_weights()

    def cleanup(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass

    # -------- evaluation helper (greedy MCTS move for play/eval) --------

    def compute_single_action(self, state: np.ndarray,
                              sims: Optional[int] = None) -> int:
        game = _GAMES[self.algo_config.game]
        mcts = MCTS(game, self.learner.get_weights(),
                    sims=sims or self.algo_config.mcts_sims,
                    noise_frac=0.0, rng=self._rng)
        return int(mcts.policy(state, temperature=1e-4).argmax())
