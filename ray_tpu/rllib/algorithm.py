"""Algorithm + AlgorithmConfig: the RLlib-equivalent driver layer.

Ref analogs: rllib/algorithms/algorithm.py:191 (Algorithm(Trainable),
setup :554, training_step :1402) and algorithm_config.py:118 (fluent
builder). Re-designed: rollout workers are plain CPU actors; the learner
is a local JAX object (or a grad-averaging LearnerGroup) so the update is
one XLA program on the accelerator the algorithm actor owns.
"""

from __future__ import annotations

import collections
import copy
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.tune.trainable import Trainable

from .learner import LearnerGroup
from .rollout_worker import RolloutWorker
from .sample_batch import SampleBatch, concat_samples


class AlgorithmConfig:
    """Fluent config (subset of the reference's fields, same shapes)."""

    def __init__(self, algo_class=None):
        self.algo_class = algo_class
        self.env = "CartPole-v1"
        self.num_rollout_workers = 2
        self.num_envs_per_worker = 4
        self.rollout_fragment_length = 64
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.lr = 3e-4
        self.train_batch_size = 512
        self.model_hiddens = (64, 64)
        self.seed = 0
        self.num_learners = 0
        self.entropy_coeff = 0.01
        self.vf_coeff = 0.5
        self.grad_clip = 0.5
        # zero-arg factory -> ConnectorPipeline; every rollout worker
        # builds its own stateful instance (ref:
        # connectors/agent/pipeline.py)
        self.connectors = None

    # ---- fluent sections (each returns self, ref: algorithm_config.py) ----

    def environment(self, env=None) -> "AlgorithmConfig":
        if env is not None:
            self.env = env
        return self

    def rollouts(self, *, num_rollout_workers: Optional[int] = None,
                 num_envs_per_worker: Optional[int] = None,
                 rollout_fragment_length: Optional[int] = None,
                 connectors=None) -> "AlgorithmConfig":
        if num_rollout_workers is not None:
            self.num_rollout_workers = num_rollout_workers
        if num_envs_per_worker is not None:
            self.num_envs_per_worker = num_envs_per_worker
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if connectors is not None:
            self.connectors = connectors
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for key, val in kwargs.items():
            if not hasattr(self, key):
                raise TypeError(f"unknown training option {key!r}")
            setattr(self, key, val)
        return self

    def resources(self, *, num_learners: Optional[int] = None
                  ) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def to_dict(self) -> dict:
        d = {k: v for k, v in self.__dict__.items() if k != "algo_class"}
        return d

    def update_from_dict(self, d: dict) -> "AlgorithmConfig":
        for key, val in d.items():
            if hasattr(self, key):
                setattr(self, key, val)
        return self

    def build(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("config has no algo_class; use PPOConfig() etc")
        return self.algo_class(config={"__algo_config__": self})


class Algorithm(Trainable):
    """Base: owns rollout-worker actors + a learner group; one train()
    iteration = one call of ``training_step()``."""

    _config_cls = AlgorithmConfig
    _worker_cls = RolloutWorker  # SAC swaps in ContinuousRolloutWorker

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return cls._config_cls(cls)

    # ---- Trainable API ----

    def setup(self, config: Dict[str, Any]):
        cfg = config.get("__algo_config__")
        if cfg is None:
            cfg = self.get_default_config()
        else:
            cfg = cfg.copy()
        # Tune search spaces override individual fields via plain keys
        cfg.update_from_dict(
            {k: v for k, v in config.items() if k != "__algo_config__"})
        self.algo_config = cfg
        worker_cls = ray_tpu.remote(self._worker_cls)
        self.workers: List = [
            worker_cls.options(num_cpus=1).remote(
                cfg.env, cfg.num_envs_per_worker,
                cfg.rollout_fragment_length, cfg.gamma, cfg.lambda_,
                cfg.model_hiddens, seed=cfg.seed + i, worker_idx=i,
                connectors=cfg.connectors)
            for i in range(cfg.num_rollout_workers)
        ]
        probe = self._probe_env = self._make_probe_env()
        # continuous envs report action_dim where discrete ones report
        # their action count — the factory knows which it asked for
        act_dim = (probe.action_dim if getattr(probe, "continuous", False)
                   else probe.num_actions)
        obs_dim = probe.observation_dim
        if cfg.connectors is not None:
            # the learner's net must be sized for CONNECTED observations
            # (factory or instance, same contract as RolloutWorker)
            pipe = cfg.connectors() if callable(cfg.connectors) \
                else cfg.connectors
            obs_dim = pipe.observation_dim(obs_dim)
        self.learners = LearnerGroup(
            self._make_learner_factory(cfg, obs_dim, act_dim),
            num_learners=cfg.num_learners)
        self._episode_returns: collections.deque = collections.deque(
            maxlen=50)
        self._num_env_steps = 0
        self._sync_weights()

    def _make_probe_env(self):
        from .env import make_env

        return make_env(self.algo_config.env)

    def _make_learner_factory(self, cfg, obs_dim, num_actions) -> Callable:
        raise NotImplementedError

    def training_step(self) -> dict:
        raise NotImplementedError

    def step(self) -> dict:
        t0 = time.perf_counter()
        metrics = self.training_step()
        elapsed = time.perf_counter() - t0
        for m in ray_tpu.get(
                [w.episode_metrics.remote() for w in self.workers],
                timeout=300):
            self._episode_returns.extend(m["episode_returns"])
        result = dict(metrics)
        result["num_env_steps_sampled"] = self._num_env_steps
        result["env_steps_per_sec"] = (
            metrics.get("env_steps_this_iter", 0) / max(elapsed, 1e-9))
        if self._episode_returns:
            result["episode_reward_mean"] = float(
                np.mean(self._episode_returns))
            result["episode_reward_max"] = float(
                np.max(self._episode_returns))
        return result

    def _sync_weights(self):
        w_ref = ray_tpu.put(self.learners.get_weights())
        ray_tpu.get([w.set_weights.remote(w_ref) for w in self.workers],
                    timeout=300)

    def save_checkpoint(self) -> Any:
        return {"weights": self.learners.get_weights(),
                "num_env_steps": self._num_env_steps}

    def load_checkpoint(self, checkpoint: Any):
        if checkpoint:
            self.learners.set_weights(checkpoint["weights"])
            self._num_env_steps = checkpoint.get("num_env_steps", 0)
            self._sync_weights()

    def cleanup(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        for r in getattr(self.learners, "remotes", []):
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    # convenience for direct (non-Tune) use, mirroring the reference
    def get_policy_weights(self) -> dict:
        return self.learners.get_weights()
