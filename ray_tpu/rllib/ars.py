"""ARS: augmented random search (Mania et al. 2018, V1-t).

Ref analog: rllib/algorithms/ars/ars.py — the same antithetic
perturbation machinery as ES but with the two "augmentations": only the
top-k best directions (by max of the pair's returns) contribute to the
update, and the step is scaled by the standard deviation of the selected
returns instead of centered ranks. Workers are the ES evaluation actors
verbatim — the algorithms differ only in how the driver combines
(seed, r+, r-) pairs. Observation normalization (ARS V2) composes via
the connector pipeline's NormalizeObs rather than being baked in.
"""

from __future__ import annotations

import numpy as np

import ray_tpu

from .algorithm import Algorithm
from .es import ESConfig, ESWorker, _flatten, _noise, _unflatten
from .env import make_env


class ARSConfig(ESConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ARS)
        self.perturbations_per_step = 16
        self.top_directions = 8      # k best antithetic pairs used
        self.sigma = 0.05
        self.lr = 0.02


class ARS(Algorithm):
    _config_cls = ARSConfig
    _worker_cls = ESWorker

    def setup(self, config):
        cfg = config.get("__algo_config__")
        cfg = cfg.copy() if cfg is not None else self.get_default_config()
        cfg.update_from_dict(
            {k: v for k, v in config.items() if k != "__algo_config__"})
        self.algo_config = cfg
        probe = make_env(cfg.env)
        assert not getattr(probe, "continuous", False), \
            "ARS here supports discrete-action envs"
        from .models import init_actor_critic

        weights = init_actor_critic(
            __import__("jax").random.key(cfg.seed),
            probe.observation_dim, probe.num_actions, cfg.model_hiddens)
        weights = {k: np.asarray(v) for k, v in weights.items()}
        self._flat, self._shapes = _flatten(weights)
        worker_cls = ray_tpu.remote(ESWorker)
        self.workers = [
            worker_cls.options(num_cpus=1).remote(
                cfg.env, cfg.episodes_per_perturbation,
                seed=cfg.seed + i, hiddens=cfg.model_hiddens)
            for i in range(cfg.num_rollout_workers)]
        self._seed_seq = cfg.seed * 1_000_003
        self._num_env_steps = 0

    def training_step(self) -> dict:
        cfg = self.algo_config
        n = cfg.perturbations_per_step
        seeds = [self._seed_seq + i for i in range(n)]
        self._seed_seq += n
        shards = np.array_split(np.asarray(seeds), len(self.workers))
        futs = [w.evaluate.remote(self._flat, self._shapes,
                                  [int(s) for s in shard], cfg.sigma)
                for w, shard in zip(self.workers, shards) if len(shard)]
        triples = [t for out in ray_tpu.get(futs, timeout=600)
                   for t in out]
        r_pos = np.asarray([t[1] for t in triples], np.float32)
        r_neg = np.asarray([t[2] for t in triples], np.float32)
        # top-k directions by the better of the pair
        k = min(cfg.top_directions, len(triples))
        order = np.argsort(-np.maximum(r_pos, r_neg))[:k]
        sel = np.asarray([r_pos[order], r_neg[order]])
        sigma_r = float(sel.std()) or 1.0
        grad = np.zeros_like(self._flat)
        for i in order:
            grad += (r_pos[i] - r_neg[i]) * _noise(
                int(triples[i][0]), self._flat.size)
        self._flat = self._flat + cfg.lr / (k * sigma_r) * grad
        return {"episode_reward_mean": float(
                    np.mean(np.concatenate([r_pos, r_neg]))),
                "episode_reward_max": float(
                    np.max(np.concatenate([r_pos, r_neg]))),
                "top_k_reward_mean": float(sel.mean()),
                "reward_std": sigma_r,
                "env_steps_this_iter": 0}

    def step(self) -> dict:
        return self.training_step()

    def get_policy_weights(self) -> dict:
        return _unflatten(self._flat, self._shapes)

    def save_checkpoint(self):
        return {"flat": self._flat, "seed_seq": self._seed_seq}

    def load_checkpoint(self, checkpoint):
        if checkpoint and "flat" in checkpoint:
            self._flat = np.asarray(checkpoint["flat"], np.float32)
            self._seed_seq = int(checkpoint["seed_seq"])

    def cleanup(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
