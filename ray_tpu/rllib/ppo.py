"""PPO: synchronous on-policy training.

Ref analog: rllib/algorithms/ppo/ppo.py:394 (PPOConfig) and :420
(training_step): synchronous parallel sampling -> SGD epochs over
minibatches -> weight broadcast.
"""

from __future__ import annotations

import ray_tpu

from .algorithm import Algorithm, AlgorithmConfig
from .learner import PPOLearner
from .sample_batch import concat_samples


class PPOConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or PPO)
        self.clip_param = 0.2
        self.num_sgd_iter = 4
        self.sgd_minibatch_size = 128


class PPO(Algorithm):
    _config_cls = PPOConfig

    def _make_learner_factory(self, cfg, obs_dim, num_actions):
        def make():
            return PPOLearner(
                obs_dim, num_actions, lr=cfg.lr,
                clip_param=cfg.clip_param, vf_coeff=cfg.vf_coeff,
                entropy_coeff=cfg.entropy_coeff, grad_clip=cfg.grad_clip,
                hiddens=cfg.model_hiddens, seed=cfg.seed)

        return make

    def training_step(self) -> dict:
        cfg = self.algo_config
        # 1. synchronous parallel sampling (ref: rollout_ops.py:21)
        batches = ray_tpu.get(
            [w.sample.remote() for w in self.workers], timeout=600)
        batch = concat_samples(batches)
        self._num_env_steps += batch.count
        # 2. SGD epochs over minibatches on the learner
        metrics = self.learners.update(
            batch, num_epochs=cfg.num_sgd_iter,
            minibatch_size=cfg.sgd_minibatch_size,
            seed=self.iteration)
        # 3. broadcast new weights to rollout workers
        self._sync_weights()
        metrics["env_steps_this_iter"] = batch.count
        return metrics
