"""ray_tpu.rllib — reinforcement learning on the ray_tpu runtime.

TPU-first re-design of the reference's RLlib (SURVEY.md §2.4; rllib/):
CPU rollout-worker actors step native vectorized envs; JAX learners run
the whole SGD step as one jitted XLA program on the accelerator; PPO is
the synchronous on-policy algorithm, IMPALA the asynchronous V-trace one.
Algorithms are Tune Trainables, so ``Tuner(PPO, param_space=...)`` works.

    from ray_tpu.rllib import PPOConfig
    algo = PPOConfig().environment("CartPole-v1").build()
    for _ in range(10):
        print(algo.train()["episode_reward_mean"])
"""

from .a2c import A2C, A2CConfig, A2CLearner
from .algorithm import Algorithm, AlgorithmConfig
from .alpha_zero import (MCTS, AlphaZero, AlphaZeroConfig,
                         AlphaZeroLearner, TicTacToe)
from .dreamer import (DreamerLearner, DreamerV3, DreamerV3Config,
                      SequenceBuffer)
from .apex_dqn import ApexDQN, ApexDQNConfig, ReplayShard
from .ars import ARS, ARSConfig
from .catalog import (ModelSpec, get_model, gru_forward, gru_unroll,
                      init_gru, register_custom_model)
from .appo import APPO, APPOConfig, APPOLearner
from .connectors import (ClipAction, ClipObs, Connector, ConnectorPipeline,
                         FlattenObs, NormalizeObs, UnsquashAction)
from .bandits import BanditConfig, BanditLinTS, BanditLinUCB
from .dqn import DQN, DQNConfig, DQNLearner
from .env import (BreakoutMini, CartPole, ContextualBandit, Env, Pendulum,
                  VectorEnv, make_env, register_env)
from .es import ES, ESConfig, ESWorker
from .impala import IMPALA, IMPALAConfig
from .offline import (BC, CQL, MARWIL, BCConfig, CQLConfig, MARWILConfig,
                      collect_dataset, load_batches, save_batches)
from .learner import ImpalaLearner, LearnerGroup, PPOLearner, vtrace
from .multi_agent import (MultiAgentBatch, MultiAgentEnv, MultiAgentPPO,
                          MultiAgentRolloutWorker)
from .policy import JaxPolicy
from .r2d2 import (R2D2, R2D2Config, R2D2Learner, R2D2RolloutWorker,
                   SequenceReplay)
from .replay_buffers import PrioritizedReplayBuffer, ReplayBuffer
from .ppo import PPO, PPOConfig
from .rollout_worker import ContinuousRolloutWorker, RolloutWorker
from .sac import SAC, SACConfig, SACLearner
from .td3 import (DDPG, TD3, DDPGConfig, TD3Config, TD3Learner,
                  TD3RolloutWorker)
from .sample_batch import SampleBatch, compute_gae, concat_samples

__all__ = [
    "Algorithm", "AlgorithmConfig", "PPO", "PPOConfig", "IMPALA",
    "DQN", "DQNConfig", "DQNLearner", "ReplayBuffer",
    "PrioritizedReplayBuffer",
    "IMPALAConfig", "Env", "CartPole", "VectorEnv", "make_env",
    "register_env", "JaxPolicy", "RolloutWorker", "SampleBatch",
    "concat_samples", "compute_gae", "PPOLearner", "ImpalaLearner",
    "LearnerGroup", "vtrace", "MultiAgentEnv", "MultiAgentBatch",
    "MultiAgentPPO", "MultiAgentRolloutWorker",
    "SAC", "SACConfig", "SACLearner", "Pendulum",
    "ContinuousRolloutWorker",
    "APPO", "APPOConfig", "APPOLearner", "ES", "ESConfig", "ESWorker",
    "BanditLinUCB", "BanditLinTS", "BanditConfig", "BC", "BCConfig",
    "CQL", "CQLConfig", "collect_dataset", "load_batches", "save_batches",
    "BreakoutMini", "ContextualBandit",
    "A2C", "A2CConfig", "A2CLearner", "ApexDQN", "ApexDQNConfig",
    "ReplayShard", "Connector", "ConnectorPipeline", "FlattenObs",
    "NormalizeObs", "ClipObs", "ClipAction", "UnsquashAction",
    "TD3", "TD3Config", "TD3Learner", "TD3RolloutWorker",
    "DDPG", "DDPGConfig", "MARWIL", "MARWILConfig", "ARS", "ARSConfig",
    "R2D2", "R2D2Config", "R2D2Learner", "R2D2RolloutWorker",
    "SequenceReplay", "ModelSpec", "get_model", "register_custom_model",
    "init_gru", "gru_forward", "gru_unroll",
    "AlphaZero", "AlphaZeroConfig", "AlphaZeroLearner", "MCTS",
    "TicTacToe",
    "DreamerV3", "DreamerV3Config", "DreamerLearner", "SequenceBuffer",
]

from ray_tpu.usage_stats import record_library_usage as _rlu
_rlu("rllib")
del _rlu
