"""RolloutWorker: CPU actor that steps a VectorEnv and emits SampleBatches.

Ref analog: rllib/evaluation/rollout_worker.py:159 (sample :660) — the
TPU-first split: rollouts stay on host CPUs as plain actors; only the
learner touches the accelerator. GAE postprocessing runs here so learners
receive ready-to-optimize batches (ref: evaluation/postprocessing.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from . import sample_batch as SB
from .connectors import ConnectorPipeline
from .env import VectorEnv
from .policy import JaxPolicy
from .sample_batch import SampleBatch, compute_gae


def _collect_transitions(vec: VectorEnv, rollout_len: int, select_actions,
                         act_shape: tuple, act_dtype,
                         conn: ConnectorPipeline) -> SampleBatch:
    """Shared (s, a, r, s', terminated) collection loop for the
    off-policy paths (DQN's epsilon-greedy and SAC's squashed-Gaussian
    workers differ only in action selection).

    Stores the PRE-reset terminal observation as NEXT_OBS and masks
    DONES to TERMINATED only — a time-limit truncation must still
    bootstrap, or the Bellman target regresses boundary transitions
    toward r alone (the classic timeout-bootstrap bug).
    """
    T, N = rollout_len, vec.num_envs
    D = conn.observation_dim(vec.observation_dim)
    obs_buf = np.zeros((T, N, D), np.float32)
    next_buf = np.zeros((T, N, D), np.float32)
    act_buf = np.zeros((T, N) + act_shape, act_dtype)
    rew_buf = np.zeros((T, N), np.float32)
    done_buf = np.zeros((T, N), np.bool_)

    obs = conn.transform_obs(vec.obs)
    for t in range(T):
        actions = select_actions(obs)
        obs_buf[t] = obs
        act_buf[t] = actions
        _, rewards, dones = vec.step(conn.transform_action(actions))
        # s' is an auxiliary view of (mostly) the same observations the
        # next iteration records — transform it with stats frozen so
        # running normalizers count each observation once
        conn.set_frozen(True)
        next_buf[t] = conn.transform_obs(vec.final_obs)
        conn.set_frozen(False)
        obs = conn.transform_obs(vec.obs)
        rew_buf[t] = rewards
        done_buf[t] = dones & ~vec.truncateds

    flat = lambda x: x.reshape((T * N,) + x.shape[2:])  # noqa: E731
    return SampleBatch({
        SB.OBS: flat(obs_buf),
        SB.ACTIONS: flat(act_buf),
        SB.REWARDS: flat(rew_buf),
        SB.DONES: flat(done_buf),
        SB.NEXT_OBS: flat(next_buf),
    })


class RolloutWorker:
    def __init__(self, env_creator, num_envs: int, rollout_len: int,
                 gamma: float, lam: float, hiddens=(64, 64),
                 seed: int = 0, worker_idx: int = 0, connectors=None):
        self.vec = VectorEnv(env_creator, num_envs, seed=seed * 1000 + 17)
        # env <-> policy coupling goes through the connector pipeline
        # (ref: connectors/agent/pipeline.py); a factory arrives here so
        # every worker owns its own (stateful) instance
        self.conn = connectors() if callable(connectors) else \
            (connectors or ConnectorPipeline())
        self.obs_dim = self.conn.observation_dim(self.vec.observation_dim)
        self.policy = JaxPolicy(self.obs_dim,
                                self.vec.num_actions, hiddens,
                                seed=seed)
        self.rollout_len = rollout_len
        self.gamma = gamma
        self.lam = lam
        self.worker_idx = worker_idx
        self._eps_seq = 0  # decorrelates sample_transitions RNG per call

    def sample(self) -> SampleBatch:
        """Collect one rollout of [T, N] and flatten to [T*N] with GAE."""
        T, N = self.rollout_len, self.vec.num_envs
        obs_buf = np.zeros((T, N, self.obs_dim), np.float32)
        act_buf = np.zeros((T, N), np.int64)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.bool_)
        logp_buf = np.zeros((T, N), np.float32)
        vf_buf = np.zeros((T, N), np.float32)
        logits_buf = np.zeros((T, N, self.vec.num_actions), np.float32)

        obs = self.conn.transform_obs(self.vec.obs)
        for t in range(T):
            actions, logp, vf, logits = self.policy.compute_actions(obs)
            obs_buf[t] = obs
            act_buf[t] = actions
            logp_buf[t] = logp
            vf_buf[t] = vf
            logits_buf[t] = logits
            _, rewards, dones = self.vec.step(
                self.conn.transform_action(actions))
            obs = self.conn.transform_obs(self.vec.obs)
            rew_buf[t] = rewards
            done_buf[t] = dones

        last_value = self.policy.value(obs)
        adv, targets = compute_gae(rew_buf, vf_buf, done_buf, last_value,
                                   self.gamma, self.lam)
        flat = lambda x: x.reshape((T * N,) + x.shape[2:])  # noqa: E731
        return SampleBatch({
            SB.OBS: flat(obs_buf),
            SB.ACTIONS: flat(act_buf),
            SB.REWARDS: flat(rew_buf),
            SB.DONES: flat(done_buf),
            SB.ACTION_LOGP: flat(logp_buf),
            SB.VF_PREDS: flat(vf_buf),
            SB.BEHAVIOUR_LOGITS: flat(logits_buf),
            SB.ADVANTAGES: flat(adv),
            SB.VALUE_TARGETS: flat(targets),
        })

    def sample_time_major(self) -> SampleBatch:
        """[T, N]-shaped batch (IMPALA/V-trace needs the time axis)."""
        T, N = self.rollout_len, self.vec.num_envs
        obs_buf = np.zeros((T, N, self.obs_dim), np.float32)
        act_buf = np.zeros((T, N), np.int64)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.bool_)
        logp_buf = np.zeros((T, N), np.float32)

        obs = self.conn.transform_obs(self.vec.obs)
        for t in range(T):
            actions, logp, _, _ = self.policy.compute_actions(obs)
            obs_buf[t] = obs
            act_buf[t] = actions
            logp_buf[t] = logp
            _, rewards, dones = self.vec.step(
                self.conn.transform_action(actions))
            obs = self.conn.transform_obs(self.vec.obs)
            rew_buf[t] = rewards
            done_buf[t] = dones

        return SampleBatch({
            SB.OBS: obs_buf,
            SB.ACTIONS: act_buf,
            SB.REWARDS: rew_buf,
            SB.DONES: done_buf,
            SB.ACTION_LOGP: logp_buf,
            "bootstrap_obs": obs.copy(),
        })

    def sample_transitions(self, epsilon: float = 0.0) -> SampleBatch:
        """(s, a, r, s', done) tuples with epsilon-greedy exploration —
        the off-policy (DQN) collection path (ref: rollout_worker sample
        with EpsilonGreedy exploration, utils/exploration/epsilon_greedy
        .py). The policy's logits head is read as Q-values."""
        N = self.vec.num_envs
        rng = np.random.default_rng(
            int(epsilon * 1e6) + self.worker_idx * 7919 + self._eps_seq)
        self._eps_seq += 1

        def select(obs):
            greedy, _ = self.policy._greedy(
                self.policy.params, np.asarray(obs, np.float32))
            actions = np.array(greedy)  # writable copy (jax views are RO)
            explore = rng.random(N) < epsilon
            actions[explore] = rng.integers(
                0, self.vec.num_actions, size=int(explore.sum()))
            return actions

        return _collect_transitions(self.vec, self.rollout_len, select,
                                    (), np.int64, self.conn)

    # ---- weight sync / metrics ----

    def set_weights(self, weights: Dict[str, np.ndarray]):
        self.policy.set_weights(weights)

    def get_weights(self) -> Dict[str, np.ndarray]:
        return self.policy.get_weights()

    def episode_metrics(self) -> dict:
        rets, lens = self.vec.pop_episode_metrics()
        return {"episode_returns": rets, "episode_lengths": lens}

    def ping(self) -> bool:
        return True


class ContinuousRolloutWorker:
    """Rollout actor for continuous-action envs (the SAC collection path).

    Same contract as RolloutWorker.sample_transitions, but actions come
    from a SquashedGaussianPolicy; ``epsilon`` is the probability of a
    uniform-random action (warmup exploration before learning starts,
    ref analog: SACConfig num_steps_sampled_before_learning_starts +
    random exploration).
    """

    def __init__(self, env_creator, num_envs: int, rollout_len: int,
                 gamma: float, lam: float, hiddens=(64, 64),
                 seed: int = 0, worker_idx: int = 0, connectors=None):
        from .policy import SquashedGaussianPolicy

        self.vec = VectorEnv(env_creator, num_envs, seed=seed * 1000 + 17)
        assert self.vec.continuous, "use RolloutWorker for discrete envs"
        self.conn = connectors() if callable(connectors) else \
            (connectors or ConnectorPipeline())
        self._env_creator = env_creator
        env0 = self.vec.envs[0]
        self.policy = SquashedGaussianPolicy(
            self.conn.observation_dim(self.vec.observation_dim),
            self.vec.action_dim,
            action_scale=(env0.action_high - env0.action_low) / 2.0,
            action_shift=(env0.action_high + env0.action_low) / 2.0,
            hiddens=hiddens, seed=seed)
        self.rollout_len = rollout_len
        self.worker_idx = worker_idx
        self._rng = np.random.default_rng(seed * 7919 + 23)

    def sample_transitions(self, epsilon: float = 0.0) -> SampleBatch:
        N, A = self.vec.num_envs, self.vec.action_dim
        env0 = self.vec.envs[0]
        lo, hi = env0.action_low, env0.action_high

        def select(obs):
            if epsilon >= 1.0:  # pure warmup: skip the policy forward
                return self._rng.uniform(
                    lo, hi, size=(N, A)).astype(np.float32)
            actions, _ = self.policy.compute_actions(obs)
            if epsilon > 0.0:
                rand = self._rng.random(N) < epsilon
                if rand.any():
                    actions = np.array(actions)
                    actions[rand] = self._rng.uniform(
                        lo, hi,
                        size=(int(rand.sum()), A)).astype(np.float32)
            return actions

        return _collect_transitions(self.vec, self.rollout_len, select,
                                    (A,), np.float32, self.conn)

    def evaluate(self, num_episodes: int = 5, seed: int = 0) -> dict:
        """Deterministic (mean-action) eval on a fresh env from the SAME
        creator the rollouts use (a configured creator must configure the
        eval env identically)."""
        from .env import make_env

        env = make_env(self._env_creator)
        returns = []
        self.conn.set_frozen(True)  # eval must not pollute running stats
        try:
            for ep in range(num_episodes):
                obs = env.reset(seed=10_000 + seed * 100 + ep)
                total, done = 0.0, False
                while not done:
                    pobs = self.conn.transform_obs(obs[None])
                    a, _ = self.policy.compute_actions(pobs, explore=False)
                    a = self.conn.transform_action(a)
                    obs, r, done, _ = env.step(a[0])
                    total += r
                returns.append(total)
        finally:
            self.conn.set_frozen(False)
        return {"mean_return": float(np.mean(returns)), "returns": returns}

    def set_weights(self, weights: Dict[str, np.ndarray]):
        self.policy.set_weights(weights)

    def get_weights(self) -> Dict[str, np.ndarray]:
        return self.policy.get_weights()

    def episode_metrics(self) -> dict:
        rets, lens = self.vec.pop_episode_metrics()
        return {"episode_returns": rets, "episode_lengths": lens}

    def ping(self) -> bool:
        return True
