"""JaxPolicy: jitted action computation + weight transport.

Ref analog: rllib/policy/policy.py:177 (compute_actions, get/set_weights) —
re-designed: one jitted sample step (forward + categorical sample + logp)
shared by rollout workers; weights move as numpy pytrees through the object
store.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .models import (forward, gaussian_forward, init_actor_critic,
                     init_gaussian_actor, logp_of, squashed_sample)


class JaxPolicy:
    def __init__(self, obs_dim: int, num_actions: int,
                 hiddens=(64, 64), seed: int = 0):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self._rng = jax.random.key(seed)
        self.params = init_actor_critic(
            jax.random.key(seed), obs_dim, num_actions, hiddens)

        @jax.jit
        def _sample(params, obs, rng):
            logits, value = forward(params, obs)
            actions = jax.random.categorical(rng, logits)
            logp = logp_of(logits, actions)
            return actions, logp, value, logits

        @jax.jit
        def _greedy(params, obs):
            logits, value = forward(params, obs)
            return jnp.argmax(logits, axis=-1), value

        self._sample = _sample
        self._greedy = _greedy

    def compute_actions(self, obs: np.ndarray, explore: bool = True
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                   np.ndarray]:
        """-> (actions, logp, vf_preds, logits) as numpy."""
        obs = jnp.asarray(obs, jnp.float32)
        if explore:
            self._rng, sub = jax.random.split(self._rng)
            a, lp, v, lg = self._sample(self.params, obs, sub)
        else:
            a, v = self._greedy(self.params, obs)
            lp = jnp.zeros_like(v)
            lg = jnp.zeros((obs.shape[0], self.num_actions))
        return (np.asarray(a), np.asarray(lp), np.asarray(v),
                np.asarray(lg))

    def value(self, obs: np.ndarray) -> np.ndarray:
        _, v = self._greedy(self.params, jnp.asarray(obs, jnp.float32))
        return np.asarray(v)

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.params.items()}

    def set_weights(self, weights: Dict[str, np.ndarray]):
        self.params = {k: jnp.asarray(v) for k, v in weights.items()}


class SquashedGaussianPolicy:
    """Continuous-action policy: a = scale*tanh(u), u ~ N(mu, std).

    The rollout-side half of SAC (ref analog: the deterministic/stochastic
    action path of rllib/algorithms/sac/sac_torch_policy.py) — one jitted
    sample step; weights move as numpy pytrees like JaxPolicy's.
    """

    def __init__(self, obs_dim: int, action_dim: int, action_scale: float,
                 hiddens=(64, 64), seed: int = 0,
                 action_shift: float = 0.0):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.action_scale = float(action_scale)
        self.action_shift = float(action_shift)
        self._rng = jax.random.key(seed)
        self.params = init_gaussian_actor(
            jax.random.key(seed), obs_dim, action_dim, hiddens)
        scale, shift = self.action_scale, self.action_shift

        @jax.jit
        def _sample(params, obs, rng):
            return squashed_sample(params, obs, rng, scale, shift)

        @jax.jit
        def _mean(params, obs):
            mu, _ = gaussian_forward(params, obs)
            return shift + scale * jnp.tanh(mu)

        self._sample_fn = _sample
        self._mean_fn = _mean

    def compute_actions(self, obs: np.ndarray, explore: bool = True
                        ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (actions [B, A], logp [B]) as numpy."""
        obs = jnp.asarray(obs, jnp.float32)
        if explore:
            self._rng, sub = jax.random.split(self._rng)
            a, lp = self._sample_fn(self.params, obs, sub)
        else:
            a = self._mean_fn(self.params, obs)
            lp = jnp.zeros(obs.shape[0])
        return np.asarray(a), np.asarray(lp)

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.params.items()}

    def set_weights(self, weights: Dict[str, np.ndarray]):
        self.params = {k: jnp.asarray(v) for k, v in weights.items()}
