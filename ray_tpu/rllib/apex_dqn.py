"""Ape-X DQN: distributed prioritized replay as sharded replay ACTORS.

Ref analog: rllib/algorithms/apex_dqn/apex_dqn.py (ApexDQN — rollout
workers push samples into ReplayActor shards, the learner pulls batches
and pushes priority updates back asynchronously, target net syncs on an
env-step cadence). Re-design on this runtime: replay shards are plain
``@remote`` actors wrapping PrioritizedReplayBuffer; the transfer of
fresh sample batches rides the OBJECT PLANE (the worker's batch object
ref is passed to the shard actor, which resolves it store-to-store —
the driver never copies the data), and the learner stays local to the
accelerator like DQN's (the Ape-X split of concerns: actors explore,
shards remember, one learner burns FLOPs).
"""

from __future__ import annotations

from typing import List

import numpy as np

import ray_tpu

from .algorithm import Algorithm
from .dqn import DQN, DQNConfig
from .replay_buffers import PrioritizedReplayBuffer
from .sample_batch import SampleBatch, concat_samples


class ApexDQNConfig(DQNConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or ApexDQN)
        self.num_replay_shards = 2
        # per-worker exploration: worker i uses eps_i = base ** (1 + i/N)
        # (the Ape-X constant-per-actor epsilon ladder), instead of one
        # global annealed epsilon
        self.per_worker_epsilon_base = 0.4


class ReplayShard:
    """One replay shard actor: add / sample / update_priorities.

    Samples are returned WITH their shard-local indexes; the learner
    routes priority updates back to the shard each batch came from."""

    def __init__(self, capacity: int, alpha: float, seed: int = 0):
        self.buf = PrioritizedReplayBuffer(capacity, alpha=alpha,
                                           seed=seed)

    def add(self, batch: SampleBatch) -> int:
        # `batch` arrives as a resolved object-plane ref (the rollout
        # worker produced it; this actor pulled it store-to-store)
        self.buf.add(batch)
        return len(self.buf)

    def size(self) -> int:
        return len(self.buf)

    def sample(self, n: int, beta: float):
        return self.buf.sample(n, beta=beta)

    def update_priorities(self, idx, prios):
        self.buf.update_priorities(np.asarray(idx), np.asarray(prios))

    def num_added(self) -> int:
        return self.buf.num_added

    def stats(self) -> dict:
        return self.buf.stats()


class ApexDQN(DQN):
    _config_cls = ApexDQNConfig

    def setup(self, config):
        Algorithm.setup(self, config)  # skip DQN's local-buffer setup
        cfg = self.algo_config
        shard_cls = ray_tpu.remote(ReplayShard)
        self.replay_shards: List = [
            shard_cls.options(num_cpus=0.5).remote(
                max(1, cfg.replay_buffer_capacity
                    // cfg.num_replay_shards),
                cfg.prioritized_replay_alpha, seed=cfg.seed + 101 * i)
            for i in range(cfg.num_replay_shards)
        ]
        self._last_target_sync = 0
        self._shard_rr = 0  # round-robin push cursor
        self._rng = np.random.default_rng(cfg.seed + 7)

    def _worker_epsilons(self) -> List[float]:
        cfg = self.algo_config
        n = max(len(self.workers), 1)
        return [cfg.per_worker_epsilon_base ** (1 + i / max(n - 1, 1) * 7)
                for i in range(n)]

    def training_step(self) -> dict:
        cfg = self.algo_config
        # 1. parallel exploration with per-worker epsilons; each worker's
        #    batch ref is handed STRAIGHT to a replay shard (object-plane
        #    transfer, no driver copy)
        eps = self._worker_epsilons()
        sample_refs = [w.sample_transitions.remote(e)
                       for w, e in zip(self.workers, eps)]
        add_refs = []
        for ref in sample_refs:
            shard = self.replay_shards[self._shard_rr
                                       % len(self.replay_shards)]
            self._shard_rr += 1
            add_refs.append(shard.add.remote(ref))
        ray_tpu.get(add_refs, timeout=300)  # barrier: all pushes landed
        # one consistent size sample per shard (summing per-push returns
        # would double-count shards pushed more than once this iter)
        sizes = ray_tpu.get([s.size.remote() for s in self.replay_shards],
                            timeout=60)
        steps = cfg.rollout_fragment_length * cfg.num_envs_per_worker \
            * len(self.workers)
        self._num_env_steps += steps
        metrics = {"env_steps_this_iter": steps,
                   "replay_size": int(sum(sizes)),
                   "worker_epsilons": [round(e, 4) for e in eps]}

        added = sum(ray_tpu.get(
            [s.num_added.remote() for s in self.replay_shards],
            timeout=60))
        learner = self.learners.local
        if added >= cfg.num_steps_sampled_before_learning_starts:
            losses = []
            for _ in range(cfg.num_updates_per_iter):
                # 2. pull a batch from a random shard, learn, route |TD|
                #    priorities back to THAT shard (async — the next pull
                #    overlaps the update)
                shard = self.replay_shards[
                    int(self._rng.integers(len(self.replay_shards)))]
                sample = ray_tpu.get(
                    shard.sample.remote(cfg.train_batch_size,
                                        cfg.prioritized_replay_beta),
                    timeout=60)
                if sample is None:
                    break
                out = learner.update(sample)
                losses.append(out["loss"])
                shard.update_priorities.remote(
                    sample["batch_indexes"], out["td_abs"])
            if losses:
                metrics["loss"] = float(np.mean(losses))
            if self._num_env_steps - self._last_target_sync >= \
                    cfg.target_network_update_freq:
                learner.sync_target()
                self._last_target_sync = self._num_env_steps
            self._sync_weights()
        return metrics
