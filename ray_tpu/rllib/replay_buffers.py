"""Replay buffers: uniform ring + proportional prioritized.

Ref analogs: rllib/utils/replay_buffers/replay_buffer.py:71 (ReplayBuffer:
add/sample/len, ring storage) and prioritized_replay_buffer.py:19
(PrioritizedReplayBuffer: proportional sampling with importance weights,
alpha/beta annealing). Re-designed storage: instead of a deque of episode
objects, columns are preallocated numpy arrays (SampleBatch columns), so
sample() is one vectorized gather that feeds the JAX learner without
Python-loop assembly — the TPU learner wants one contiguous batch.
The priority tree is a flat numpy segment tree (O(log n) updates,
vectorized prefix-sum sampling).
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from .sample_batch import SampleBatch


class ReplayBuffer:
    """Uniform-sampling ring buffer over SampleBatch columns."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._cols: Dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)
        self._num_added = 0

    def __len__(self) -> int:
        return self._size

    @property
    def num_added(self) -> int:
        return self._num_added

    def _ensure_storage(self, batch: SampleBatch):
        for k, v in batch.items():
            if k not in self._cols:
                v = np.asarray(v)
                self._cols[k] = np.zeros((self.capacity,) + v.shape[1:],
                                         v.dtype)

    def add(self, batch: SampleBatch):
        """Append a batch of transitions (vectorized ring write)."""
        n = batch.count
        if n == 0:
            return
        self._ensure_storage(batch)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._cols[k][idx] = np.asarray(v)[:n]
        self._next = int((self._next + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))
        self._num_added += n
        return idx

    def sample(self, num_items: int) -> Optional[SampleBatch]:
        if self._size == 0:
            return None
        idx = self._rng.integers(0, self._size, size=num_items)
        out = SampleBatch({k: c[idx] for k, c in self._cols.items()})
        out["batch_indexes"] = idx
        return out

    def update_priorities(self, idx, priorities):  # uniform: no-op
        pass

    def stats(self) -> dict:
        return {"size": self._size, "num_added": self._num_added,
                "capacity": self.capacity}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (alpha exponent, beta IS weights).

    Priorities live in a flat binary-indexed segment tree so sampling N
    items is N vectorized descents (ref: utils/replay_buffers/
    prioritized_replay_buffer.py + execution/segment_tree.py)."""

    def __init__(self, capacity: int = 100_000, *, alpha: float = 0.6,
                 seed: int = 0):
        super().__init__(capacity, seed)
        if alpha <= 0:
            raise ValueError("alpha must be positive")
        self._alpha = alpha
        # full binary tree over `capacity` leaves, 1-indexed internal nodes
        self._tree_size = 1
        while self._tree_size < self.capacity:
            self._tree_size *= 2
        self._sum_tree = np.zeros(2 * self._tree_size, np.float64)
        self._max_priority = 1.0

    # ------------------------------------------------------ tree ops

    def _set_priorities(self, idx: np.ndarray, prios: np.ndarray):
        if len(idx) == 0:
            return
        pos = idx + self._tree_size
        self._sum_tree[pos] = prios
        # propagate sums up, one vectorized recompute per level; stop at
        # the root (pos==1) — capacity==1 puts leaves AT the root, where
        # there is nothing to propagate
        pos = np.unique(pos // 2)
        while len(pos) and pos[-1] >= 1:
            pos = pos[pos >= 1]
            self._sum_tree[pos] = (self._sum_tree[2 * pos]
                                   + self._sum_tree[2 * pos + 1])
            if pos[0] == 1 and len(pos) == 1:
                break
            pos = np.unique(pos // 2)

    def _sample_indices(self, n: int) -> np.ndarray:
        total = self._sum_tree[1]
        # stratified prefix targets (lower variance than iid uniforms)
        seg = total / n
        targets = (np.arange(n) + self._rng.random(n)) * seg
        pos = np.ones(n, np.int64)
        while pos[0] < self._tree_size:
            left = 2 * pos
            left_sum = self._sum_tree[left]
            go_right = targets > left_sum
            targets = np.where(go_right, targets - left_sum, targets)
            pos = np.where(go_right, left + 1, left)
        return pos - self._tree_size

    # ----------------------------------------------------- buffer API

    def add(self, batch: SampleBatch):
        n = batch.count
        if n == 0:
            return
        idx = super().add(batch)
        self._set_priorities(
            np.asarray(idx),
            np.full(len(idx), self._max_priority ** self._alpha))
        return idx

    def sample(self, num_items: int, beta: float = 0.4
               ) -> Optional[SampleBatch]:
        if self._size == 0 or self._sum_tree[1] <= 0:
            return None
        idx = np.minimum(self._sample_indices(num_items), self._size - 1)
        out = SampleBatch({k: c[idx] for k, c in self._cols.items()})
        out["batch_indexes"] = idx
        # importance-sampling weights, normalized by the max weight
        probs = self._sum_tree[idx + self._tree_size] / self._sum_tree[1]
        weights = (self._size * np.maximum(probs, 1e-12)) ** (-beta)
        out["weights"] = (weights / weights.max()).astype(np.float32)
        return out

    def update_priorities(self, idx, priorities):
        prios = np.maximum(np.asarray(priorities, np.float64), 1e-6)
        self._max_priority = max(self._max_priority, float(prios.max()))
        self._set_priorities(np.asarray(idx, np.int64),
                             prios ** self._alpha)
