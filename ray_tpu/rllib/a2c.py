"""A2C: synchronous advantage actor-critic.

Ref analog: rllib/algorithms/a2c/a2c.py (A2CConfig, training_step —
sample synchronously from all workers, ONE gradient step on the joint
batch, broadcast). The TPU-first shape mirrors PPO's learner but with
the vanilla policy-gradient loss (no ratio clipping, no SGD epochs):
the whole update is one jitted XLA program; microbatching is available
via ``microbatch_size`` (the reference's A2C grad-accumulation knob).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu

from . import sample_batch as SB
from .algorithm import Algorithm, AlgorithmConfig
from .models import entropy_of, forward, init_actor_critic, logp_of
from .sample_batch import SampleBatch, concat_samples


class A2CConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or A2C)
        self.lr = 1e-3
        self.microbatch_size = 0  # 0 = single step on the whole batch


class A2CLearner:
    """One jitted actor-critic gradient step (loss = -logp * adv +
    vf_coeff * vf_mse - entropy_coeff * entropy)."""

    def __init__(self, obs_dim: int, num_actions: int, *, lr: float,
                 vf_coeff: float, entropy_coeff: float, grad_clip: float,
                 hiddens=(64, 64), seed: int = 0):
        self.params = init_actor_critic(jax.random.key(seed), obs_dim,
                                        num_actions, hiddens)
        self.tx = optax.chain(optax.clip_by_global_norm(grad_clip),
                              optax.adam(lr))
        self.opt_state = self.tx.init(self.params)

        def loss_fn(params, batch):
            logits, values = forward(params, batch[SB.OBS])
            logp = logp_of(logits, batch[SB.ACTIONS])
            adv = batch[SB.ADVANTAGES]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            pi_loss = -(logp * adv).mean()
            vf_loss = jnp.mean((values - batch[SB.VALUE_TARGETS]) ** 2)
            ent = entropy_of(logits).mean()
            total = pi_loss + vf_coeff * vf_loss - entropy_coeff * ent
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": ent}

        @jax.jit
        def train_step(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        @jax.jit
        def grad_step(params, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            metrics["total_loss"] = loss
            return grads, metrics

        @jax.jit
        def apply_grads_step(params, opt_state, grads):
            updates, opt_state = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state

        self._train_step = train_step
        self._grad_step = grad_step
        self._apply_grads_step = apply_grads_step

    def update(self, batch: SampleBatch, *, microbatch_size: int = 0,
               **_) -> dict:
        if microbatch_size and batch.count > microbatch_size:
            # grad ACCUMULATION (the reference's A2C microbatch knob):
            # average microbatch grads, then ONE optimizer step (adv
            # normalization stays per-microbatch, as in the reference)
            acc, metric_sums, n = None, {}, 0
            for mb in batch.minibatches(microbatch_size):
                grads, metrics = self._grad_step(
                    self.params, {k: jnp.asarray(v)
                                  for k, v in mb.items()})
                acc = grads if acc is None else jax.tree.map(
                    jnp.add, acc, grads)
                for k, v in metrics.items():
                    metric_sums[k] = metric_sums.get(k, 0.0) + float(v)
                n += 1
            self.params, self.opt_state = self._apply_grads_step(
                self.params, self.opt_state,
                jax.tree.map(lambda g: g / n, acc))
            return {k: v / n for k, v in metric_sums.items()}
        self.params, self.opt_state, metrics = self._train_step(
            self.params, self.opt_state,
            {k: jnp.asarray(v) for k, v in batch.items()})
        return {k: float(v) for k, v in metrics.items()}

    # distributed (grad-averaging) path — LearnerGroup remote learners
    def compute_grads(self, batch: SampleBatch):
        grads, metrics = self._grad_step(
            self.params, {k: jnp.asarray(v) for k, v in batch.items()})
        return ({k: np.asarray(v) for k, v in grads.items()},
                {k: float(v) for k, v in metrics.items()})

    def apply_grads(self, grads: Dict[str, np.ndarray]):
        self.params, self.opt_state = self._apply_grads_step(
            self.params, self.opt_state,
            {k: jnp.asarray(v) for k, v in grads.items()})

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.params.items()}

    def set_weights(self, weights: Dict[str, np.ndarray]):
        self.params = {k: jnp.asarray(v) for k, v in weights.items()}


class A2C(Algorithm):
    _config_cls = A2CConfig

    def _make_learner_factory(self, cfg, obs_dim, num_actions):
        def make():
            return A2CLearner(obs_dim, num_actions, lr=cfg.lr,
                              vf_coeff=cfg.vf_coeff,
                              entropy_coeff=cfg.entropy_coeff,
                              grad_clip=cfg.grad_clip,
                              hiddens=cfg.model_hiddens, seed=cfg.seed)

        return make

    def training_step(self) -> dict:
        cfg = self.algo_config
        batches = ray_tpu.get(
            [w.sample.remote() for w in self.workers], timeout=600)
        batch = concat_samples(batches)
        self._num_env_steps += batch.count
        metrics = self.learners.update(
            batch, microbatch_size=cfg.microbatch_size)
        self._sync_weights()
        metrics["env_steps_this_iter"] = batch.count
        return metrics
