"""Environments: a minimal Env protocol, classic-control tasks, VectorEnv.

Ref analogs: rllib/env/base_env.py + env/vector_env.py (the reference wraps
gym; this image has no gym, so the classic CartPole dynamics are implemented
directly — same physics constants as gym's cartpole.py, which are public
textbook values from Barto, Sutton & Anderson 1983).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class Env:
    """Single environment: reset() -> obs; step(a) -> (obs, r, done, info)."""

    observation_dim: int
    num_actions: int
    max_episode_steps: int = 1000

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, dict]:
        raise NotImplementedError


class CartPole(Env):
    """Pole balancing; solved threshold 475 (v1 cap 500)."""

    observation_dim = 4
    num_actions = 2
    max_episode_steps = 500

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5  # half pole length
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * math.pi / 360
    X_LIMIT = 2.4

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self._state = np.zeros(4, np.float32)
        self._steps = 0

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._steps = 0
        return self._state.copy()

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE_MAG if action == 1 else -self.FORCE_MAG
        costh, sinth = math.cos(theta), math.sin(theta)
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH
        temp = (force + polemass_length * theta_dot ** 2 * sinth) / total_mass
        theta_acc = (self.GRAVITY * sinth - costh * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costh ** 2
                           / total_mass))
        x_acc = temp - polemass_length * theta_acc * costh / total_mass
        x += self.TAU * x_dot
        x_dot += self.TAU * x_acc
        theta += self.TAU * theta_dot
        theta_dot += self.TAU * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self._steps += 1
        done = bool(abs(x) > self.X_LIMIT or abs(theta) > self.THETA_LIMIT
                    or self._steps >= self.max_episode_steps)
        return self._state.copy(), 1.0, done, {}


class StatelessGuess(Env):
    """Trivial 1-step bandit-ish env for fast unit tests: reward 1 iff the
    action matches the sign feature of the observation."""

    observation_dim = 2
    num_actions = 2
    max_episode_steps = 1

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self._obs = np.zeros(2, np.float32)

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        sign = 1.0 if self._rng.random() < 0.5 else -1.0
        self._obs = np.array([sign, self._rng.random()], np.float32)
        return self._obs.copy()

    def step(self, action: int):
        want = 1 if self._obs[0] > 0 else 0
        r = 1.0 if action == want else 0.0
        return self.reset(), r, True, {}


_REGISTRY: Dict[str, Callable[[], Env]] = {
    "CartPole-v1": CartPole,
    "StatelessGuess-v0": StatelessGuess,
}


def register_env(name: str, creator: Callable[[], Env]):
    """Custom env registration (ref: rllib tune.register_env)."""
    _REGISTRY[name] = creator


def make_env(name_or_creator) -> Env:
    if callable(name_or_creator):
        return name_or_creator()
    try:
        return _REGISTRY[name_or_creator]()
    except KeyError:
        raise KeyError(
            f"unknown env {name_or_creator!r}; registered: "
            f"{sorted(_REGISTRY)}") from None


class VectorEnv:
    """N env copies stepped together with auto-reset on done.

    Ref analog: rllib/env/vector_env.py:37 (_VectorizedGymEnv); completed
    episode returns/lengths are surfaced for metrics.
    """

    def __init__(self, creator, num_envs: int, seed: int = 0):
        self.envs: List[Env] = [make_env(creator) for _ in range(num_envs)]
        self.num_envs = num_envs
        self.obs = np.stack([e.reset(seed + i)
                             for i, e in enumerate(self.envs)])
        self._ep_rew = np.zeros(num_envs, np.float64)
        self._ep_len = np.zeros(num_envs, np.int64)
        self.episode_returns: List[float] = []
        self.episode_lengths: List[int] = []

    @property
    def observation_dim(self) -> int:
        return self.envs[0].observation_dim

    @property
    def num_actions(self) -> int:
        return self.envs[0].num_actions

    def step(self, actions: np.ndarray):
        """-> (next_obs [N,D], rewards [N], dones [N])."""
        obs_out = np.empty_like(self.obs)
        rews = np.zeros(self.num_envs, np.float32)
        dones = np.zeros(self.num_envs, np.bool_)
        for i, env in enumerate(self.envs):
            o, r, d, _ = env.step(int(actions[i]))
            self._ep_rew[i] += r
            self._ep_len[i] += 1
            if d:
                self.episode_returns.append(float(self._ep_rew[i]))
                self.episode_lengths.append(int(self._ep_len[i]))
                self._ep_rew[i] = 0.0
                self._ep_len[i] = 0
                o = env.reset()
            obs_out[i] = o
            rews[i] = r
            dones[i] = d
        self.obs = obs_out
        return obs_out.copy(), rews, dones

    def pop_episode_metrics(self) -> Tuple[List[float], List[int]]:
        rets, lens = self.episode_returns, self.episode_lengths
        self.episode_returns, self.episode_lengths = [], []
        return rets, lens
