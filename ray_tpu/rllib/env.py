"""Environments: a minimal Env protocol, classic-control tasks, VectorEnv.

Ref analogs: rllib/env/base_env.py + env/vector_env.py (the reference wraps
gym; this image has no gym, so the classic CartPole dynamics are implemented
directly — same physics constants as gym's cartpole.py, which are public
textbook values from Barto, Sutton & Anderson 1983).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class Env:
    """Single environment: reset() -> obs; step(a) -> (obs, r, done, info).

    Discrete envs set ``num_actions``; continuous envs set
    ``continuous = True``, ``action_dim``, and ``action_low/high`` (and
    receive a float32 [action_dim] array in step()).
    """

    observation_dim: int
    num_actions: int = 0
    max_episode_steps: int = 1000
    continuous: bool = False
    action_dim: int = 0
    action_low: float = -1.0
    action_high: float = 1.0

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, dict]:
        raise NotImplementedError


class CartPole(Env):
    """Pole balancing; solved threshold 475 (v1 cap 500)."""

    observation_dim = 4
    num_actions = 2
    max_episode_steps = 500

    GRAVITY = 9.8
    MASSCART = 1.0
    MASSPOLE = 0.1
    LENGTH = 0.5  # half pole length
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * math.pi / 360
    X_LIMIT = 2.4

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self._state = np.zeros(4, np.float32)
        self._steps = 0

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._steps = 0
        return self._state.copy()

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE_MAG if action == 1 else -self.FORCE_MAG
        costh, sinth = math.cos(theta), math.sin(theta)
        total_mass = self.MASSCART + self.MASSPOLE
        polemass_length = self.MASSPOLE * self.LENGTH
        temp = (force + polemass_length * theta_dot ** 2 * sinth) / total_mass
        theta_acc = (self.GRAVITY * sinth - costh * temp) / (
            self.LENGTH * (4.0 / 3.0 - self.MASSPOLE * costh ** 2
                           / total_mass))
        x_acc = temp - polemass_length * theta_acc * costh / total_mass
        x += self.TAU * x_dot
        x_dot += self.TAU * x_acc
        theta += self.TAU * theta_dot
        theta_dot += self.TAU * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self._steps += 1
        fell = bool(abs(x) > self.X_LIMIT or abs(theta) > self.THETA_LIMIT)
        timeout = self._steps >= self.max_episode_steps
        info = {"truncated": True} if (timeout and not fell) else {}
        return self._state.copy(), 1.0, fell or timeout, info


class Pendulum(Env):
    """Torque-controlled pendulum swing-up (continuous actions).

    Same dynamics as gym's pendulum.py (public textbook inverted-pendulum
    physics): obs = [cos th, sin th, th_dot], action = torque in [-2, 2],
    reward = -(th^2 + 0.1 th_dot^2 + 0.001 u^2). Episodes are fixed
    200-step (never "done" early). The continuous-control workhorse for
    SAC (ref analog: rllib's Pendulum-v1 tuned examples).
    """

    observation_dim = 3
    continuous = True
    action_dim = 1
    action_low = -2.0
    action_high = 2.0
    max_episode_steps = 200

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self._th = 0.0
        self._th_dot = 0.0
        self._steps = 0

    def _obs(self) -> np.ndarray:
        return np.array([math.cos(self._th), math.sin(self._th),
                         self._th_dot], np.float32)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._th = float(self._rng.uniform(-math.pi, math.pi))
        self._th_dot = float(self._rng.uniform(-1.0, 1.0))
        self._steps = 0
        return self._obs()

    def step(self, action):
        u = float(np.clip(np.asarray(action, np.float32).reshape(-1)[0],
                          -self.MAX_TORQUE, self.MAX_TORQUE))
        th, th_dot = self._th, self._th_dot
        norm_th = ((th + math.pi) % (2 * math.pi)) - math.pi
        cost = norm_th ** 2 + 0.1 * th_dot ** 2 + 0.001 * u ** 2
        th_dot = th_dot + (3.0 * self.G / (2.0 * self.L) * math.sin(th)
                           + 3.0 / (self.M * self.L ** 2) * u) * self.DT
        th_dot = float(np.clip(th_dot, -self.MAX_SPEED, self.MAX_SPEED))
        th = th + th_dot * self.DT
        self._th, self._th_dot = th, th_dot
        self._steps += 1
        # the episode only ever ends by time limit: pure truncation
        done = self._steps >= self.max_episode_steps
        return self._obs(), -cost, done, {"truncated": True} if done else {}


class StatelessGuess(Env):
    """Trivial 1-step bandit-ish env for fast unit tests: reward 1 iff the
    action matches the sign feature of the observation."""

    observation_dim = 2
    num_actions = 2
    max_episode_steps = 1

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self._obs = np.zeros(2, np.float32)

    def reset(self, seed: Optional[int] = None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        sign = 1.0 if self._rng.random() < 0.5 else -1.0
        self._obs = np.array([sign, self._rng.random()], np.float32)
        return self._obs.copy()

    def step(self, action: int):
        want = 1 if self._obs[0] > 0 else 0
        r = 1.0 if action == want else 0.0
        return self.reset(), r, True, {}


class BreakoutMini(Env):
    """MinAtar-style Breakout on a 10x10 grid (Atari-class benchmark env).

    Ref analog: the reference's RLlib Atari suites (tuned_examples/*atari*)
    run on ALE via gym; this image has neither, so the environment is a
    from-scratch miniature in the spirit of MinAtar (Young & Tian 2019):
    4 feature planes (2-wide paddle, ball, ball trail, bricks) on a
    10x10 board, 3 actions (stay/left/right), +1 per brick, episode ends
    when the ball falls past the paddle. Observation is the flattened
    400-float board — enough spatial structure that linear policies
    plateau, which is what a learner-throughput benchmark needs from
    "Atari-class". (The paddle is 2 cells: brick bounces redirect the
    ball unpredictably, and a 1-cell paddle at ball speed makes some
    rallies geometrically unwinnable.)
    """

    N = 10
    observation_dim = 4 * N * N
    num_actions = 3
    max_episode_steps = 1000

    def __init__(self):
        self._rng = np.random.default_rng(0)
        self.reset()

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        n = self.N
        self._paddle = n // 2
        self._ball_x = int(self._rng.integers(0, n))
        self._ball_y = 3
        self._dx = 1 if self._rng.random() < 0.5 else -1
        self._dy = 1
        self._trail_x, self._trail_y = self._ball_x, self._ball_y
        self._bricks = np.ones((3, n), np.bool_)
        self._steps = 0
        return self._obs()

    def _obs(self) -> np.ndarray:
        n = self.N
        planes = np.zeros((4, n, n), np.float32)
        planes[0, n - 1, self._paddle] = 1.0
        planes[0, n - 1, min(self._paddle + 1, n - 1)] = 1.0
        planes[1, self._ball_y, self._ball_x] = 1.0
        planes[2, self._trail_y, self._trail_x] = 1.0
        planes[3, :3, :] = self._bricks
        return planes.reshape(-1)

    def step(self, action: int):
        n = self.N
        if action == 1:
            self._paddle = max(0, self._paddle - 1)
        elif action == 2:
            self._paddle = min(n - 2, self._paddle + 1)
        self._trail_x, self._trail_y = self._ball_x, self._ball_y
        nx = self._ball_x + self._dx
        ny = self._ball_y + self._dy
        if nx < 0 or nx >= n:  # side wall
            self._dx = -self._dx
            nx = self._ball_x + self._dx
        reward = 0.0
        if ny < 0:  # ceiling
            self._dy = 1
            ny = self._ball_y + self._dy
        if ny < 3 and self._bricks[ny, nx]:  # brick hit
            self._bricks[ny, nx] = False
            reward = 1.0
            self._dy = -self._dy
            ny = self._ball_y + self._dy
        done = False
        if ny == n - 1:  # paddle row (paddle covers 2 cells)
            if nx in (self._paddle, self._paddle + 1):
                self._dy = -1
                ny = self._ball_y + self._dy
            else:
                done = True  # ball lost
        if not self._bricks.any():  # cleared: fresh wall, keep going
            self._bricks[:] = True
        self._ball_x, self._ball_y = nx, ny
        self._steps += 1
        timeout = self._steps >= self.max_episode_steps
        info = {"truncated": True} if (timeout and not done) else {}
        return self._obs(), reward, done or timeout, info


class ContextualBandit(Env):
    """Linear contextual bandit: one-step episodes, K arms whose expected
    reward is a fixed hidden linear function of the context.

    Ref analog: rllib/env/wrappers + the bandit envs under
    rllib/examples/env/bandit_envs_discrete.py — redesigned minimal: the
    env owns hidden arm vectors theta_k; reward = theta_k . x + noise;
    ``best_mean`` is exposed so tests measure regret exactly.
    """

    CONTEXT_DIM = 8
    NUM_ARMS = 5
    observation_dim = CONTEXT_DIM
    num_actions = NUM_ARMS
    max_episode_steps = 1

    def __init__(self):
        self._rng = np.random.default_rng(0)
        theta_rng = np.random.default_rng(1234)  # fixed task
        self.theta = theta_rng.normal(
            size=(self.NUM_ARMS, self.CONTEXT_DIM)).astype(np.float32)
        self.theta /= np.linalg.norm(self.theta, axis=1, keepdims=True)
        self.noise = 0.1
        self._ctx = np.zeros(self.CONTEXT_DIM, np.float32)

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._ctx = self._rng.normal(
            size=self.CONTEXT_DIM).astype(np.float32)
        self._ctx /= max(np.linalg.norm(self._ctx), 1e-8)
        return self._ctx.copy()

    def means(self) -> np.ndarray:
        return self.theta @ self._ctx

    def step(self, action: int):
        means = self.means()
        r = float(means[action] + self._rng.normal() * self.noise)
        info = {"regret": float(means.max() - means[action])}
        return self.reset(), r, True, info


_REGISTRY: Dict[str, Callable[[], Env]] = {
    "CartPole-v1": CartPole,
    "Pendulum-v1": Pendulum,
    "StatelessGuess-v0": StatelessGuess,
    "Breakout-Mini-v0": BreakoutMini,
    "ContextualBandit-v0": ContextualBandit,
}


def register_env(name: str, creator: Callable[[], Env]):
    """Custom env registration (ref: rllib tune.register_env)."""
    _REGISTRY[name] = creator


def make_env(name_or_creator) -> Env:
    if callable(name_or_creator):
        return name_or_creator()
    try:
        return _REGISTRY[name_or_creator]()
    except KeyError:
        raise KeyError(
            f"unknown env {name_or_creator!r}; registered: "
            f"{sorted(_REGISTRY)}") from None


class VectorEnv:
    """N env copies stepped together with auto-reset on done.

    Ref analog: rllib/env/vector_env.py:37 (_VectorizedGymEnv); completed
    episode returns/lengths are surfaced for metrics.
    """

    def __init__(self, creator, num_envs: int, seed: int = 0):
        self.envs: List[Env] = [make_env(creator) for _ in range(num_envs)]
        self.num_envs = num_envs
        self.obs = np.stack([e.reset(seed + i)
                             for i, e in enumerate(self.envs)])
        self._ep_rew = np.zeros(num_envs, np.float64)
        self._ep_len = np.zeros(num_envs, np.int64)
        self.episode_returns: List[float] = []
        self.episode_lengths: List[int] = []
        # per-step truncation view (time-limit "done"s that must still
        # bootstrap, ref: postprocessing's TimeLimit handling) and the
        # PRE-reset terminal observation for done envs
        self.truncateds = np.zeros(num_envs, np.bool_)
        self.final_obs = self.obs.copy()

    @property
    def observation_dim(self) -> int:
        return self.envs[0].observation_dim

    @property
    def num_actions(self) -> int:
        return self.envs[0].num_actions

    @property
    def continuous(self) -> bool:
        return self.envs[0].continuous

    @property
    def action_dim(self) -> int:
        return self.envs[0].action_dim

    def step(self, actions: np.ndarray):
        """-> (next_obs [N,D], rewards [N], dones [N]).

        ``actions`` is int [N] for discrete envs, float32 [N, action_dim]
        for continuous ones.
        """
        cont = self.continuous
        obs_out = np.empty_like(self.obs)
        rews = np.zeros(self.num_envs, np.float32)
        dones = np.zeros(self.num_envs, np.bool_)
        self.truncateds = np.zeros(self.num_envs, np.bool_)
        for i, env in enumerate(self.envs):
            o, r, d, info = env.step(
                np.asarray(actions[i], np.float32) if cont
                else int(actions[i]))
            self._ep_rew[i] += r
            self._ep_len[i] += 1
            self.final_obs[i] = o
            if d:
                self.truncateds[i] = bool(info.get("truncated", False))
                self.episode_returns.append(float(self._ep_rew[i]))
                self.episode_lengths.append(int(self._ep_len[i]))
                self._ep_rew[i] = 0.0
                self._ep_len[i] = 0
                o = env.reset()
            obs_out[i] = o
            rews[i] = r
            dones[i] = d
        self.obs = obs_out
        return obs_out.copy(), rews, dones

    def pop_episode_metrics(self) -> Tuple[List[float], List[int]]:
        rets, lens = self.episode_returns, self.episode_lengths
        self.episode_returns, self.episode_lengths = [], []
        return rets, lens
