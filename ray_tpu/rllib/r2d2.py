"""R2D2: recurrent-replay distributed DQN (Kapturowski et al. 2019).

Ref analog: rllib/algorithms/r2d2/r2d2.py (R2D2Config: model.use_lstm,
zero_init_states/burn-in knobs, replay_buffer_config with
storage_unit="sequences") and r2d2_torch_policy.py (burn-in unroll +
h-stored sequence replay). TPU-first re-design: the whole sequence
update — burn-in unroll under stop_gradient, train-segment unroll, double
Q-learning targets, Huber loss, Adam — is ONE jitted XLA program whose
time dimension is a lax.scan (static sequence length, MXU-batched over
sequences); the replay buffer hands it contiguous [B, T, ...] numpy.

Simplifications vs the paper, stated: 1-step targets (not n-step),
no distributed prioritization (ApexDQN covers the distributed-replay
axis here), stored-state strategy with in-sequence episode resets.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu

from .algorithm import Algorithm, AlgorithmConfig
from .catalog import gru_forward, gru_unroll, init_gru
from .connectors import ConnectorPipeline
from .env import VectorEnv
from .sample_batch import SampleBatch


class R2D2Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or R2D2)
        self.lr = 1e-3
        self.train_batch_size = 32        # sequences per update
        self.seq_len = 16                 # trained timesteps per sequence
        self.burn_in = 4                  # unrolled-not-trained prefix
        self.replay_buffer_capacity = 4000   # sequences
        self.num_steps_sampled_before_learning_starts = 1000
        self.target_network_update_freq = 1000  # env steps
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.02
        self.epsilon_timesteps = 10_000
        self.num_updates_per_iter = 16
        self.gru_hidden = 64


class SequenceReplay:
    """Uniform replay over fixed-length sequences.

    Each entry: obs [T, D], actions/rewards/dones [T], reset [T] (True
    where a new episode begins at that step), h0 [H] (the recurrent
    state STORED at collection time, the paper's stored-state strategy).
    """

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self._data: List[dict] = []
        self._next = 0
        self.num_added = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self):
        return len(self._data)

    def add(self, seqs: List[dict]):
        for s in seqs:
            if len(self._data) < self.capacity:
                self._data.append(s)
            else:
                self._data[self._next] = s
                self._next = (self._next + 1) % self.capacity
            self.num_added += 1

    def sample(self, n: int) -> Optional[Dict[str, np.ndarray]]:
        if not self._data:
            return None
        idx = self._rng.integers(0, len(self._data), n)
        keys = self._data[0].keys()
        return {k: np.stack([self._data[i][k] for i in idx])
                for k in keys}


class R2D2Learner:
    """Online + target recurrent Q-nets; one jitted sequence update."""

    def __init__(self, obs_dim: int, num_actions: int, *, lr: float,
                 gamma: float, burn_in: int, hidden: int = 64,
                 seed: int = 0):
        self.params = init_gru(jax.random.key(seed), obs_dim,
                               num_actions, hidden)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.opt = optax.adam(lr)
        self.opt_state = self.opt.init(self.params)

        def q_seq(params, obs, reset, h0):
            # [B, T, ...] -> time-major scan -> back
            logits, _, _ = gru_unroll(
                params, obs.swapaxes(0, 1), h0,
                reset.swapaxes(0, 1))
            return logits.swapaxes(0, 1)  # [B, T, A]

        def loss_fn(params, target_params, batch):
            obs, reset, h0 = (batch["obs"], batch["reset"], batch["h0"])
            if burn_in:
                # burn-in: warm the carry without training through it
                _, _, h_live = gru_unroll(
                    params, obs[:, :burn_in].swapaxes(0, 1), h0,
                    reset[:, :burn_in].swapaxes(0, 1))
                _, _, h_tgt = gru_unroll(
                    target_params, obs[:, :burn_in].swapaxes(0, 1), h0,
                    reset[:, :burn_in].swapaxes(0, 1))
                h_live = jax.lax.stop_gradient(h_live)
                h_tgt = jax.lax.stop_gradient(h_tgt)
                obs = obs[:, burn_in:]
                reset = reset[:, burn_in:]
            else:
                h_live = h_tgt = h0
            acts = batch["actions"][:, burn_in:]
            rews = batch["rewards"][:, burn_in:]
            dones = batch["dones"][:, burn_in:].astype(jnp.float32)
            q_all = q_seq(params, obs, reset, h_live)       # [B, T, A]
            q_tgt = q_seq(target_params, obs, reset, h_tgt)
            q_sel = jnp.take_along_axis(
                q_all[:, :-1], acts[:, :-1, None], axis=2).squeeze(-1)
            # double-Q: online argmax at t+1, target net's value
            a_star = jnp.argmax(q_all[:, 1:], axis=2)
            q_next = jnp.take_along_axis(
                q_tgt[:, 1:], a_star[:, :, None], axis=2).squeeze(-1)
            # a step that ENDS its episode bootstraps nothing; a reset at
            # t+1 means q_next belongs to a different episode — mask both
            valid_next = 1.0 - jnp.maximum(
                dones[:, :-1], reset[:, 1:].astype(jnp.float32))
            target = rews[:, :-1] + gamma * valid_next * q_next
            td = q_sel - jax.lax.stop_gradient(target)
            return optax.huber_loss(td, jnp.zeros_like(td),
                                    delta=1.0).mean()

        @jax.jit
        def train_step(params, target_params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, target_params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._train_step = train_step

    def update(self, batch: Dict[str, np.ndarray]) -> dict:
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, loss = self._train_step(
            self.params, self.target_params, self.opt_state, jb)
        return {"loss": float(loss)}

    def sync_target(self):
        self.target_params = jax.tree.map(jnp.copy, self.params)

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.params.items()}

    def set_weights(self, weights):
        self.params = {k: jnp.asarray(v) for k, v in weights.items()}
        self.sync_target()


class R2D2RolloutWorker:
    """Steps a VectorEnv with the recurrent policy (carry persists
    across calls, clears on episode end) and emits stored-state training
    sequences of seq_len + burn_in steps."""

    def __init__(self, env_creator, num_envs: int, seq_len: int,
                 burn_in: int, hidden: int = 64, seed: int = 0,
                 worker_idx: int = 0, connectors=None):
        self.vec = VectorEnv(env_creator, num_envs, seed=seed * 1000 + 17)
        self.conn = connectors() if callable(connectors) else \
            (connectors or ConnectorPipeline())
        self.obs_dim = self.conn.observation_dim(self.vec.observation_dim)
        self.seq_len = seq_len
        self.burn_in = burn_in
        self.hidden = hidden
        self.params = {k: np.asarray(v) for k, v in init_gru(
            jax.random.key(seed), self.obs_dim, self.vec.num_actions,
            hidden).items()}
        self._h = np.zeros((num_envs, hidden), np.float32)
        self._rng = np.random.default_rng(seed * 7919 + 29)
        self._fwd = jax.jit(gru_forward)
        self._episode_returns: List[float] = []
        self._ep_ret = np.zeros(num_envs, np.float32)

    def sample_sequences(self, epsilon: float) -> List[dict]:
        """Collect T = burn_in + seq_len steps and cut one sequence per
        env, h0 = the carry at collection start."""
        T, N = self.burn_in + self.seq_len, self.vec.num_envs
        D, H = self.obs_dim, self.hidden
        h0 = self._h.copy()
        obs_buf = np.zeros((N, T, D), np.float32)
        act_buf = np.zeros((N, T), np.int64)
        rew_buf = np.zeros((N, T), np.float32)
        done_buf = np.zeros((N, T), np.bool_)
        reset_buf = np.zeros((N, T), np.bool_)

        obs = self.conn.transform_obs(self.vec.obs)
        for t in range(T):
            q, _v, h_new = self._fwd(
                {k: jnp.asarray(v) for k, v in self.params.items()},
                jnp.asarray(obs), jnp.asarray(self._h))
            acts = np.asarray(jnp.argmax(q, axis=-1))
            explore = self._rng.random(N) < epsilon
            acts = np.where(
                explore,
                self._rng.integers(0, self.vec.num_actions, N), acts)
            obs_buf[:, t] = obs
            act_buf[:, t] = acts
            _, rewards, dones = self.vec.step(
                self.conn.transform_action(acts))
            obs = self.conn.transform_obs(self.vec.obs)
            rew_buf[:, t] = rewards
            done_buf[:, t] = dones & ~self.vec.truncateds
            # np.array (copy): asarray of a jax array is a READ-ONLY view
            # and the episode-boundary clear below writes into it
            self._h = np.array(h_new)
            self._ep_ret += rewards
            ended = dones | self.vec.truncateds
            if ended.any():
                # clear the carry at episode boundaries; mark the NEXT
                # step as a reset point inside the sequence
                self._h[ended] = 0.0
                if t + 1 < T:
                    reset_buf[ended, t + 1] = True
                for i in np.nonzero(ended)[0]:
                    self._episode_returns.append(float(self._ep_ret[i]))
                    self._ep_ret[i] = 0.0
        return [{"obs": obs_buf[i], "actions": act_buf[i],
                 "rewards": rew_buf[i], "dones": done_buf[i],
                 "reset": reset_buf[i], "h0": h0[i]}
                for i in range(N)]

    def set_weights(self, weights):
        self.params = {k: np.asarray(v) for k, v in weights.items()}

    def get_weights(self):
        return dict(self.params)

    def episode_metrics(self) -> dict:
        out = {"episode_returns": self._episode_returns,
               "episode_lengths": []}
        self._episode_returns = []
        return out


class R2D2(Algorithm):
    _config_cls = R2D2Config

    def setup(self, config):
        cfg = config.get("__algo_config__")
        cfg = cfg.copy() if cfg is not None else self.get_default_config()
        cfg.update_from_dict(
            {k: v for k, v in config.items() if k != "__algo_config__"})
        self.algo_config = cfg
        worker_cls = ray_tpu.remote(R2D2RolloutWorker)
        self.workers = [
            worker_cls.options(num_cpus=1).remote(
                cfg.env, cfg.num_envs_per_worker, cfg.seq_len,
                cfg.burn_in, hidden=cfg.gru_hidden, seed=cfg.seed + i,
                worker_idx=i, connectors=cfg.connectors)
            for i in range(cfg.num_rollout_workers)]
        probe = self._make_probe_env()
        obs_dim = probe.observation_dim
        if cfg.connectors is not None:
            pipe = cfg.connectors() if callable(cfg.connectors) \
                else cfg.connectors
            obs_dim = pipe.observation_dim(obs_dim)
        self.learner = R2D2Learner(
            obs_dim, probe.num_actions, lr=cfg.lr, gamma=cfg.gamma,
            burn_in=cfg.burn_in, hidden=cfg.gru_hidden, seed=cfg.seed)
        # base-class cleanup()/step() look at self.learners; the single
        # local recurrent learner fills that slot
        self.learners = self.learner
        self.replay = SequenceReplay(cfg.replay_buffer_capacity,
                                     seed=cfg.seed)
        self._episode_returns = __import__("collections").deque(maxlen=50)
        self._num_env_steps = 0
        self._last_target_sync = 0
        self._sync_weights()

    def _sync_weights(self):
        w_ref = ray_tpu.put(self.learner.get_weights())
        ray_tpu.get([w.set_weights.remote(w_ref) for w in self.workers],
                    timeout=300)

    def _epsilon(self) -> float:
        cfg = self.algo_config
        frac = min(1.0, self._num_env_steps / max(cfg.epsilon_timesteps, 1))
        return cfg.epsilon_initial + frac * (cfg.epsilon_final
                                             - cfg.epsilon_initial)

    def training_step(self) -> dict:
        cfg = self.algo_config
        eps = self._epsilon()
        seq_lists = ray_tpu.get(
            [w.sample_sequences.remote(eps) for w in self.workers],
            timeout=300)
        n_steps = 0
        for seqs in seq_lists:
            self.replay.add(seqs)
            n_steps += sum(len(s["actions"]) for s in seqs)
        self._num_env_steps += n_steps
        metrics = {"env_steps_this_iter": n_steps, "epsilon": eps,
                   "replay_sequences": len(self.replay)}
        if self._num_env_steps >= \
                cfg.num_steps_sampled_before_learning_starts:
            losses = []
            for _ in range(cfg.num_updates_per_iter):
                batch = self.replay.sample(cfg.train_batch_size)
                if batch is None:
                    break
                losses.append(self.learner.update(batch)["loss"])
            if losses:
                metrics["loss"] = float(np.mean(losses))
            if self._num_env_steps - self._last_target_sync >= \
                    cfg.target_network_update_freq:
                self.learner.sync_target()
                self._last_target_sync = self._num_env_steps
            self._sync_weights()
        return metrics

    def save_checkpoint(self):
        return {"weights": self.learner.get_weights(),
                "num_env_steps": self._num_env_steps}

    def load_checkpoint(self, checkpoint):
        if checkpoint:
            self.learner.set_weights(checkpoint["weights"])
            self._num_env_steps = checkpoint.get("num_env_steps", 0)
            self._sync_weights()

    def get_policy_weights(self) -> dict:
        return self.learner.get_weights()
