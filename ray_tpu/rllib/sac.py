"""SAC: off-policy maximum-entropy actor-critic for continuous control.

Ref analogs: rllib/algorithms/sac/sac.py:34 (SACConfig: twin-Q, tau,
target-entropy/alpha knobs, training_step via the DQN-style
sample->store->replay->learn loop) and sac_torch_policy.py (actor/critic/
alpha losses). TPU-first re-design: the whole update — twin-critic
Bellman regression against the entropy-regularized target, reparameterized
actor step, temperature (alpha) step, and the Polyak target blend — is ONE
jitted XLA program over a contiguous replay batch; rollouts stay CPU
actors (ContinuousRolloutWorker).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu

from . import sample_batch as SB
from .algorithm import Algorithm, AlgorithmConfig
from .models import (init_gaussian_actor, init_q_net, q_forward,
                     squashed_sample)
from .replay_buffers import ReplayBuffer
from .rollout_worker import ContinuousRolloutWorker
from .sample_batch import SampleBatch, concat_samples


class SACConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or SAC)
        self.env = "Pendulum-v1"
        self.lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.train_batch_size = 128
        self.replay_buffer_capacity = 100_000
        self.num_steps_sampled_before_learning_starts = 1000
        self.tau = 0.005                 # Polyak target blend
        self.initial_alpha = 0.2
        self.target_entropy = None       # None -> -action_dim (SAC paper)
        self.num_updates_per_iter = 64
        self.warmup_random_action_prob = 1.0


class SACLearner:
    """Actor + twin critics + targets + learnable temperature; one jitted
    update step (losses per Haarnoja et al. 2018, the same ones the
    reference's sac_torch_policy.py implements with three torch
    optimizers — here a single fused XLA program)."""

    def __init__(self, obs_dim: int, action_dim: int, *, actor_lr: float,
                 critic_lr: float, alpha_lr: float, gamma: float,
                 tau: float, action_scale: float, initial_alpha: float,
                 target_entropy: float, hiddens=(64, 64), seed: int = 0,
                 action_shift: float = 0.0):
        k = jax.random.split(jax.random.key(seed), 3)
        self.state = {
            "actor": init_gaussian_actor(k[0], obs_dim, action_dim,
                                         hiddens),
            "q1": init_q_net(k[1], obs_dim, action_dim, hiddens),
            "q2": init_q_net(k[2], obs_dim, action_dim, hiddens),
            "log_alpha": jnp.asarray(float(np.log(initial_alpha))),
        }
        self.state["tq1"] = jax.tree.map(jnp.copy, self.state["q1"])
        self.state["tq2"] = jax.tree.map(jnp.copy, self.state["q2"])
        self._actor_opt = optax.adam(actor_lr)
        self._critic_opt = optax.adam(critic_lr)
        self._alpha_opt = optax.adam(alpha_lr)
        self.opt_state = {
            "actor": self._actor_opt.init(self.state["actor"]),
            "critic": self._critic_opt.init(
                (self.state["q1"], self.state["q2"])),
            "alpha": self._alpha_opt.init(self.state["log_alpha"]),
        }
        self._rng = jax.random.key(seed + 1)
        scale, shift = float(action_scale), float(action_shift)

        def critic_loss(qs, actor, tq1, tq2, alpha, batch, rng):
            q1p, q2p = qs
            a_next, logp_next = squashed_sample(
                actor, batch[SB.NEXT_OBS], rng, scale, shift)
            tq = jnp.minimum(q_forward(tq1, batch[SB.NEXT_OBS], a_next),
                             q_forward(tq2, batch[SB.NEXT_OBS], a_next))
            not_done = 1.0 - batch[SB.DONES].astype(jnp.float32)
            target = batch[SB.REWARDS] + gamma * not_done * (
                tq - alpha * logp_next)
            target = jax.lax.stop_gradient(target)
            e1 = q_forward(q1p, batch[SB.OBS], batch[SB.ACTIONS]) - target
            e2 = q_forward(q2p, batch[SB.OBS], batch[SB.ACTIONS]) - target
            return jnp.mean(e1 ** 2) + jnp.mean(e2 ** 2)

        def actor_loss(actor, q1p, q2p, alpha, batch, rng):
            a, logp = squashed_sample(actor, batch[SB.OBS], rng, scale,
                                      shift)
            q = jnp.minimum(q_forward(q1p, batch[SB.OBS], a),
                            q_forward(q2p, batch[SB.OBS], a))
            return jnp.mean(alpha * logp - q), logp

        @jax.jit
        def train_step(state, opt_state, batch, rng):
            r1, r2 = jax.random.split(rng)
            alpha = jnp.exp(state["log_alpha"])

            closs, cgrads = jax.value_and_grad(critic_loss)(
                (state["q1"], state["q2"]), state["actor"],
                state["tq1"], state["tq2"], alpha, batch, r1)
            cupd, copt = self._critic_opt.update(
                cgrads, opt_state["critic"],
                (state["q1"], state["q2"]))
            q1, q2 = optax.apply_updates(
                (state["q1"], state["q2"]), cupd)

            (aloss, logp), agrads = jax.value_and_grad(
                actor_loss, has_aux=True)(
                state["actor"], q1, q2, alpha, batch, r2)
            aupd, aopt = self._actor_opt.update(
                agrads, opt_state["actor"], state["actor"])
            actor = optax.apply_updates(state["actor"], aupd)

            # temperature: alpha tracks target entropy on the FRESH logp
            lgrad = jax.grad(
                lambda la: -la * jax.lax.stop_gradient(
                    jnp.mean(logp) + target_entropy))(state["log_alpha"])
            lupd, lopt = self._alpha_opt.update(
                lgrad, opt_state["alpha"], state["log_alpha"])
            log_alpha = optax.apply_updates(state["log_alpha"], lupd)

            blend = lambda t, o: jax.tree.map(  # noqa: E731
                lambda a, b: tau * a + (1.0 - tau) * b, t, o)
            new_state = {"actor": actor, "q1": q1, "q2": q2,
                         "log_alpha": log_alpha,
                         "tq1": blend(q1, state["tq1"]),
                         "tq2": blend(q2, state["tq2"])}
            new_opt = {"actor": aopt, "critic": copt, "alpha": lopt}
            metrics = {"critic_loss": closs, "actor_loss": aloss,
                       "alpha": alpha, "entropy": -jnp.mean(logp)}
            return new_state, new_opt, metrics

        self._train_step = train_step

    def update(self, batch: SampleBatch) -> dict:
        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k in (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.DONES,
                       SB.NEXT_OBS)}
        self._rng, sub = jax.random.split(self._rng)
        self.state, self.opt_state, metrics = self._train_step(
            self.state, self.opt_state, jb, sub)
        return {k: float(v) for k, v in metrics.items()}

    # weights contract: workers only need the actor

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.state["actor"].items()}

    def set_weights(self, weights: Dict[str, np.ndarray]):
        self.state["actor"] = {k: jnp.asarray(v)
                               for k, v in weights.items()}

    def full_state(self) -> dict:
        """Everything resume needs: params/targets/alpha AND the three
        Adam states + RNG key (restoring without optimizer moments would
        transiently destabilize the alpha update)."""
        return {
            "state": jax.tree.map(np.asarray, self.state),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
            "rng": np.asarray(jax.random.key_data(self._rng)),
        }

    def load_full_state(self, payload: dict):
        if "state" not in payload:  # pre-opt_state checkpoint layout
            self.state = jax.tree.map(jnp.asarray, payload)
            return
        self.state = jax.tree.map(jnp.asarray, payload["state"])
        self.opt_state = jax.tree.map(jnp.asarray, payload["opt_state"])
        self._rng = jax.random.wrap_key_data(
            jnp.asarray(payload["rng"]))


class SAC(Algorithm):
    _config_cls = SACConfig
    _worker_cls = ContinuousRolloutWorker

    def _make_learner_factory(self, cfg, obs_dim, action_dim):
        probe = self._probe_env  # the probe Algorithm.setup already built
        scale = (probe.action_high - probe.action_low) / 2.0
        shift = (probe.action_high + probe.action_low) / 2.0
        tgt_ent = (cfg.target_entropy if cfg.target_entropy is not None
                   else -float(action_dim))

        def make():
            return SACLearner(
                obs_dim, action_dim, actor_lr=cfg.lr,
                critic_lr=cfg.critic_lr, alpha_lr=cfg.alpha_lr,
                gamma=cfg.gamma, tau=cfg.tau, action_scale=scale,
                action_shift=shift, initial_alpha=cfg.initial_alpha,
                target_entropy=tgt_ent, hiddens=cfg.model_hiddens,
                seed=cfg.seed)

        return make

    def setup(self, config):
        cfg0 = config.get("__algo_config__")
        # num_learners can arrive on the config object OR as a plain key
        # (the Tune search-space path algorithm.py merges in setup)
        if (cfg0 is not None and getattr(cfg0, "num_learners", 0)) or \
                config.get("num_learners"):
            raise ValueError(
                "SAC uses a single local learner (its update is one fused "
                "XLA program); num_learners > 0 is not supported")
        super().setup(config)
        cfg = self.algo_config
        self.replay = ReplayBuffer(cfg.replay_buffer_capacity,
                                   seed=cfg.seed)

    def training_step(self) -> dict:
        cfg = self.algo_config
        warming_up = (self.replay.num_added <
                      cfg.num_steps_sampled_before_learning_starts)
        eps = cfg.warmup_random_action_prob if warming_up else 0.0
        batches = ray_tpu.get(
            [w.sample_transitions.remote(eps) for w in self.workers],
            timeout=300)
        fresh = concat_samples(batches)
        self.replay.add(fresh)
        self._num_env_steps += fresh.count

        metrics = {"env_steps_this_iter": fresh.count,
                   "replay_size": len(self.replay)}
        learner = self.learners.local  # SAC updates are local/single-chip
        if self.replay.num_added >= \
                cfg.num_steps_sampled_before_learning_starts:
            last = {}
            for _ in range(cfg.num_updates_per_iter):
                sample = self.replay.sample(cfg.train_batch_size)
                if sample is None:
                    break
                last = learner.update(sample)
            metrics.update(last)
            self._sync_weights()
        return metrics

    def save_checkpoint(self):
        return {"sac_state": self.learners.local.full_state(),
                "num_env_steps": self._num_env_steps}

    def load_checkpoint(self, checkpoint):
        if checkpoint and "sac_state" in checkpoint:
            self.learners.local.load_full_state(checkpoint["sac_state"])
            self._num_env_steps = checkpoint.get("num_env_steps", 0)
            self._sync_weights()
        else:
            super().load_checkpoint(checkpoint)
