"""Contextual bandits: LinUCB and Linear Thompson Sampling.

Ref analog: rllib/algorithms/bandit/ (BanditLinUCB, BanditLinTS over
bandit_envs_discrete) — per-arm Bayesian linear regression with either a
UCB exploration bonus (Li et al. 2010) or posterior sampling. Re-design:
the per-arm sufficient statistics (A = I + X'X, b = X'r) update and the
arm scores are closed-form numpy on the driver — a bandit "learner" is
a rank-1 update, not an SGD program, so no rollout-worker fleet or XLA
step is warranted. The Algorithm surface (config/step/checkpoint) stays
identical so Tune drives bandits like any other algorithm.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .env import make_env


class BanditConfig(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or BanditLinUCB)
        self.env = "ContextualBandit-v0"
        self.steps_per_iter = 256
        self.alpha = 1.0          # UCB exploration width / prior scale
        self.lambda_reg = 1.0     # ridge prior


class _LinearBanditState:
    def __init__(self, num_arms: int, dim: int, lam: float):
        self.A = np.stack([np.eye(dim, dtype=np.float64) * lam
                           for _ in range(num_arms)])
        self.b = np.zeros((num_arms, dim), np.float64)
        self.num_arms, self.dim = num_arms, dim

    def theta(self) -> np.ndarray:
        return np.stack([np.linalg.solve(self.A[k], self.b[k])
                         for k in range(self.num_arms)])

    def update(self, arm: int, x: np.ndarray, r: float):
        self.A[arm] += np.outer(x, x)
        self.b[arm] += r * x


class BanditLinUCB(Algorithm):
    """argmax_k  theta_k.x + alpha * sqrt(x' A_k^-1 x)."""

    _config_cls = BanditConfig

    def setup(self, config):
        cfg = config.get("__algo_config__")
        cfg = cfg.copy() if cfg is not None else self.get_default_config()
        cfg.update_from_dict(
            {k: v for k, v in config.items() if k != "__algo_config__"})
        self.algo_config = cfg
        self.env = make_env(cfg.env)
        self.state = _LinearBanditState(self.env.num_actions,
                                        self.env.observation_dim,
                                        cfg.lambda_reg)
        self._rng = np.random.default_rng(cfg.seed)
        self._obs = self.env.reset(seed=cfg.seed)
        self.cumulative_regret = 0.0
        self.cumulative_reward = 0.0
        self._num_env_steps = 0

    def _choose(self, x: np.ndarray) -> int:
        cfg = self.algo_config
        scores = np.empty(self.state.num_arms)
        for k in range(self.state.num_arms):
            A_inv_x = np.linalg.solve(self.state.A[k], x)
            mean = float(self.state.b[k] @ A_inv_x)
            width = float(np.sqrt(max(x @ A_inv_x, 0.0)))
            scores[k] = mean + cfg.alpha * width
        return int(np.argmax(scores))

    def training_step(self) -> dict:
        cfg = self.algo_config
        regret_this = 0.0
        reward_this = 0.0
        for _ in range(cfg.steps_per_iter):
            x = self._obs.astype(np.float64)
            arm = self._choose(x)
            self._obs, r, _done, info = self.env.step(arm)
            self.state.update(arm, x, r)
            reward_this += r
            regret_this += info.get("regret", 0.0)
        self._num_env_steps += cfg.steps_per_iter
        self.cumulative_regret += regret_this
        self.cumulative_reward += reward_this
        return {
            "reward_mean": reward_this / cfg.steps_per_iter,
            "regret_mean": regret_this / cfg.steps_per_iter,
            "cumulative_regret": self.cumulative_regret,
            "num_env_steps_sampled": self._num_env_steps,
        }

    def step(self) -> dict:
        return self.training_step()

    def save_checkpoint(self):
        return {"A": self.state.A, "b": self.state.b,
                "steps": self._num_env_steps,
                "cum_regret": self.cumulative_regret}

    def load_checkpoint(self, checkpoint):
        if checkpoint:
            self.state.A = checkpoint["A"]
            self.state.b = checkpoint["b"]
            self._num_env_steps = checkpoint["steps"]
            self.cumulative_regret = checkpoint["cum_regret"]

    def cleanup(self):
        pass

    def get_policy_weights(self) -> Dict[str, np.ndarray]:
        return {"theta": self.state.theta()}


class BanditLinTS(BanditLinUCB):
    """Thompson sampling: draw theta_k ~ N(A_k^-1 b_k, alpha^2 A_k^-1),
    play the argmax (ref: BanditLinTS)."""

    def _choose(self, x: np.ndarray) -> int:
        cfg = self.algo_config
        scores = np.empty(self.state.num_arms)
        for k in range(self.state.num_arms):
            A_inv = np.linalg.inv(self.state.A[k])
            mu = A_inv @ self.state.b[k]
            sample = self._rng.multivariate_normal(
                mu, cfg.alpha ** 2 * A_inv)
            scores[k] = sample @ x
        return int(np.argmax(scores))
