"""APPO: asynchronous PPO — IMPALA's pipelined sampling with the PPO
clipped-surrogate loss over V-trace-corrected advantages.

Ref analogs: rllib/algorithms/appo/appo.py (APPOConfig: use_kl_loss /
clip_param on top of ImpalaConfig) and appo_torch_policy.py's loss:
ratio = pi/behaviour, surrogate clipped at 1±clip, advantages and value
targets from V-trace (asynchronous off-policy data). Re-design: same
jitted-update shape as ImpalaLearner — the whole loss+Adam step is one
XLA program; the async rollout pipeline is inherited from IMPALA.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import sample_batch as SB
from .impala import IMPALA, IMPALAConfig
from .learner import vtrace
from .models import entropy_of, forward, init_actor_critic
from .sample_batch import SampleBatch


class APPOConfig(IMPALAConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or APPO)
        self.clip_param = 0.2
        self.lr = 5e-4


class APPOLearner:
    """V-trace advantages + PPO ratio clip in one jitted update."""

    def __init__(self, obs_dim: int, num_actions: int, *, lr: float = 5e-4,
                 gamma: float = 0.99, clip_param: float = 0.2,
                 vf_coeff: float = 0.5, entropy_coeff: float = 0.01,
                 grad_clip: float = 40.0, clip_rho: float = 1.0,
                 clip_c: float = 1.0, hiddens=(64, 64), seed: int = 0):
        self.params = init_actor_critic(jax.random.key(seed), obs_dim,
                                        num_actions, hiddens)
        self.tx = optax.chain(optax.clip_by_global_norm(grad_clip),
                              optax.adam(lr))
        self.opt_state = self.tx.init(self.params)

        def loss_fn(params, batch):
            T, N = batch[SB.ACTIONS].shape
            logits, values = forward(params,
                                     batch[SB.OBS].reshape(T * N, -1))
            logits = logits.reshape(T, N, -1)
            values = values.reshape(T, N)
            target_logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits),
                batch[SB.ACTIONS][..., None], axis=-1).squeeze(-1)
            _, bootstrap_value = forward(params, batch["bootstrap_obs"])
            vs, pg_adv = vtrace(
                batch[SB.ACTION_LOGP], target_logp, batch[SB.REWARDS],
                batch[SB.DONES], values, bootstrap_value, gamma,
                clip_rho, clip_c)
            adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)
            ratio = jnp.exp(target_logp - batch[SB.ACTION_LOGP])
            surr = jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
            pi_loss = -surr.mean()
            vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
            ent = entropy_of(logits.reshape(T * N, -1)).mean()
            total = pi_loss + vf_coeff * vf_loss - entropy_coeff * ent
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": ent,
                           "mean_ratio": jnp.mean(ratio)}

        @jax.jit
        def train_step(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics["total_loss"] = loss
            return params, opt_state, metrics

        self._train_step = train_step

    def update(self, batch: SampleBatch) -> dict:
        self.params, self.opt_state, metrics = self._train_step(
            self.params, self.opt_state,
            {k: jnp.asarray(v) for k, v in batch.items()})
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.params.items()}

    def set_weights(self, weights: Dict[str, np.ndarray]):
        self.params = {k: jnp.asarray(v) for k, v in weights.items()}


class APPO(IMPALA):
    """IMPALA's async pipeline, APPO's clipped loss."""

    _config_cls = APPOConfig

    def _make_learner_factory(self, cfg, obs_dim, num_actions):
        def make():
            return APPOLearner(
                obs_dim, num_actions, lr=cfg.lr, gamma=cfg.gamma,
                clip_param=cfg.clip_param, vf_coeff=cfg.vf_coeff,
                entropy_coeff=cfg.entropy_coeff, grad_clip=cfg.grad_clip,
                clip_rho=cfg.clip_rho, clip_c=cfg.clip_c,
                hiddens=cfg.model_hiddens, seed=cfg.seed)

        return make
