"""TD3 + DDPG: deterministic-policy off-policy continuous control.

Ref analogs: rllib/algorithms/ddpg/ddpg.py (DDPGConfig: actor/critic lr,
tau, target-noise knobs, the DQN-style sample->store->replay->learn
training_step) and rllib/algorithms/td3/td3.py (TD3 = DDPG config preset
with twin_q, policy_delay=2, smoothed target actions — Fujimoto et al.
2018). TPU-first re-design: the critic regression (twin-min smoothed
Bellman target) and the delayed actor ascent are each ONE jitted XLA
program over a contiguous replay batch; rollouts stay CPU actors.

The actor reuses the squashed-Gaussian parameter layout (mu head only is
trained) so worker-side weight sync lands in the same
``SquashedGaussianPolicy`` every continuous algorithm here uses — TD3's
exploration is mean action + numpy Gaussian noise, not the policy's own
(untrained) log_std head.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu

from . import sample_batch as SB
from .algorithm import Algorithm, AlgorithmConfig
from .models import (gaussian_forward, init_gaussian_actor, init_q_net,
                     q_forward)
from .replay_buffers import ReplayBuffer
from .rollout_worker import ContinuousRolloutWorker, _collect_transitions
from .sample_batch import SampleBatch, concat_samples


class TD3Config(AlgorithmConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or TD3)
        self.env = "Pendulum-v1"
        self.lr = 1e-3                   # actor
        self.critic_lr = 1e-3
        self.train_batch_size = 128
        self.replay_buffer_capacity = 100_000
        self.num_steps_sampled_before_learning_starts = 1000
        self.tau = 0.005                 # Polyak target blend
        self.twin_q = True
        self.policy_delay = 2
        self.target_noise = 0.2          # smoothing noise on target action
        self.target_noise_clip = 0.5
        self.explore_noise = 0.1         # rollout-side N(0, s*scale)
        self.num_updates_per_iter = 64
        self.warmup_random_action_prob = 1.0


class DDPGConfig(TD3Config):
    """DDPG = TD3 minus its three fixes (ref: td3.py presets inverted)."""

    def __init__(self, algo_class=None):
        super().__init__(algo_class or DDPG)
        self.twin_q = False
        self.policy_delay = 1
        self.target_noise = 0.0


class TD3Learner:
    """Deterministic actor + (twin) critics + Polyak targets.

    Two jitted programs: ``critic_step`` every update, ``actor_step``
    every ``policy_delay`` updates (static Python cadence, so each stays
    a single compiled program with no traced branching)."""

    def __init__(self, obs_dim: int, action_dim: int, *, actor_lr: float,
                 critic_lr: float, gamma: float, tau: float,
                 action_scale, action_shift, twin_q: bool,
                 target_noise: float, target_noise_clip: float,
                 hiddens=(64, 64), seed: int = 0):
        k = jax.random.split(jax.random.key(seed), 3)
        self.twin_q = bool(twin_q)
        self.state = {
            "actor": init_gaussian_actor(k[0], obs_dim, action_dim,
                                         hiddens),
            "q1": init_q_net(k[1], obs_dim, action_dim, hiddens),
            "q2": init_q_net(k[2], obs_dim, action_dim, hiddens),
        }
        self.state["t_actor"] = jax.tree.map(jnp.copy, self.state["actor"])
        self.state["tq1"] = jax.tree.map(jnp.copy, self.state["q1"])
        self.state["tq2"] = jax.tree.map(jnp.copy, self.state["q2"])
        self._actor_opt = optax.adam(actor_lr)
        self._critic_opt = optax.adam(critic_lr)
        self.opt_state = {
            "actor": self._actor_opt.init(self.state["actor"]),
            "critic": self._critic_opt.init(
                (self.state["q1"], self.state["q2"])),
        }
        self._rng = jax.random.key(seed + 1)
        scale = jnp.asarray(action_scale, jnp.float32)
        shift = jnp.asarray(action_shift, jnp.float32)
        lo, hi = shift - scale, shift + scale

        def act(actor, obs):
            mu, _ = gaussian_forward(actor, obs)
            return shift + scale * jnp.tanh(mu)

        def critic_loss(qs, state, batch, rng):
            a_next = act(state["t_actor"], batch[SB.NEXT_OBS])
            if target_noise > 0.0:
                eps = jnp.clip(
                    target_noise * scale
                    * jax.random.normal(rng, a_next.shape),
                    -target_noise_clip * scale, target_noise_clip * scale)
                a_next = jnp.clip(a_next + eps, lo, hi)
            tq = q_forward(state["tq1"], batch[SB.NEXT_OBS], a_next)
            if self.twin_q:
                tq = jnp.minimum(
                    tq, q_forward(state["tq2"], batch[SB.NEXT_OBS],
                                  a_next))
            not_done = 1.0 - batch[SB.DONES].astype(jnp.float32)
            target = jax.lax.stop_gradient(
                batch[SB.REWARDS] + gamma * not_done * tq)
            q1p, q2p = qs
            e1 = q_forward(q1p, batch[SB.OBS], batch[SB.ACTIONS]) - target
            loss = jnp.mean(e1 ** 2)
            if self.twin_q:
                e2 = q_forward(q2p, batch[SB.OBS],
                               batch[SB.ACTIONS]) - target
                loss = loss + jnp.mean(e2 ** 2)
            return loss

        @jax.jit
        def critic_step(state, opt_state, batch, rng):
            loss, grads = jax.value_and_grad(critic_loss)(
                (state["q1"], state["q2"]), state, batch, rng)
            upd, copt = self._critic_opt.update(
                grads, opt_state["critic"], (state["q1"], state["q2"]))
            q1, q2 = optax.apply_updates((state["q1"], state["q2"]), upd)
            state = dict(state, q1=q1, q2=q2)
            return state, dict(opt_state, critic=copt), loss

        def actor_loss(actor, state, batch):
            a = act(actor, batch[SB.OBS])
            return -jnp.mean(q_forward(state["q1"], batch[SB.OBS], a))

        @jax.jit
        def actor_step(state, opt_state, batch):
            loss, grads = jax.value_and_grad(actor_loss)(
                state["actor"], state, batch)
            upd, aopt = self._actor_opt.update(
                grads, opt_state["actor"], state["actor"])
            actor = optax.apply_updates(state["actor"], upd)
            blend = lambda t, s: jax.tree.map(  # noqa: E731
                lambda a, b: (1 - tau) * a + tau * b, t, s)
            state = dict(state, actor=actor,
                         t_actor=blend(state["t_actor"], actor),
                         tq1=blend(state["tq1"], state["q1"]),
                         tq2=blend(state["tq2"], state["q2"]))
            return state, dict(opt_state, actor=aopt), loss

        self._critic_step = critic_step
        self._actor_step = actor_step

    def update(self, batch: SampleBatch, *, do_actor: bool) -> dict:
        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k in (SB.OBS, SB.ACTIONS, SB.REWARDS, SB.DONES,
                       SB.NEXT_OBS)}
        self._rng, sub = jax.random.split(self._rng)
        self.state, self.opt_state, closs = self._critic_step(
            self.state, self.opt_state, jb, sub)
        out = {"critic_loss": float(closs)}
        if do_actor:
            self.state, self.opt_state, aloss = self._actor_step(
                self.state, self.opt_state, jb)
            out["actor_loss"] = float(aloss)
        return out

    def get_weights(self) -> Dict[str, np.ndarray]:
        # worker policies are SquashedGaussianPolicy — same param layout
        return {k: np.asarray(v) for k, v in self.state["actor"].items()}

    def set_weights(self, weights):
        self.state["actor"] = {k: jnp.asarray(v)
                               for k, v in weights.items()}

    def full_state(self) -> dict:
        return {"state": jax.tree.map(np.asarray, self.state),
                "opt_state": jax.tree.map(np.asarray, self.opt_state),
                "rng": np.asarray(jax.random.key_data(self._rng))}

    def load_full_state(self, payload: dict):
        self.state = jax.tree.map(jnp.asarray, payload["state"])
        self.opt_state = jax.tree.map(jnp.asarray, payload["opt_state"])
        self._rng = jax.random.wrap_key_data(jnp.asarray(payload["rng"]))


class TD3RolloutWorker(ContinuousRolloutWorker):
    """Deterministic action + N(0, noise*scale), clipped to bounds
    (ref: DDPG's GaussianNoise exploration, rllib/utils/exploration/
    gaussian_noise.py)."""

    def sample_transitions(self, epsilon: float = 0.0,
                           noise: float = 0.1) -> SampleBatch:
        N, A = self.vec.num_envs, self.vec.action_dim
        env0 = self.vec.envs[0]
        lo, hi = env0.action_low, env0.action_high
        sigma = noise * (hi - lo) / 2.0

        def select(obs):
            if epsilon >= 1.0:  # pure warmup
                return self._rng.uniform(
                    lo, hi, size=(N, A)).astype(np.float32)
            actions, _ = self.policy.compute_actions(obs, explore=False)
            actions = actions + sigma * self._rng.standard_normal(
                (N, A)).astype(np.float32)
            return np.clip(actions, lo, hi).astype(np.float32)

        return _collect_transitions(self.vec, self.rollout_len, select,
                                    (A,), np.float32, self.conn)


class TD3(Algorithm):
    _config_cls = TD3Config
    _worker_cls = TD3RolloutWorker

    def _make_learner_factory(self, cfg, obs_dim, action_dim):
        probe = self._probe_env
        scale = (probe.action_high - probe.action_low) / 2.0
        shift = (probe.action_high + probe.action_low) / 2.0

        def make():
            return TD3Learner(
                obs_dim, action_dim, actor_lr=cfg.lr,
                critic_lr=cfg.critic_lr, gamma=cfg.gamma, tau=cfg.tau,
                action_scale=scale, action_shift=shift,
                twin_q=cfg.twin_q, target_noise=cfg.target_noise,
                target_noise_clip=cfg.target_noise_clip,
                hiddens=cfg.model_hiddens, seed=cfg.seed)

        return make

    def setup(self, config):
        super().setup(config)
        cfg = self.algo_config
        self.replay = ReplayBuffer(cfg.replay_buffer_capacity,
                                   seed=cfg.seed)
        self._updates = 0

    def training_step(self) -> dict:
        cfg = self.algo_config
        warming_up = (self.replay.num_added <
                      cfg.num_steps_sampled_before_learning_starts)
        eps = cfg.warmup_random_action_prob if warming_up else 0.0
        batches = ray_tpu.get(
            [w.sample_transitions.remote(eps, cfg.explore_noise)
             for w in self.workers], timeout=300)
        fresh = concat_samples(batches)
        self.replay.add(fresh)
        self._num_env_steps += fresh.count

        metrics = {"env_steps_this_iter": fresh.count,
                   "replay_size": len(self.replay)}
        learner = self.learners.local
        if self.replay.num_added >= \
                cfg.num_steps_sampled_before_learning_starts:
            last = {}
            for _ in range(cfg.num_updates_per_iter):
                sample = self.replay.sample(cfg.train_batch_size)
                if sample is None:
                    break
                self._updates += 1
                last = learner.update(
                    sample,
                    do_actor=self._updates % cfg.policy_delay == 0)
            metrics.update(last)
            self._sync_weights()
        return metrics

    def save_checkpoint(self):
        return {"td3_state": self.learners.local.full_state(),
                "num_env_steps": self._num_env_steps,
                "updates": self._updates}

    def load_checkpoint(self, checkpoint):
        if checkpoint and "td3_state" in checkpoint:
            self.learners.local.load_full_state(checkpoint["td3_state"])
            self._num_env_steps = checkpoint.get("num_env_steps", 0)
            self._updates = checkpoint.get("updates", 0)
            self._sync_weights()
        else:
            super().load_checkpoint(checkpoint)


class DDPG(TD3):
    _config_cls = DDPGConfig
