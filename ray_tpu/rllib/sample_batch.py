"""SampleBatch: the dict-of-arrays currency between rollouts and learners.

Ref analog: rllib/policy/sample_batch.py:98 (SampleBatch) — re-designed as a
thin numpy container with exactly the operations the JAX learner needs:
concat, shuffle, minibatch iteration. Column names match the reference's.
"""

from __future__ import annotations

from typing import Dict, Iterator, List

import numpy as np

OBS = "obs"
NEXT_OBS = "new_obs"
ACTIONS = "actions"
REWARDS = "rewards"
DONES = "dones"
ACTION_LOGP = "action_logp"
VF_PREDS = "vf_preds"
ADVANTAGES = "advantages"
VALUE_TARGETS = "value_targets"
BEHAVIOUR_LOGITS = "behaviour_logits"


class SampleBatch(dict):
    @property
    def count(self) -> int:
        for v in self.values():
            return len(v)
        return 0

    def shuffle(self, rng: np.random.Generator) -> "SampleBatch":
        perm = rng.permutation(self.count)
        return SampleBatch({k: v[perm] for k, v in self.items()})

    def minibatches(self, size: int) -> Iterator["SampleBatch"]:
        n = self.count
        for start in range(0, n - size + 1, size):
            yield SampleBatch({k: v[start:start + size]
                               for k, v in self.items()})

    def slice(self, start: int, end: int) -> "SampleBatch":
        return SampleBatch({k: v[start:end] for k, v in self.items()})


def concat_samples(batches: List[SampleBatch]) -> SampleBatch:
    keys = batches[0].keys()
    return SampleBatch({k: np.concatenate([b[k] for b in batches])
                        for k in keys})


def compute_gae(rewards: np.ndarray, values: np.ndarray, dones: np.ndarray,
                last_value: np.ndarray, gamma: float, lam: float):
    """Generalized Advantage Estimation over [T, N] rollout arrays.

    Ref analog: rllib/evaluation/postprocessing.py compute_advantages —
    computed on the rollout worker so the learner sees ready advantages.
    Returns (advantages [T,N], value_targets [T,N]).
    """
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    last_gae = np.zeros_like(last_value)
    next_value = last_value
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t].astype(np.float32)
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    return adv, adv + values
