"""Model catalog: observation/action spec -> encoder + heads.

Ref analog: rllib/models/catalog.py (ModelCatalog.get_model_v2 maps
space + model_config to a network class, with a custom-model registry)
— re-designed functionally: a catalog entry is a pure
``(init_fn, forward_fn)`` pair over a params pytree, so every learner's
jitted update stays a single XLA program regardless of which encoder the
catalog picked. Built-ins: "mlp" (the default the gradient algorithms
use), "conv" (MinAtar-class plane observations -> MXU-friendly NHWC
convs), "gru" (recurrent encoder for R2D2-style sequence learners).

    init_fn(rng) -> params
    forward_fn(params, obs[, state]) -> (logits, value[, state])

Custom models register by name, mirroring
``ModelCatalog.register_custom_model``::

    register_custom_model("my_net", my_init, my_forward)
    init, fwd = get_model(spec, {"type": "my_net"})
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .models import _ortho, forward as _mlp_forward, init_actor_critic

Params = Dict[str, jnp.ndarray]

_CUSTOM: Dict[str, Tuple[Callable, Callable]] = {}


def register_custom_model(name: str, init_fn: Callable,
                          forward_fn: Callable) -> None:
    """Register ``(init_fn(rng, spec, config), forward_fn)`` under
    ``name`` (ref: ModelCatalog.register_custom_model)."""
    _CUSTOM[name] = (init_fn, forward_fn)


class ModelSpec:
    """What the catalog needs to size a model: flat observation dim (or
    plane shape for conv) and the discrete action count."""

    def __init__(self, obs_dim: int, num_actions: int,
                 obs_planes: Optional[Tuple[int, int, int]] = None):
        self.obs_dim = int(obs_dim)
        self.num_actions = int(num_actions)
        # (C, H, W) when observations are flattened feature planes
        # (BreakoutMini: (4, 10, 10) flattened to 400)
        self.obs_planes = obs_planes


def get_model(spec: ModelSpec, model_config: Optional[dict] = None
              ) -> Tuple[Callable[[jax.Array], Params], Callable]:
    """-> (init_fn, forward_fn) for the configured model type.

    forward_fn(params, obs [B, D]) -> (logits [B, A], value [B]) for
    feedforward types; the "gru" type returns/consumes a carry state
    (see gru_forward).
    """
    cfg = dict(model_config or {})
    kind = cfg.get("type", "mlp")
    if kind in _CUSTOM:
        init, fwd = _CUSTOM[kind]
        return (lambda rng: init(rng, spec, cfg)), fwd
    if kind == "mlp":
        hiddens = tuple(cfg.get("hiddens", (64, 64)))
        return (lambda rng: init_actor_critic(
            rng, spec.obs_dim, spec.num_actions, hiddens)), _mlp_forward
    if kind == "conv":
        if spec.obs_planes is None:
            raise ValueError("conv model needs spec.obs_planes=(C, H, W)")
        return _conv_entry(spec, cfg)
    if kind == "gru":
        hidden = int(cfg.get("hidden", 64))
        embed = tuple(cfg.get("hiddens", (64,)))
        return (lambda rng: init_gru(rng, spec.obs_dim, spec.num_actions,
                                     hidden, embed)), gru_forward
    raise ValueError(f"unknown model type {kind!r}; "
                     f"registered: {sorted(_CUSTOM)}")


# ------------------------------------------------------------------- conv


def _conv_entry(spec: ModelSpec, cfg: dict):
    filters = tuple(cfg.get("conv_filters", (16, 32)))
    hiddens = tuple(cfg.get("hiddens", (128,)))
    C, H, W = spec.obs_planes

    def init(rng) -> Params:
        params: Params = {}
        keys = jax.random.split(rng, len(filters) + len(hiddens) + 2)
        cin = C
        for i, cout in enumerate(filters):
            # 3x3 convs; He-style scale on the fan-in
            fan_in = cin * 9
            params[f"cw{i}"] = jax.random.normal(
                keys[i], (3, 3, cin, cout)) * jnp.sqrt(2.0 / fan_in)
            params[f"cb{i}"] = jnp.zeros((cout,))
            cin = cout
        flat = cin * H * W  # SAME padding keeps the plane size
        sizes = [flat, *hiddens]
        for i in range(len(hiddens)):
            params[f"w{i}"] = _ortho(keys[len(filters) + i],
                                     (sizes[i], sizes[i + 1]),
                                     gain=jnp.sqrt(2.0))
            params[f"b{i}"] = jnp.zeros((sizes[i + 1],))
        params["w_pi"] = _ortho(keys[-2], (sizes[-1], spec.num_actions),
                                gain=0.01)
        params["b_pi"] = jnp.zeros((spec.num_actions,))
        params["w_v"] = _ortho(keys[-1], (sizes[-1], 1), gain=1.0)
        params["b_v"] = jnp.zeros((1,))
        return params

    def fwd(params: Params, obs: jnp.ndarray):
        B = obs.shape[0]
        # flat [B, C*H*W] -> NHWC (TPU conv layout)
        x = obs.reshape(B, C, H, W).transpose(0, 2, 3, 1)
        n_conv = sum(1 for k in params if k.startswith("cw"))
        for i in range(n_conv):
            x = jax.lax.conv_general_dilated(
                x, params[f"cw{i}"], window_strides=(1, 1),
                padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = jax.nn.relu(x + params[f"cb{i}"])
        x = x.reshape(B, -1)
        n = sum(1 for k in params
                if k.startswith("w") and k[1:].isdigit())
        for i in range(n):
            x = jnp.tanh(x @ params[f"w{i}"] + params[f"b{i}"])
        logits = x @ params["w_pi"] + params["b_pi"]
        value = (x @ params["w_v"] + params["b_v"]).squeeze(-1)
        return logits, value

    return init, fwd


# -------------------------------------------------------------------- gru


def init_gru(rng, obs_dim: int, num_actions: int, hidden: int = 64,
             embed: Sequence[int] = (64,)) -> Params:
    """Embedding MLP -> GRU cell -> (pi, v) heads. The recurrent model
    for R2D2-class sequence learners (ref: rllib's use_lstm wrapper,
    models/torch/recurrent_net.py)."""
    params: Params = {}
    keys = jax.random.split(rng, len(embed) + 5)
    sizes = [obs_dim, *embed]
    for i in range(len(embed)):
        params[f"w{i}"] = _ortho(keys[i], (sizes[i], sizes[i + 1]),
                                 gain=jnp.sqrt(2.0))
        params[f"b{i}"] = jnp.zeros((sizes[i + 1],))
    E = sizes[-1]
    # fused GRU weights: [E, 3H] input and [H, 3H] recurrent (r, z, n)
    params["gru_wi"] = _ortho(keys[-4], (E, 3 * hidden), gain=1.0)
    params["gru_wh"] = _ortho(keys[-3], (hidden, 3 * hidden), gain=1.0)
    params["gru_b"] = jnp.zeros((3 * hidden,))
    params["w_pi"] = _ortho(keys[-2], (hidden, num_actions), gain=0.01)
    params["b_pi"] = jnp.zeros((num_actions,))
    params["w_v"] = _ortho(keys[-1], (hidden, 1), gain=1.0)
    params["b_v"] = jnp.zeros((1,))
    return params


def gru_cell(params: Params, x: jnp.ndarray, h: jnp.ndarray
             ) -> jnp.ndarray:
    """One GRU step: x [B, E], h [B, H] -> new h [B, H]."""
    H = h.shape[-1]
    gi = x @ params["gru_wi"] + params["gru_b"]
    gh = h @ params["gru_wh"]
    r = jax.nn.sigmoid(gi[:, :H] + gh[:, :H])
    z = jax.nn.sigmoid(gi[:, H:2 * H] + gh[:, H:2 * H])
    n = jnp.tanh(gi[:, 2 * H:] + r * gh[:, 2 * H:])
    return (1.0 - z) * n + z * h


def _embed(params: Params, obs: jnp.ndarray) -> jnp.ndarray:
    n = sum(1 for k in params if k.startswith("w") and k[1:].isdigit())
    x = obs
    for i in range(n):
        x = jnp.tanh(x @ params[f"w{i}"] + params[f"b{i}"])
    return x


def gru_forward(params: Params, obs: jnp.ndarray,
                state: Optional[jnp.ndarray] = None):
    """obs [B, D] (one step) -> (logits [B, A], value [B], new state).
    ``state`` [B, H] defaults to zeros (episode start)."""
    H = params["gru_wh"].shape[0]
    if state is None:
        state = jnp.zeros((obs.shape[0], H), obs.dtype)
    h = gru_cell(params, _embed(params, obs), state)
    logits = h @ params["w_pi"] + params["b_pi"]
    value = (h @ params["w_v"] + params["b_v"]).squeeze(-1)
    return logits, value, h


def gru_unroll(params: Params, obs_seq: jnp.ndarray,
               h0: jnp.ndarray, reset: Optional[jnp.ndarray] = None):
    """Unroll over time with lax.scan: obs_seq [T, B, D], h0 [B, H],
    reset [T, B] bool (True clears the carry BEFORE consuming step t —
    episode boundaries inside a training sequence); -> (logits
    [T, B, A], values [T, B], h_final [B, H])."""

    def step(h, inp):
        if reset is None:
            x = inp
        else:
            x, r = inp
            h = jnp.where(r[:, None], jnp.zeros_like(h), h)
        h = gru_cell(params, _embed(params, x), h)
        logits = h @ params["w_pi"] + params["b_pi"]
        value = (h @ params["w_v"] + params["b_v"]).squeeze(-1)
        return h, (logits, value)

    xs = obs_seq if reset is None else (obs_seq, reset)
    h_final, (logits, values) = jax.lax.scan(step, h0, xs)
    return logits, values, h_final
