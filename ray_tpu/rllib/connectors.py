"""Observation/action connector pipeline.

Ref analogs: rllib/connectors/agent/pipeline.py (AgentConnectorPipeline
— composable transforms between env and policy) and
connectors/action/pipeline.py. Re-design, lite: connectors are plain
objects with vectorized numpy transforms ([N, ...] batches from the
VectorEnv), a pipeline composes them, and RolloutWorker applies the
pipeline on both legs (obs: env -> policy; action: policy -> env) so
env/model coupling stops being hand-rolled per algorithm. State that
must ship with weights (e.g. running normalization moments) round-trips
through get_state/set_state.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class Connector:
    """One transform. Obs connectors see [N, ...] observation batches;
    action connectors see [N, ...] action batches."""

    def transform_obs(self, obs: np.ndarray) -> np.ndarray:
        return obs

    def transform_action(self, actions: np.ndarray) -> np.ndarray:
        return actions

    def observation_dim(self, dim: int) -> int:
        """Output obs dim given input dim (policy sizing)."""
        return dim

    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict):
        pass


class FlattenObs(Connector):
    """[N, ...] -> [N, prod(...)] (image/grid envs -> MLP policies)."""

    def __init__(self, input_shape: Sequence[int]):
        self.input_shape = tuple(input_shape)

    def transform_obs(self, obs: np.ndarray) -> np.ndarray:
        return obs.reshape(obs.shape[0], -1)

    def observation_dim(self, dim: int) -> int:
        return int(np.prod(self.input_shape))


class ClipObs(Connector):
    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = float(low), float(high)

    def transform_obs(self, obs: np.ndarray) -> np.ndarray:
        return np.clip(obs, self.low, self.high)


class NormalizeObs(Connector):
    """Running mean/std normalization (Welford over batches).

    ``frozen`` stops stat updates (evaluation). Stats are WORKER-LOCAL
    (each rollout worker normalizes from its own stream, the common
    mean-std-filter deployment); get_state/set_state exist so callers
    that need cross-worker or checkpoint consistency can move the
    moments explicitly. Ref analog: connectors/agent/mean_std_filter.py.
    """

    def __init__(self, eps: float = 1e-8):
        self.count = 0.0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None
        self.eps = eps
        self.frozen = False

    def transform_obs(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, np.float64)
        if not self.frozen:
            if self.mean is None:
                self.mean = np.zeros(obs.shape[1:], np.float64)
                self.m2 = np.zeros(obs.shape[1:], np.float64)
            n = obs.shape[0]
            batch_mean = obs.mean(axis=0)
            batch_m2 = ((obs - batch_mean) ** 2).sum(axis=0)
            delta = batch_mean - self.mean
            tot = self.count + n
            self.mean = self.mean + delta * n / tot
            self.m2 = self.m2 + batch_m2 + delta ** 2 * self.count * n / tot
            self.count = tot
        if self.mean is None or self.count < 2:
            return obs.astype(np.float32)
        std = np.sqrt(self.m2 / max(self.count - 1, 1.0)) + self.eps
        return ((obs - self.mean) / std).astype(np.float32)

    def get_state(self) -> dict:
        return {"count": self.count,
                "mean": None if self.mean is None else self.mean.copy(),
                "m2": None if self.m2 is None else self.m2.copy()}

    def set_state(self, state: dict):
        self.count = state["count"]
        self.mean = state["mean"]
        self.m2 = state["m2"]


class ClipAction(Connector):
    """Clamp continuous actions into the env's bounds (ref:
    connectors/action/clip.py)."""

    def __init__(self, low: float, high: float):
        self.low, self.high = float(low), float(high)

    def transform_action(self, actions: np.ndarray) -> np.ndarray:
        return np.clip(actions, self.low, self.high)


class UnsquashAction(Connector):
    """tanh-squashed policy output in [-1, 1] -> env bounds [low, high]."""

    def __init__(self, low: float, high: float):
        self.low, self.high = float(low), float(high)

    def transform_action(self, actions: np.ndarray) -> np.ndarray:
        return self.low + (np.asarray(actions) + 1.0) * 0.5 * \
            (self.high - self.low)


class ConnectorPipeline(Connector):
    """Ordered composition; obs transforms apply left-to-right, action
    transforms right-to-left (innermost closest to the policy), the
    pipeline.py convention."""

    def __init__(self, connectors: Sequence[Connector] = ()):
        self.connectors: List[Connector] = list(connectors)

    def append(self, c: Connector) -> "ConnectorPipeline":
        self.connectors.append(c)
        return self

    def transform_obs(self, obs: np.ndarray) -> np.ndarray:
        for c in self.connectors:
            obs = c.transform_obs(obs)
        return obs

    def transform_action(self, actions: np.ndarray) -> np.ndarray:
        for c in reversed(self.connectors):
            actions = c.transform_action(actions)
        return actions

    def observation_dim(self, dim: int) -> int:
        for c in self.connectors:
            dim = c.observation_dim(dim)
        return dim

    def get_state(self) -> List[dict]:
        return [c.get_state() for c in self.connectors]

    def set_state(self, states: List[dict]):
        for c, s in zip(self.connectors, states):
            c.set_state(s)

    def set_frozen(self, flag: bool):
        """Stop/resume stat updates on every stateful member (eval, or
        transforming auxiliary arrays like s' that must not be counted
        twice)."""
        for c in self.connectors:
            if hasattr(c, "frozen"):
                c.frozen = flag
