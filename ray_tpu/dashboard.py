"""Dashboard: HTTP JSON state API + a minimal live HTML overview.

Ref parity: the reference dashboard head (python/ray/dashboard/head.py:81)
serving the REST endpoints its UI and `ray list ...` tooling consume.
Re-design: one stdlib ThreadingHTTPServer in the driver/head process,
reading the same head tables the state API uses — no aiohttp, no separate
agent processes. Endpoints:

    /api/nodes /api/workers /api/actors /api/tasks /api/objects
    /api/placement_groups /api/io_loop
    /api/cluster_events     -> state API rows (JSON)
    /api/cluster            -> resource totals/availability
    /api/jobs               -> submitted jobs (jobs.py)
    /api/metrics            -> merged metric rows (JSON)
    /api/summary/{tasks,actors,objects} -> state summaries
    /api/timeline           -> chrome-trace events (tracing.timeline)
    /api/timeseries         -> flight-recorder series (state.metrics_history)
    /api/serve/applications -> serve deployment status rows
    /metrics                -> Prometheus text exposition
    /                       -> the SPA (dashboard_ui.py; hash-routed
                               nodes/actors/tasks/jobs/metrics/serve/
                               timeline pages, the reference's React
                               client re-done as one vanilla-JS file)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import ray_tpu
from ray_tpu.dashboard_ui import INDEX_HTML as _INDEX_HTML


class _Handler(BaseHTTPRequestHandler):
    server_version = "ray_tpu-dashboard"

    def log_message(self, *args):  # quiet
        pass

    def _send(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj, code: int = 200):
        self._send(code, json.dumps(obj, default=str).encode(),
                   "application/json")

    def do_GET(self):  # noqa: N802 - stdlib API
        from urllib.parse import parse_qs, urlsplit

        from ray_tpu import metrics, state

        split = urlsplit(self.path)
        query = {k: v[-1] for k, v in parse_qs(split.query).items()}
        path = split.path.rstrip("/") or "/"
        try:
            if path == "/":
                self._send(200, _INDEX_HTML.encode(), "text/html")
            elif path == "/api/cluster":
                self._json({
                    "nodes": len(ray_tpu.nodes()),
                    "resources_total": ray_tpu.cluster_resources(),
                    "resources_available": ray_tpu.available_resources(),
                })
            elif path == "/api/jobs":
                from ray_tpu.jobs import JOB_MANAGER_NAME

                try:
                    mgr = ray_tpu.get_actor(JOB_MANAGER_NAME)
                except ValueError:  # manager never created: no jobs yet
                    self._json([])
                else:
                    # a dead/stuck manager surfaces as 500, not as an
                    # empty-but-healthy list
                    self._json(ray_tpu.get(mgr.list.remote(), timeout=10))
            elif path == "/api/metrics":
                self._json(metrics.metrics_summary())
            elif path == "/api/timeline":
                from ray_tpu import tracing

                self._json(tracing.timeline())
            elif path == "/api/timeseries":
                # flight-recorder readback (r19):
                # ?names=head.loop_lag_ms,collective.*&window_s=60
                names = [n for n in
                         query.get("names", "").split(",") if n] or None
                win = query.get("window_s")
                self._json(state.metrics_history(
                    names, float(win) if win else None))
            elif path == "/api/profile":
                # on-demand flamegraph: ?worker_id=...&duration_s=1&hz=100
                # (omit worker_id to profile the driver/head process);
                # ref analog: dashboard/modules/reporter/profile_manager
                from ray_tpu import profiling

                dur = float(query.get("duration_s", 1.0))
                hz = float(query.get("hz", 100.0))
                wid = query.get("worker_id")
                if wid:
                    self._json(profiling.profile_worker(
                        wid, duration_s=dur, hz=hz))
                else:
                    self._json(profiling.profile_self(
                        duration_s=dur, hz=hz))
            elif path.startswith("/api/summary/"):
                kind = path[len("/api/summary/"):]
                fn = {"tasks": state.summarize_tasks,
                      "actors": state.summarize_actors,
                      "objects": state.summarize_objects,
                      # per-pipeline-stage bubble/transfer/exec view
                      # (r15) — same head data as summary/tasks, keyed
                      # stage{k}.fwd/bwd and split per stage; DP runs
                      # (r18, stage{k}r{rep}.*) add a "replicas"
                      # sub-dict per stage so stragglers attribute per
                      # (stage, replica)
                      "pipeline": state.pipeline_stage_summary,
                      # pipelined-exchange counters (r17): cluster
                      # data.shuffle_* metric rows + the driver-local
                      # live SHUFFLE_STATS view
                      "shuffle": state.data_shuffle_summary,
                      # memory observatory (r20): per-node/-job/-owner
                      # resident bytes, arena heartbeats, class
                      # breakdown, top objects — `ray_tpu memory`'s
                      # data, served over HTTP
                      "memory": state.memory_summary}.get(kind)
                if fn is None:
                    self._json({"error": f"unknown summary {kind}"}, 404)
                else:
                    self._json(fn())
            elif path == "/api/serve/applications":
                from ray_tpu import serve

                rows = []
                for app, info in serve.status()["applications"].items():
                    for dn, dep in info.get("deployments", {}).items():
                        running = dep.get("replica_states", {}) \
                            .get("RUNNING", 0)
                        auto = dep.get("autoscaler") or {}
                        cold = auto.get("cold_start") or {}
                        last = auto.get("last_decision") or {}
                        rows.append({
                            "app": app, "deployment": dn,
                            "target_replicas": dep.get("target_replicas"),
                            "running_replicas": running,
                            "version": dep.get("version"),
                            "status": dep.get("status"),
                            # autoscaler introspection (r14): scale
                            # events must be debuggable from the row
                            "autoscaling": auto.get("enabled", False),
                            "desired_replicas": auto.get("desired"),
                            "queue_depth": auto.get("queue_depth", 0),
                            "last_decision":
                                (f"{last.get('direction')} "
                                 f"{last.get('from')}->{last.get('to')}: "
                                 f"{last.get('reason')}")
                                if last else "",
                            "reversals_60s": auto.get("reversals_60s", 0),
                            "cold_start_p50_s": cold.get("p50_s", 0.0),
                            "cold_start_p95_s": cold.get("p95_s", 0.0)})
                self._json(rows)
            elif path == "/metrics":
                self._send(200, metrics.export_prometheus().encode(),
                           "text/plain; version=0.0.4")
            elif path.startswith("/api/"):
                kind = path[len("/api/"):]
                fn = {
                    "nodes": state.list_nodes,
                    "workers": state.list_workers,
                    "actors": state.list_actors,
                    "tasks": state.list_tasks,
                    "objects": state.list_objects,
                    "placement_groups": state.list_placement_groups,
                    # severity-tagged structured cluster event log
                    "cluster_events": state.list_cluster_events,
                    # head event-loop lag (instrumented_io_context analog)
                    "io_loop": lambda limit=10: state.io_loop_stats(),
                    # object directory + locality/pull counters
                    "object_plane":
                        lambda limit=1: state.object_plane_stats(),
                }.get(kind)
                if fn is None:
                    self._json({"error": f"unknown endpoint {path}"}, 404)
                else:
                    self._json(fn(limit=1000))
            else:
                self._json({"error": "not found"}, 404)
        except Exception as e:  # noqa: BLE001 — surface as 500
            self._json({"error": repr(e)}, 500)


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "Dashboard":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="dashboard")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> Dashboard:
    """Start the dashboard against the current runtime; returns the
    handle (``.url``, ``.stop()``). Port 0 picks a free port."""
    if not ray_tpu.is_initialized():
        raise RuntimeError("call ray_tpu.init() before start_dashboard()")
    return Dashboard(host, port).start()


# Every GET the doctor smoke exercises — keep in sync with _Handler.
DOCTOR_ENDPOINTS = (
    "/",
    "/api/cluster", "/api/nodes", "/api/workers", "/api/actors",
    "/api/tasks", "/api/objects", "/api/placement_groups",
    "/api/io_loop", "/api/object_plane", "/api/cluster_events",
    "/api/metrics", "/api/jobs", "/api/timeline", "/api/timeseries",
    "/api/summary/tasks", "/api/summary/actors", "/api/summary/objects",
    "/api/summary/pipeline", "/api/summary/shuffle",
    "/api/summary/memory",
    "/api/serve/applications",
    "/metrics",
)


# Head IO-loop lag p99 above this is a wedged-control-plane signal
# (every lease grant / locate / state query on that host waits at least
# this long for the loop): warn, pointing at the usual culprits.
LOOP_LAG_WARN_MS = 250.0

# Head-channel reattachments above this mean clients are reconnecting
# over and over (a reconnect STORM): the head is flapping — crashing
# repeatedly, or its socket is being cut by something between — rather
# than having restarted once.
RECONNECT_STORM_THRESHOLD = 20

# A worker reported live by a re-registering agent that has not
# re-REGISTERed itself within this long is stuck (wedged interpreter, or
# its node's re-registration is looping): the node is not fully back.
REATTACH_STUCK_S = 15.0

# Speculative arg prefetch (r13): wasted = pulls aborted because their
# task was cancelled / retried elsewhere before any worker asked. Above
# this fraction of issued — over the window since the previous
# doctor_warnings() call, with a minimum sample — speculation is doing
# more harm than good: caps are misconfigured for the workload, or
# retry/cancel churn is re-placing tasks away from their prefetches.
PREFETCH_WASTE_RATIO = 0.5
PREFETCH_WASTE_MIN_ISSUED = 20
# previous poll's cumulative counters, so repeated doctor calls judge
# the WINDOW between them instead of diluting a recent regression in
# the lifetime totals (first call judges the totals)
_prefetch_last = {"issued": 0, "wasted": 0}

# Serve autoscaler flap window (r14): direction reversals inside this
# many seconds are counted against serve_flap_warn_reversals.
SERVE_FLAP_WINDOW_S = 60.0


def orphan_arena_files(shm_dir: str = "/dev/shm") -> list:
    """Arena hygiene (r19, ROADMAP 5c): ``rtpu_*`` files in /dev/shm
    that no live process has mapped — the residue of hard-killed agents
    and crashed sessions; each one pins its full arena size in shared
    memory until someone unlinks it. Detection is by scanning
    ``/proc/*/maps`` for the file path (a mapped arena always shows
    there); a file nobody maps is garbage by definition, whatever
    session named it. Returns ``[(path, size_bytes)]``."""
    import os

    try:
        names = [f for f in os.listdir(shm_dir) if f.startswith("rtpu_")]
    except OSError:
        return []
    if not names:
        return []
    candidates = {f"{shm_dir}/{n}" for n in names}
    try:
        pids = [p for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        pids = []
    for pid in pids:
        if not candidates:
            break
        try:
            with open(f"/proc/{pid}/maps") as fh:
                txt = fh.read()
        except OSError:  # raced exit / permission — treat as not-mapping
            continue
        candidates = {p for p in candidates if p not in txt}
    out = []
    for path in sorted(candidates):
        try:
            out.append((path, os.path.getsize(path)))
        except OSError:  # unlinked while we scanned
            pass
    return out


def sweep_orphan_arenas(shm_dir: str = "/dev/shm") -> list:
    """Unlink every orphaned arena: a file no live process maps is
    garbage by definition (the residue of a SIGKILL'd head/agent that
    never ran its exit unlink), and each one pins its full size in
    shared memory until someone reclaims it. A booting head calls this
    — the natural janitor, since a hard-killed predecessor on the same
    host is exactly what it replaces. Returns the swept
    ``[(path, size_bytes)]``."""
    import os

    swept = []
    for path, size in orphan_arena_files(shm_dir):
        try:
            os.unlink(path)
            swept.append((path, size))
        except OSError:  # raced another janitor
            pass
    return swept


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}TiB"


def _arena_growth_warnings(history: dict, cfg) -> list:
    """Leak detection off the flight recorder (memory observatory),
    factored pure so tests feed crafted history dicts: a node's
    ``object_plane.arena_used_bytes`` series that never dipped across
    the trailing ``arena_growth_warn_window_s`` AND grew by more than
    ``arena_growth_warn_min_frac`` of capacity is the signature of a
    reference leak — steady-state churn frees something eventually, so
    its fill curve dips on every free."""
    warns = []
    series = (history or {}).get("series", {})
    window = cfg.arena_growth_warn_window_s
    if window <= 0:
        return warns
    for key, s in sorted(series.items()):
        base = key.split("{", 1)[0]
        if base != "object_plane.arena_used_bytes":
            continue
        pts = list(s.get("points") or [])
        if pts:
            newest = pts[-1][0]
            pts = [p for p in pts if p[0] >= newest - window]
        if len(pts) < 4 or pts[-1][0] - pts[0][0] < 0.5 * window:
            continue  # not enough history to judge the window
        vals = [p[1] for p in pts]
        if any(b < a for a, b in zip(vals, vals[1:])):
            continue  # dipped at least once: churn, not a leak
        growth = vals[-1] - vals[0]
        cap_pts = (series.get(key.replace(
            "arena_used_bytes", "arena_capacity_bytes")) or {}) \
            .get("points") or []
        cap = cap_pts[-1][1] if cap_pts else 0.0
        if growth <= 0 or (cap > 0 and
                           growth < cfg.arena_growth_warn_min_frac * cap):
            continue
        where = key[key.find("{"):] if "{" in key else key
        warns.append(
            f"arena{where}: used bytes grew monotonically by "
            f"{_fmt_bytes(growth)} over the last "
            f"{pts[-1][0] - pts[0][0]:.0f}s without a single dip — "
            "likely an object-reference leak (refs held in a growing "
            "structure, or returns never freed); `ray_tpu memory "
            "--group-by job` shows whose bytes are accumulating")
    return warns


def _memory_warnings(summary: dict, cfg) -> list:
    """Point-in-time memory health off ``state.memory_summary()``,
    factored pure for deterministic tests: near-highwater arena
    pressure, resident objects whose owner worker is dead (orphan
    refs — nothing will ever free them), and borrow-ledger deferred
    deletes stuck past the TTL (a leaked zero-copy view holding arena
    slots)."""
    warns = []
    for idx, row in sorted((summary or {}).get("nodes", {}).items(),
                           key=lambda kv: str(kv[0])):
        arena = row.get("arena") or {}
        cap = arena.get("capacity", 0)
        used = arena.get("used_bytes", 0)
        if cap and used / cap > cfg.arena_pressure_warn_frac:
            warns.append(
                f"node {idx} arena at {used / cap:.0%} of capacity "
                f"({_fmt_bytes(used)} / {_fmt_bytes(cap)}, > "
                f"{cfg.arena_pressure_warn_frac:.0%}): the next "
                "allocation burst will evict or fail — free objects, "
                "raise object_store_bytes, or spill")
        dd = arena.get("deferred_deletes", 0)
        oldest = arena.get("deferred_delete_oldest_s", 0.0)
        ttl = cfg.borrow_deferred_delete_warn_s
        if dd and ttl > 0 and oldest > ttl:
            warns.append(
                f"node {idx}: {dd:.0f} deferred delete(s) stuck behind "
                f"live zero-copy borrow views for {oldest:.0f}s (> "
                f"{ttl:g}s): a leaked view (held array / dangling "
                "reference) is pinning freed arena slots — the memory "
                "is unreclaimable until the view dies")
    do = (summary or {}).get("dead_owner") or {}
    if do.get("bytes"):
        owners = ", ".join(o[:8] for o in do.get("owners", [])[:5])
        warns.append(
            f"{do['objects']} resident object(s) "
            f"({_fmt_bytes(do['bytes'])}) owned by dead worker(s) "
            f"[{owners}]: orphan refs — their owners exited without "
            "freeing them and nothing will; `ray_tpu memory --group-by "
            "owner` lists them, free them or restart the job")
    return warns


def _serve_warnings(apps_status: dict, cfg) -> list:
    """Serve-at-scale health checks (r14), factored pure so tests can
    feed crafted status dicts: flag an autoscaler that keeps reversing
    direction (flapping burns cold-starts and kills warm replicas —
    raise the hysteresis windows/cooldowns) and a deployment whose
    replica cold-start p95 blew the configured bound (weights are not
    riding the broadcast path, or scale-ups queue behind placement)."""
    warns = []
    for app, info in (apps_status or {}).items():
        for dn, dep in info.get("deployments", {}).items():
            auto = dep.get("autoscaler") or {}
            if auto.get("enabled"):
                rev = auto.get("reversals_60s", 0)
                if rev > cfg.serve_flap_warn_reversals:
                    warns.append(
                        f"serve {app}/{dn}: autoscaler flapping — {rev} "
                        f"direction reversals in the last "
                        f"{SERVE_FLAP_WINDOW_S:.0f}s "
                        f"(> {cfg.serve_flap_warn_reversals}); raise "
                        "upscale/downscale delay windows or cooldowns "
                        "(AutoscalingConfig) — every flap burns a replica "
                        "cold-start")
            # cold-start applies to manual fleets too: a fixed
            # num_replicas deployment missing the weights-by-ref path
            # is exactly the misconfiguration this flags
            cold = auto.get("cold_start") or {}
            p95 = cold.get("p95_s", 0.0)
            if cold.get("count", 0) >= 2 and \
                    p95 > cfg.serve_cold_start_p95_warn_s:
                warns.append(
                    f"serve {app}/{dn}: replica cold-start p95 "
                    f"{p95:.1f}s exceeds "
                    f"{cfg.serve_cold_start_p95_warn_s:g}s — large init "
                    "args may not be riding the weights-by-ref "
                    "broadcast path (serve_weights_by_ref_min_bytes), "
                    "or scale-ups are queueing behind placement")
    return warns


def doctor_warnings() -> list:
    """Health warnings that are not endpoint failures: nonzero
    ``task_events_dropped`` / ``cluster_events_dropped`` mean the
    bounded event buffers overflowed — the task timelines and event log
    are silently missing transitions, which blinds the phase breakdown
    and straggler detector; ``fold_queue_drops`` means whole TASK_EVENTS
    batches were shed before folding (same blindness, different
    buffer); a high ``loop_lag_ms_p99`` means the head IO loop itself
    is not keeping up — every control-plane RPC queues behind it.
    Returns human-readable warning strings (empty on a healthy
    cluster)."""
    from ray_tpu import state

    warns = []
    # arena hygiene (r19): flag leaked /dev/shm arenas FIRST — this
    # check needs no live cluster (orphans matter most when nothing is
    # running and the memory is still pinned)
    orphans = orphan_arena_files()
    if orphans:
        total_mb = sum(sz for _, sz in orphans) / (1024 * 1024)
        names = ", ".join(p for p, _ in orphans[:5])
        more = f" (+{len(orphans) - 5} more)" if len(orphans) > 5 else ""
        warns.append(
            f"{len(orphans)} orphaned arena file(s) in /dev/shm pinning "
            f"{total_mb:.0f} MB: {names}{more} — left by hard-killed "
            "agents/sessions; rm them to release the shared memory")
    try:
        rows = state.io_loop_stats()
    except Exception:  # noqa: BLE001 — no cluster up: nothing to warn on
        return warns
    for row in rows:
        td = row.get("task_events_dropped", 0)
        cd = row.get("cluster_events_dropped", 0)
        fd = row.get("fold_queue_drops", 0)
        lag = row.get("loop_lag_ms_p99", 0.0)
        if td:
            warns.append(
                f"task_events_dropped={td}: task timelines are missing "
                "transitions (phase breakdowns / straggler detection are "
                "unreliable) — raise task_event_buffer_size")
        if cd:
            warns.append(
                f"cluster_events_dropped={cd}: the cluster event log "
                "overflowed and lost records — raise "
                "cluster_event_buffer_size")
        if fd:
            warns.append(
                f"fold_queue_drops={fd}: the head shed whole TASK_EVENTS "
                "batches before folding (timelines are missing tasks) — "
                "raise task_event_fold_queue_max or investigate fold-"
                "thread starvation")
        if lag > LOOP_LAG_WARN_MS:
            warns.append(
                f"loop_lag_ms_p99={lag:.0f}: the head IO loop is behind "
                f"(> {LOOP_LAG_WARN_MS:.0f}ms p99) — every control-plane "
                "RPC queues behind it; look for slow handlers "
                "(slow_events / max_handler_s in io_loop state)")
        rc = row.get("client_reconnects", 0)
        distinct = row.get("reconnect_clients", 0)
        # a STORM is many reattaches PER CLIENT, not a big cluster
        # riding out one clean restart (which costs exactly one
        # reattach per client): require both an absolute floor and a
        # >3x reattach-to-client ratio
        if rc > max(RECONNECT_STORM_THRESHOLD, 3 * max(distinct, 1)):
            warns.append(
                f"client_reconnects={rc} across {distinct} clients: "
                "reconnect storm — head channels are reattaching "
                "repeatedly; the head is flapping or its socket path "
                "is unstable (one clean restart costs one reattach "
                "per client)")
        stuck = row.get("reattach_pending_workers", 0)
        oldest = row.get("reattach_oldest_s", 0.0)
        if stuck and oldest > REATTACH_STUCK_S:
            warns.append(
                f"reattach_pending_workers={stuck} (oldest "
                f"{oldest:.0f}s): a node is stuck re-registering — "
                "workers its agent reported alive never re-REGISTERed "
                "with the restarted head; they will be ghost-swept at "
                "worker_register_timeout_s, check the node's worker "
                "logs")
    try:
        op = state.object_plane_stats()
    except Exception:  # noqa: BLE001
        op = {}
    issued = op.get("prefetch_issued", 0)
    wasted = op.get("prefetch_wasted", 0)
    d_issued = issued - _prefetch_last["issued"]
    d_wasted = wasted - _prefetch_last["wasted"]
    if d_issued < 0 or d_wasted < 0:  # head restarted: counters reset
        d_issued, d_wasted = issued, wasted
    _prefetch_last["issued"], _prefetch_last["wasted"] = issued, wasted
    if d_issued >= PREFETCH_WASTE_MIN_ISSUED and \
            d_wasted > PREFETCH_WASTE_RATIO * d_issued:
        warns.append(
            f"prefetch_wasted={d_wasted} of {d_issued} issued in this "
            f"window (>{PREFETCH_WASTE_RATIO:.0%}): arg prefetch is "
            "mostly stale speculation — task retry/cancel churn is "
            "re-placing work away from its prefetches, or "
            "arg_prefetch_max_bytes/_max_inflight are misconfigured "
            "for the workload")
    # graceful-drain health (r16): a node still `draining` past
    # drain_deadline_s means the force-escalation (drain_forced ->
    # SHUTDOWN_NODE) itself wedged — the head's housekeeping thread is
    # stuck or dead, and the node will neither finish nor be removed
    try:
        from ray_tpu.core.config import get_config as _gc

        deadline_s = _gc().drain_deadline_s
        for n in state.list_nodes():
            age = n.get("drain_age_s", 0.0)
            if n.get("draining") and age > deadline_s + 5.0:
                warns.append(
                    f"node {n.get('node_idx')} stuck draining for "
                    f"{age:.0f}s (> drain_deadline_s="
                    f"{deadline_s:g}s + escalation slack): the "
                    "drain_forced escalation did not fire — head "
                    "housekeeping may be wedged; remove the node "
                    "manually or restart the head")
    except Exception:  # noqa: BLE001 — no cluster up
        pass
    # memory observatory (r20): arena pressure / dead-owner orphans /
    # deferred-delete pileup off the summary, monotone-growth leak
    # detection off the flight recorder
    try:
        from ray_tpu.core.config import get_config as _gc

        cfg = _gc()
        summary = state.memory_summary()
        if summary:
            warns.extend(_memory_warnings(summary, cfg))
        hist = state.metrics_history(
            ["object_plane.arena_used_bytes",
             "object_plane.arena_capacity_bytes"])
        warns.extend(_arena_growth_warnings(hist, cfg))
    except Exception:  # noqa: BLE001 — pre-r20 head / no cluster
        pass
    # serve autoscaler health (r14): reads the controller's status
    # introspection; no serve running (or no controller) warns nothing
    try:
        from ray_tpu import serve
        from ray_tpu.core.config import get_config

        apps = serve.status().get("applications", {})
        if apps:
            warns.extend(_serve_warnings(apps, get_config()))
    except Exception:  # noqa: BLE001 — controller gone mid-query
        pass
    return warns


def doctor(verbose: bool = False) -> list:
    """Dashboard endpoint smoke check (``python -m ray_tpu doctor``):
    boots a 2-node local cluster when no runtime is up, runs a task so
    the tables are non-trivial, then GETs every ``/api/*`` endpoint and
    reports per-endpoint status — anything but a 2xx (500s AND 404s
    from renamed/removed endpoints) is a failure, so endpoints can't
    silently rot. Returns ``[{endpoint, status, ok, error}]``."""
    import urllib.request

    booted = False
    results = []
    dash = None
    try:
        if not ray_tpu.is_initialized():
            booted = True  # set BEFORE init: a partial boot must tear down
            ray_tpu.init(num_cpus=2, num_tpus=0)
            from ray_tpu.core.api import _head

            _head.add_node(num_cpus=1, num_tpus=0)  # a real 2-node cluster
        dash = start_dashboard(port=0)
        # populate task/object/event tables before probing
        @ray_tpu.remote
        def _doctor_probe():
            return 1

        ray_tpu.get([_doctor_probe.remote() for _ in range(2)], timeout=60)
        for ep in DOCTOR_ENDPOINTS:
            row = {"endpoint": ep, "status": 0, "ok": False, "error": ""}
            try:
                with urllib.request.urlopen(dash.url + ep,
                                            timeout=60) as resp:
                    row["status"] = resp.status
                    body = resp.read()
                    row["ok"] = 200 <= resp.status < 300 and bool(body)
            except urllib.error.HTTPError as e:  # non-2xx with a body
                row["status"], row["error"] = e.code, str(e)
            except Exception as e:  # noqa: BLE001 — conn refused etc.
                row["error"] = repr(e)
            if verbose:
                mark = "ok " if row["ok"] else "FAIL"
                print(f"  [{mark}] {row['status'] or '---'} {ep}"
                      + (f"  {row['error']}" if row["error"] else ""))
            results.append(row)
        if verbose:
            # programmatic callers use doctor_warnings() directly; the
            # CLI (doctor verbose=True) surfaces them here
            for warn in doctor_warnings():
                print(f"  [warn] {warn}")
    finally:
        if dash is not None:
            dash.stop()
        if booted:
            ray_tpu.shutdown()
    return results
