"""Dashboard: HTTP JSON state API + a minimal live HTML overview.

Ref parity: the reference dashboard head (python/ray/dashboard/head.py:81)
serving the REST endpoints its UI and `ray list ...` tooling consume.
Re-design: one stdlib ThreadingHTTPServer in the driver/head process,
reading the same head tables the state API uses — no aiohttp, no separate
agent processes. Endpoints:

    /api/nodes /api/workers /api/actors /api/tasks /api/objects
    /api/placement_groups   -> state API rows (JSON)
    /api/cluster            -> resource totals/availability
    /api/jobs               -> submitted jobs (jobs.py)
    /api/metrics            -> merged metric rows (JSON)
    /metrics                -> Prometheus text exposition
    /                       -> auto-refreshing HTML overview
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import ray_tpu

_INDEX_HTML = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>body{font-family:monospace;margin:2em}table{border-collapse:collapse}
td,th{border:1px solid #999;padding:4px 8px;text-align:left}</style></head>
<body><h2>ray_tpu cluster</h2><div id=cluster></div>
<h3>nodes</h3><table id=nodes></table>
<h3>actors</h3><table id=actors></table>
<h3>recent tasks</h3><table id=tasks></table>
<script>
async function fill(id, url, cols) {
  const rows = await (await fetch(url)).json();
  const t = document.getElementById(id);
  t.innerHTML = '<tr>' + cols.map(c => '<th>'+c+'</th>').join('') + '</tr>' +
    rows.slice(0, 50).map(r => '<tr>' + cols.map(
      c => '<td>' + JSON.stringify(r[c] ?? '') + '</td>').join('') +
      '</tr>').join('');
}
async function refresh() {
  const c = await (await fetch('/api/cluster')).json();
  document.getElementById('cluster').textContent = JSON.stringify(c);
  await fill('nodes', '/api/nodes',
             ['node_idx','alive','resources_total','resources_available']);
  await fill('actors', '/api/actors',
             ['actor_id','class_name','name','state']);
  await fill('tasks', '/api/tasks', ['task_id','name','state','node_idx']);
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    server_version = "ray_tpu-dashboard"

    def log_message(self, *args):  # quiet
        pass

    def _send(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj, code: int = 200):
        self._send(code, json.dumps(obj, default=str).encode(),
                   "application/json")

    def do_GET(self):  # noqa: N802 - stdlib API
        from ray_tpu import metrics, state

        path = self.path.split("?")[0].rstrip("/") or "/"
        try:
            if path == "/":
                self._send(200, _INDEX_HTML.encode(), "text/html")
            elif path == "/api/cluster":
                self._json({
                    "nodes": len(ray_tpu.nodes()),
                    "resources_total": ray_tpu.cluster_resources(),
                    "resources_available": ray_tpu.available_resources(),
                })
            elif path == "/api/jobs":
                from ray_tpu.jobs import JOB_MANAGER_NAME

                try:
                    mgr = ray_tpu.get_actor(JOB_MANAGER_NAME)
                except ValueError:  # manager never created: no jobs yet
                    self._json([])
                else:
                    # a dead/stuck manager surfaces as 500, not as an
                    # empty-but-healthy list
                    self._json(ray_tpu.get(mgr.list.remote(), timeout=10))
            elif path == "/api/metrics":
                self._json(metrics.metrics_summary())
            elif path == "/metrics":
                self._send(200, metrics.export_prometheus().encode(),
                           "text/plain; version=0.0.4")
            elif path.startswith("/api/"):
                kind = path[len("/api/"):]
                fn = {
                    "nodes": state.list_nodes,
                    "workers": state.list_workers,
                    "actors": state.list_actors,
                    "tasks": state.list_tasks,
                    "objects": state.list_objects,
                    "placement_groups": state.list_placement_groups,
                }.get(kind)
                if fn is None:
                    self._json({"error": f"unknown endpoint {path}"}, 404)
                else:
                    self._json(fn(limit=1000))
            else:
                self._json({"error": "not found"}, 404)
        except Exception as e:  # noqa: BLE001 — surface as 500
            self._json({"error": repr(e)}, 500)


class Dashboard:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "Dashboard":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="dashboard")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> Dashboard:
    """Start the dashboard against the current runtime; returns the
    handle (``.url``, ``.stop()``). Port 0 picks a free port."""
    if not ray_tpu.is_initialized():
        raise RuntimeError("call ray_tpu.init() before start_dashboard()")
    return Dashboard(host, port).start()
