"""Training backends: per-framework rendezvous hooks.

Ref analog: train/backend.py + train/torch/config.py:70 — where the
reference rendezvouses `torch.distributed` over NCCL, the JAX backend wires
`jax.distributed.initialize` so every worker (host) joins one global JAX
runtime and a Mesh can span the pod slice; ICI collectives then come from
XLA, not from a process-group library.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from ray_tpu.train.worker_group import WorkerGroup


@dataclasses.dataclass
class BackendConfig:
    @property
    def backend_cls(self):
        return Backend


class Backend:
    def on_start(self, worker_group: WorkerGroup, backend_config):
        pass

    def on_training_start(self, worker_group: WorkerGroup, backend_config):
        pass

    def on_shutdown(self, worker_group: WorkerGroup, backend_config):
        pass


@dataclasses.dataclass
class JaxConfig(BackendConfig):
    """JAX multi-host rendezvous config.

    distributed=None (auto): initialize `jax.distributed` only when the
    group has >1 worker — single-worker groups (including every unit test
    and the single-chip bench) run plain single-process JAX, where the mesh
    covers the locally visible devices.
    """

    distributed: Optional[bool] = None
    coordinator_port: int = 0  # 0 -> pick a free port on worker 0

    @property
    def backend_cls(self):
        return _JaxBackend


def _init_jax_distributed(coordinator_address: str, num_processes: int,
                          process_id: int):
    import jax

    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return True


def _jax_shutdown():
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        pass


class _JaxBackend(Backend):
    def on_start(self, worker_group: WorkerGroup, backend_config: JaxConfig):
        n = worker_group.num_workers
        dist = backend_config.distributed
        if dist is None:
            dist = n > 1
        if not dist:
            return
        import ray_tpu

        w0 = worker_group.workers[0]
        addr = ray_tpu.get([w0.get_address.remote()])[0]
        port = backend_config.coordinator_port or ray_tpu.get(
            [w0.find_free_port.remote()])[0]
        coordinator = f"{addr}:{port}"
        self.coordinator_address = coordinator
        ray_tpu.get([
            w.execute.remote(_init_jax_distributed, coordinator, n, i)
            for i, w in enumerate(worker_group.workers)
        ])

    def on_shutdown(self, worker_group: WorkerGroup, backend_config):
        try:
            worker_group.execute(_jax_shutdown)
        except Exception:
            pass
