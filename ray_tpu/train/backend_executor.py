"""BackendExecutor: drives the worker gang through a training run.

Ref analog: train/_internal/backend_executor.py:47 (start :106,
start_training :345) — spawns the WorkerGroup, runs the backend's rendezvous
(JAX multi-host init instead of torch.distributed), installs per-rank
sessions, and streams back reported results round by round.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.session import TrainContext
from ray_tpu.train.worker_group import WorkerGroup


class TrainingWorkerError(RuntimeError):
    """A worker failed mid-training; carries the underlying cause."""


class BackendExecutor:
    def __init__(self, backend_config: Optional[BackendConfig],
                 num_workers: int,
                 resources_per_worker: Dict[str, float],
                 placement_strategy: str = "PACK"):
        self._backend_config = backend_config or JaxConfig()
        self._backend = self._backend_config.backend_cls()
        self._num_workers = num_workers
        self._resources = resources_per_worker
        self._strategy = placement_strategy
        self.worker_group: Optional[WorkerGroup] = None

    def start(self):
        self.worker_group = WorkerGroup(self._num_workers, self._resources,
                                        self._strategy)
        self._backend.on_start(self.worker_group, self._backend_config)

    def start_training(self, train_fn: Callable, config: Dict[str, Any],
                       checkpoint=None, dataset_shards=None,
                       experiment_name: str = "", trial_id: str = ""):
        assert self.worker_group is not None, "call start() first"
        self._done_ranks = set()
        n = self._num_workers
        refs = []
        for rank, w in enumerate(self.worker_group.workers):
            ctx = TrainContext(
                world_rank=rank, world_size=n, local_rank=0,
                local_world_size=1, node_rank=rank,
                experiment_name=experiment_name, trial_id=trial_id,
                coordinator_address=getattr(self._backend,
                                            "coordinator_address", ""))
            shard = None
            if dataset_shards is not None:
                shard = {name: shards[rank]
                         for name, shards in dataset_shards.items()}
            refs.append(w.init_session.remote(
                train_fn, config, ctx, checkpoint, shard))
        ray_tpu.get(refs)
        self._backend.on_training_start(self.worker_group,
                                        self._backend_config)
        ray_tpu.get([w.start_training.remote()
                     for w in self.worker_group.workers])

    def next_results(self) -> Optional[List[Any]]:
        """One round: the next result from every still-running worker
        (lock-step, like the reference's TrainingIterator). None once all
        workers are done. Workers that already returned their 'done'
        sentinel are not polled again (their queues are empty — polling
        would block forever on uneven loop lengths)."""
        assert self.worker_group is not None
        if not hasattr(self, "_done_ranks"):
            self._done_ranks = set()
        live = [(rank, w)
                for rank, w in enumerate(self.worker_group.workers)
                if rank not in self._done_ranks]
        if not live:
            return None
        try:
            results = ray_tpu.get([w.get_next.remote() for _, w in live])
        except Exception as e:  # worker raised or died
            raise TrainingWorkerError(str(e)) from e
        reports = []
        for (rank, _), (kind, payload) in zip(live, results):
            if kind == "done":
                self._done_ranks.add(rank)
            else:
                reports.append(payload)
        if not reports:
            return None if len(self._done_ranks) == self._num_workers \
                else self.next_results()
        return reports

    def shutdown(self):
        if self.worker_group is not None:
            self._backend.on_shutdown(self.worker_group,
                                      self._backend_config)
            self.worker_group.shutdown()
            self.worker_group = None
