"""Pipeline schedules as per-stage op orders.

A schedule here is nothing more than, for every stage ``k``, the ordered
list of ops ``("F", mb)`` / ``("B", mb)`` that stage executes. The
object-plane pipeline (``train/pipeline.py``) turns each op into one
actor-method task; two mechanisms then enforce the schedule with no
central coordinator on the hot path:

- **intra-stage order** — actor tasks execute in per-actor submission
  (seqno) order, so submitting a stage's ops in schedule order IS the
  stage's local schedule;
- **inter-stage deps** — each op's input rides in as a by-ref
  ``ObjectRef`` produced by the neighbouring stage's op, so an op cannot
  start before its producer finished (and, with dispatch-time prefetch
  hints, its activation is usually already in flight to the stage's node
  when it does).

Ref analog: the paper "Scaling Deep Learning Training with MPMD Pipeline
Parallelism" hand-schedules per-stage programs with explicit cross-slice
sends; here the same orders are plain task graphs. The SPMD cousin
(`parallel/pipeline.py`) pipelines inside ONE XLA program over the
``pipeline`` mesh axis; this module is the multi-program (per-node
actors, object-plane handoff) face.
"""

from __future__ import annotations

from typing import List, Tuple

Op = Tuple[str, int]  # ("F" | "B", microbatch index)


def gpipe_order(num_stages: int, num_microbatches: int) -> List[List[Op]]:
    """GPipe: every stage runs all forwards, then all backwards (reverse
    microbatch order). Peak live activations per stage = M (all saved
    contexts wait for the backward wave) — the all-fwd-then-all-bwd
    memory shape the 1F1B schedule exists to fix."""
    _check(num_stages, num_microbatches)
    orders: List[List[Op]] = []
    for _ in range(num_stages):
        order: List[Op] = [("F", mb) for mb in range(num_microbatches)]
        order += [("B", mb) for mb in reversed(range(num_microbatches))]
        orders.append(order)
    return orders


def one_f_one_b_order(num_stages: int,
                      num_microbatches: int) -> List[List[Op]]:
    """1F1B (PipeDream-flush / GPipe-1F1B): stage ``k`` warms up with
    ``min(M, S-1-k)`` forwards, then alternates one-forward-one-backward,
    then drains the remaining backwards. At any point stage ``k`` holds
    at most ``S - k`` live microbatch contexts, so the steady-state
    footprint is O(stages), independent of M."""
    _check(num_stages, num_microbatches)
    orders: List[List[Op]] = []
    for k in range(num_stages):
        warm = min(num_microbatches, num_stages - 1 - k)
        order: List[Op] = [("F", mb) for mb in range(warm)]
        nf, nb = warm, 0
        while nb < num_microbatches:
            if nf < num_microbatches:
                order.append(("F", nf))
                nf += 1
            order.append(("B", nb))
            nb += 1
        orders.append(order)
    return orders


SCHEDULES = {
    "gpipe": gpipe_order,
    "1f1b": one_f_one_b_order,
}


def replica_orders(schedule_fn, num_stages: int,
                   mb_ids_by_replica: List[List[int]]
                   ) -> List[List[List[Op]]]:
    """Generalize a per-stage schedule to per-(stage, replica) op
    orders (r18 PP x DP): replica ``rep`` of every stage runs the base
    schedule over ITS microbatch subset ``mb_ids_by_replica[rep]``
    (microbatch mb is assigned to replica mb mod R, so activations flow
    stage k replica rep -> stage k+1 replica rep — R independent
    1-wide pipelines sharing the stage programs). Returns
    ``orders[stage][replica]`` as ops over the GLOBAL microbatch ids;
    a replica with no microbatches this wave gets an empty order."""
    out: List[List[List[Op]]] = []
    for k in range(num_stages):
        row: List[List[Op]] = []
        for ids in mb_ids_by_replica:
            if not ids:
                row.append([])
                continue
            base = schedule_fn(num_stages, len(ids))[k]
            row.append([(op, ids[i]) for op, i in base])
        out.append(row)
    return out


def validate_replica_orders(orders: List[List[List[Op]]]) -> None:
    """Validate each replica's S-stage slice independently with the
    plain simulator: deps never cross replicas (microbatch ids are
    opaque to ``validate_order`` and each global id appears in exactly
    one replica's lanes), so per-replica validity IS gang validity."""
    if not orders:
        return
    for rep in range(len(orders[0])):
        slice_ = [orders[k][rep] for k in range(len(orders))]
        if any(slice_):
            validate_order(slice_)


def _check(num_stages: int, num_microbatches: int):
    if num_stages < 1:
        raise ValueError(f"num_stages must be >= 1, got {num_stages}")
    if num_microbatches < 1:
        raise ValueError(
            f"num_microbatches must be >= 1, got {num_microbatches}")


def max_live_contexts(order: List[Op]) -> int:
    """Peak number of microbatches a stage holds a saved forward context
    for at once, scanning the stage's op order (F opens, B closes)."""
    live = peak = 0
    for op, _ in order:
        live += 1 if op == "F" else -1
        peak = max(peak, live)
    return peak


def validate_order(orders: List[List[Op]]) -> None:
    """Simulate a dependency-respecting execution of per-stage op orders
    and raise if it cannot complete (a deadlocked / malformed schedule).
    Dep model: F(k, mb) needs F(k-1, mb); B(k, mb) needs B(k+1, mb) and
    this stage's own F(k, mb); each stage executes its list in order."""
    S = len(orders)
    idx = [0] * S
    done = set()
    total = sum(len(o) for o in orders)
    completed = 0
    while completed < total:
        progressed = False
        for k in range(S):
            while idx[k] < len(orders[k]):
                op, mb = orders[k][idx[k]]
                if op == "F":
                    ready = k == 0 or ("F", k - 1, mb) in done
                else:
                    ready = (("F", k, mb) in done
                             and (k == S - 1 or ("B", k + 1, mb) in done))
                if not ready:
                    break
                done.add((op, k, mb))
                idx[k] += 1
                completed += 1
                progressed = True
        if not progressed:
            stuck = [(k, orders[k][idx[k]]) for k in range(S)
                     if idx[k] < len(orders[k])]
            raise ValueError(f"schedule deadlocks; stuck at {stuck}")
