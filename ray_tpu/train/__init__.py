"""ray_tpu.train — distributed training over worker-actor gangs.

Ref analog: python/ray/train + python/ray/air config/session layers
(SURVEY.md §2.4). TPU-native: the tensor plane is jax.distributed + XLA ICI
collectives (backend.py), not a NCCL process group.
"""

from ray_tpu.train.backend import Backend, BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import (
    BackendExecutor,
    TrainingWorkerError,
)
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.session import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    get_local_rank,
    get_world_rank,
    get_world_size,
    report,
)
from ray_tpu.train.trainer import (
    BaseTrainer,
    DataParallelTrainer,
    JaxTrainer,
)
from ray_tpu.train.worker_group import RayTrainWorker, WorkerGroup
from ray_tpu.train.pipeline import (
    Pipeline,
    PipelineStage,
    SingleProgramPipeline,
    single_program_reference,
)
from ray_tpu.train.pipeline_schedules import (
    gpipe_order,
    one_f_one_b_order,
)

__all__ = [
    "ScalingConfig", "RunConfig", "CheckpointConfig", "FailureConfig",
    "Result", "Checkpoint", "CheckpointManager",
    "Backend", "BackendConfig", "JaxConfig",
    "BackendExecutor", "TrainingWorkerError",
    "BaseTrainer", "DataParallelTrainer", "JaxTrainer",
    "WorkerGroup", "RayTrainWorker",
    "Pipeline", "PipelineStage", "SingleProgramPipeline",
    "single_program_reference", "gpipe_order", "one_f_one_b_order",
    "report", "get_checkpoint", "get_context", "get_dataset_shard",
    "get_world_rank", "get_world_size", "get_local_rank", "TrainContext",
]

from ray_tpu.usage_stats import record_library_usage as _rlu
_rlu("train")
del _rlu
