"""Checkpoints: dict/dir duality + top-K retention.

Ref analogs: air/checkpoint.py (dict<->directory Checkpoint) and
train/_internal/checkpoint_manager.py (top-K by score). JAX pytrees are
stored as a flat .npz of leaves plus a pickled treedef, so checkpoints of
sharded arrays round-trip through host memory without torch/pickle bloat.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_METADATA = "ckpt_meta.json"
_PAYLOAD = "payload.pkl"
_PYTREE_NPZ = "pytree_leaves.npz"
_PYTREE_DEF = "pytree_def.pkl"


class Checkpoint:
    """Immutable handle on a checkpoint, backed by a dict or a directory."""

    def __init__(self, *, _dict: Optional[Dict[str, Any]] = None,
                 _path: Optional[str] = None):
        self._dict = _dict
        self._path = _path

    # -- constructors --

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        return cls(_dict=dict(data))

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(_path=str(path))

    @classmethod
    def from_pytree(cls, tree: Any, **extra) -> "Checkpoint":
        """Store a JAX pytree (params/opt state) efficiently."""
        import jax

        leaves, treedef = jax.tree.flatten(jax.device_get(tree))
        return cls(_dict={"__pytree_leaves__": leaves,
                          "__pytree_def__": treedef, **extra})

    # -- accessors --

    def to_dict(self) -> Dict[str, Any]:
        if self._dict is not None:
            return dict(self._dict)
        data = {}
        payload = os.path.join(self._path, _PAYLOAD)
        if os.path.exists(payload):
            with open(payload, "rb") as f:
                data.update(pickle.load(f))
        npz = os.path.join(self._path, _PYTREE_NPZ)
        if os.path.exists(npz):
            with np.load(npz, allow_pickle=False) as z:
                leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
            with open(os.path.join(self._path, _PYTREE_DEF), "rb") as f:
                data["__pytree_def__"] = pickle.load(f)
            data["__pytree_leaves__"] = leaves
        return data

    def to_pytree(self) -> Tuple[Any, Dict[str, Any]]:
        data = self.to_dict()
        leaves = data.pop("__pytree_leaves__")
        treedef = data.pop("__pytree_def__")
        import jax

        return jax.tree.unflatten(treedef, leaves), data

    def to_directory(self, path: Optional[str] = None) -> str:
        if path is None:
            path = tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        if self._path is not None:
            if os.path.abspath(self._path) != os.path.abspath(path):
                shutil.copytree(self._path, path, dirs_exist_ok=True)
            return path
        data = dict(self._dict)
        leaves = data.pop("__pytree_leaves__", None)
        treedef = data.pop("__pytree_def__", None)
        if leaves is not None:
            np.savez(os.path.join(path, _PYTREE_NPZ),
                     **{f"leaf_{i}": np.asarray(x)
                        for i, x in enumerate(leaves)})
            with open(os.path.join(path, _PYTREE_DEF), "wb") as f:
                pickle.dump(treedef, f)
        with open(os.path.join(path, _PAYLOAD), "wb") as f:
            pickle.dump(data, f)
        with open(os.path.join(path, _METADATA), "w") as f:
            json.dump({"created_at": time.time()}, f)
        return path

    @property
    def path(self) -> Optional[str]:
        return self._path

    def __repr__(self):
        src = self._path if self._path else f"dict[{len(self._dict or {})}]"
        return f"Checkpoint({src})"


class _TrackedCheckpoint:
    def __init__(self, checkpoint: Checkpoint, metrics: Dict[str, Any],
                 index: int, path: Optional[str]):
        self.checkpoint = checkpoint
        self.metrics = metrics
        self.index = index
        self.path = path


class CheckpointManager:
    """Persists reported checkpoints under `root`, keeps top-K by score."""

    def __init__(self, root: str, num_to_keep: Optional[int] = None,
                 score_attribute: Optional[str] = None,
                 score_order: str = "max"):
        self.root = root
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        self._tracked: List[_TrackedCheckpoint] = []
        self._counter = 0
        os.makedirs(root, exist_ok=True)

    def register(self, checkpoint: Checkpoint,
                 metrics: Dict[str, Any]) -> _TrackedCheckpoint:
        idx = self._counter
        self._counter += 1
        path = os.path.join(self.root, f"checkpoint_{idx:06d}")
        checkpoint.to_directory(path)
        tracked = _TrackedCheckpoint(Checkpoint.from_directory(path), metrics,
                                     idx, path)
        self._tracked.append(tracked)
        self._evict()
        return tracked

    def _score(self, t: _TrackedCheckpoint) -> float:
        if not self.score_attribute:
            return float(t.index)  # keep most recent
        v = float(t.metrics.get(self.score_attribute, float("-inf")))
        return v if self.score_order == "max" else -v

    def _evict(self):
        if self.num_to_keep is None or len(self._tracked) <= self.num_to_keep:
            return
        self._tracked.sort(key=self._score, reverse=True)
        for victim in self._tracked[self.num_to_keep:]:
            if victim.path and os.path.exists(victim.path):
                shutil.rmtree(victim.path, ignore_errors=True)
        self._tracked = self._tracked[:self.num_to_keep]

    @property
    def best(self) -> Optional[_TrackedCheckpoint]:
        if not self._tracked:
            return None
        return max(self._tracked, key=self._score)

    @property
    def latest(self) -> Optional[_TrackedCheckpoint]:
        if not self._tracked:
            return None
        return max(self._tracked, key=lambda t: t.index)

    @property
    def checkpoints(self) -> List[_TrackedCheckpoint]:
        return sorted(self._tracked, key=lambda t: t.index)
